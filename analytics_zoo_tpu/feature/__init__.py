from .feature_set import (ArrayFeatureSet, FeatureSet, GeneratorFeatureSet,
                          MiniBatch, PrefetchIterator, Sample)

__all__ = ["ArrayFeatureSet", "FeatureSet", "GeneratorFeatureSet",
           "MiniBatch", "PrefetchIterator", "Sample"]
