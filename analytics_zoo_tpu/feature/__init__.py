from .common import (ArrayToTensor, BigDLAdapter, ChainedPreprocessing,
                     FeatureLabelPreprocessing, FeatureToTupleAdapter,
                     LambdaPreprocessing, MLlibVectorToTensor, Preprocessing,
                     Relation, RelationPair, Relations, SampleToMiniBatch,
                     ScalarToTensor, SeqToMultipleTensors, SeqToTensor,
                     TensorToSample, ToTuple)
from .dataset import (DatasetShard, ShardedDatasetFeatureSet, assign_shards,
                      discover_shards, write_parquet_shards)
from .feature_set import (ArrayFeatureSet, FeatureSet, GeneratorFeatureSet,
                          MiniBatch, PrefetchIterator, Sample,
                          ShardedFileFeatureSet, TransformStats,
                          TransformedFeatureSet, pad_minibatch,
                          register_pipeline, shutdown_all_pipelines)
from .host_pipeline import (DeviceStagingIterator, ParallelTransformIterator,
                            build_host_pipeline)

__all__ = ["ArrayFeatureSet", "FeatureSet", "GeneratorFeatureSet",
           "MiniBatch", "PrefetchIterator", "Sample", "pad_minibatch",
           "ShardedFileFeatureSet", "TransformedFeatureSet",
           "TransformStats", "ParallelTransformIterator",
           "DeviceStagingIterator", "build_host_pipeline",
           "DatasetShard", "ShardedDatasetFeatureSet", "assign_shards",
           "discover_shards", "write_parquet_shards",
           "register_pipeline", "shutdown_all_pipelines",
           "Preprocessing", "ChainedPreprocessing", "LambdaPreprocessing",
           "ScalarToTensor", "SeqToTensor", "SeqToMultipleTensors",
           "ArrayToTensor", "MLlibVectorToTensor",
           "FeatureLabelPreprocessing", "TensorToSample", "ToTuple",
           "FeatureToTupleAdapter", "BigDLAdapter", "SampleToMiniBatch",
           "Relation", "RelationPair", "Relations"]
