"""FeatureSet: the train-time dataset abstraction.

Reference: ``zoo/.../feature/FeatureSet.scala`` — an RDD-backed dataset with
memory tiers (DRAM / PMEM / DIRECT / DISK_AND_DRAM) feeding per-executor
MiniBatch iterators.  TPU-native redesign: samples live in host RAM (numpy,
possibly memory-mapped), a background thread prefetches minibatches, and each
batch is laid onto the device mesh with ``jax.device_put`` under the batch
sharding — the host→HBM copy overlaps the previous step's compute, replacing
the reference's BlockManager fetch phase.
"""

from __future__ import annotations

import math
import queue
import threading
from typing import Any, Callable, Iterator, List, Optional, Sequence, Tuple

import numpy as np


class Sample:
    """One (features, labels) record; mirrors BigDL ``Sample`` marshalled via
    JTensor (pyzoo/zoo/common/utils.py:75)."""

    def __init__(self, features, labels=None):
        self.features = _as_list(features)
        self.labels = _as_list(labels) if labels is not None else None

    @staticmethod
    def from_ndarray(features, labels=None):
        return Sample(features, labels)


def _as_list(x):
    if isinstance(x, (list, tuple)):
        return [np.asarray(v) for v in x]
    return [np.asarray(x)]


class MiniBatch(tuple):
    """(inputs: tuple, targets, sample_weight) — pytree-friendly."""
    __slots__ = ()

    def __new__(cls, inputs, targets=None, weights=None):
        return super().__new__(cls, (tuple(inputs), targets, weights))

    @property
    def inputs(self):
        return self[0]

    @property
    def targets(self):
        return self[1]

    @property
    def weights(self):
        return self[2]


class FeatureSet:
    """Base: iterable of minibatches over host-resident data."""

    def size(self) -> int:
        raise NotImplementedError

    def num_batches(self, batch_size: int, drop_remainder: bool) -> int:
        n = self.size()
        return n // batch_size if drop_remainder else math.ceil(n / batch_size)

    def batches(self, batch_size: int, shuffle: bool = False,
                drop_remainder: bool = True, pad_remainder: bool = False,
                seed: int = 0) -> Iterator[MiniBatch]:
        raise NotImplementedError

    def transform(self, preprocessing) -> "FeatureSet":
        return TransformedFeatureSet(self, preprocessing)

    def __len__(self):
        return self.size()

    # -- factories (parity with FeatureSet.rdd / ImageSet / python
    #    zoo.feature.common.FeatureSet) --------------------------------
    @staticmethod
    def array(features, labels=None, weights=None) -> "ArrayFeatureSet":
        return ArrayFeatureSet(features, labels, weights)

    @staticmethod
    def sample_rdd(samples: Sequence[Sample], **kw) -> "ArrayFeatureSet":
        return FeatureSet.samples(samples)

    @staticmethod
    def samples(samples: Sequence[Sample]) -> "ArrayFeatureSet":
        feats, labels = stack_samples(samples)
        return ArrayFeatureSet(
            list(feats) if len(feats) > 1 else feats[0], labels)

    @staticmethod
    def generator(fn: Callable[[], Iterator], size: int,
                  batch_size_hint: Optional[int] = None):
        return GeneratorFeatureSet(fn, size)


class ArrayFeatureSet(FeatureSet):
    """In-memory (host-RAM tier) dataset of numpy arrays."""

    def __init__(self, features, labels=None, weights=None):
        self.features: List[np.ndarray] = [np.asarray(f) for f in (
            features if isinstance(features, (list, tuple)) else [features])]
        n = self.features[0].shape[0]
        for f in self.features:
            assert f.shape[0] == n, "feature arrays disagree on batch dim"
        self.labels = None
        if labels is not None:
            self.labels = [np.asarray(l) for l in (
                labels if isinstance(labels, (list, tuple)) else [labels])]
            for l in self.labels:
                assert l.shape[0] == n
        self.weights = np.asarray(weights) if weights is not None else None
        self._n = n

    def size(self):
        return self._n

    def batches(self, batch_size, shuffle=False, drop_remainder=True,
                pad_remainder=False, seed=0):
        n = self._n
        idx = np.arange(n)
        if shuffle:
            np.random.default_rng(seed).shuffle(idx)
        end = (n // batch_size) * batch_size if drop_remainder else n
        for start in range(0, end, batch_size):
            take = idx[start:start + batch_size]
            pad = 0
            if take.shape[0] < batch_size and pad_remainder:
                pad = batch_size - take.shape[0]
                take = np.concatenate([take, np.repeat(take[-1:], pad)])
            xs = tuple(f[take] for f in self.features)
            ys = None
            if self.labels is not None:
                ys = [l[take] for l in self.labels]
                ys = ys[0] if len(ys) == 1 else tuple(ys)
            w = np.ones(take.shape[0], np.float32)
            if self.weights is not None:
                w = self.weights[take].astype(np.float32)
            if pad:
                w[-pad:] = 0.0
            yield MiniBatch(xs, ys, w)


class GeneratorFeatureSet(FeatureSet):
    def __init__(self, fn, size):
        self.fn = fn
        self._size = size

    def size(self):
        return self._size

    def batches(self, batch_size, shuffle=False, drop_remainder=True,
                pad_remainder=False, seed=0):
        buf_x, buf_y = [], []
        for item in self.fn():
            x, y = item if isinstance(item, tuple) and len(item) == 2 \
                else (item, None)
            buf_x.append(x)
            buf_y.append(y)
            if len(buf_x) == batch_size:
                yield _stack_batch(buf_x, buf_y, batch_size)
                buf_x, buf_y = [], []
        if buf_x and not drop_remainder:
            yield _stack_batch(buf_x, buf_y, batch_size if pad_remainder
                               else len(buf_x), pad=pad_remainder)


def stack_samples(samples: Sequence[Sample]):
    """Stack Samples into (features_tuple, labels); the single shared
    batching helper (used by FeatureSet.samples and SampleToMiniBatch)."""
    samples = list(samples)
    if not samples:
        raise ValueError("empty sample collection")
    n_feat = len(samples[0].features)
    feats = tuple(np.stack([s.features[i] for s in samples])
                  for i in range(n_feat))
    labels = None
    if samples[0].labels is not None:
        labs = [np.stack([s.labels[i] for s in samples])
                for i in range(len(samples[0].labels))]
        labels = labs[0] if len(labs) == 1 else labs
    return feats, labels


def minibatch_len(batch: MiniBatch) -> int:
    return len(batch.weights) if batch.weights is not None else \
        len(batch.inputs[0])


def pad_minibatch(batch: MiniBatch, target: int) -> MiniBatch:
    """Pad a MiniBatch to ``target`` samples by repeating the last sample
    with zero weight. Loss/metrics are weight-aware so the padding does not
    bias them; note BatchNorm running stats are NOT weight-aware — training
    batch sizes should be a multiple of the data-parallel size to avoid
    padded samples entering normalization statistics."""
    n = minibatch_len(batch)
    if target <= n:
        return batch
    reps = target - n

    def pad(a):
        a = np.asarray(a)
        return np.concatenate([a, np.repeat(a[-1:], reps, 0)])

    xs = tuple(pad(x) for x in batch.inputs)
    ys = batch.targets
    if ys is not None:
        ys = [pad(y) for y in ys] if isinstance(ys, (list, tuple)) \
            else pad(ys)
    w = batch.weights if batch.weights is not None else \
        np.ones(n, np.float32)
    w = np.concatenate([np.asarray(w), np.zeros(reps, np.float32)])
    return MiniBatch(xs, ys, w)


def _stack_batch(buf_x, buf_y, batch_size, pad=False):
    n = len(buf_x)
    multi = isinstance(buf_x[0], (list, tuple))
    if multi:
        xs = tuple(np.stack([b[i] for b in buf_x])
                   for i in range(len(buf_x[0])))
    else:
        xs = (np.stack(buf_x),)
    ys = None
    if buf_y[0] is not None:
        ys = np.stack(buf_y)
    batch = MiniBatch(xs, ys, np.ones(n, np.float32))
    if pad and n < batch_size:
        batch = pad_minibatch(batch, batch_size)
    return batch


class TransformedFeatureSet(FeatureSet):
    """Applies a Preprocessing chain per batch on the host, off the hot path
    when wrapped by the prefetcher."""

    def __init__(self, base: FeatureSet, preprocessing):
        self.base = base
        self.preprocessing = preprocessing

    def size(self):
        return self.base.size()

    def batches(self, *args, **kw):
        for batch in self.base.batches(*args, **kw):
            yield self.preprocessing(batch)


class PrefetchIterator:
    """Background-thread prefetch of host minibatches (double buffering the
    host side; ``jax.device_put`` overlap covers the device side). Replaces
    the reference's PMEM/DRAM cache tiers + MTSampleToMiniBatch."""

    def __init__(self, it: Iterator, depth: int = 2):
        self.it = it
        self.q: "queue.Queue" = queue.Queue(maxsize=depth)
        self.done = object()
        self.error = None
        self._stopped = False
        self.thread = threading.Thread(target=self._worker, daemon=True)
        self.thread.start()

    def _worker(self):
        try:
            for item in self.it:
                while not self._stopped:
                    try:
                        self.q.put(item, timeout=0.2)
                        break
                    except queue.Full:
                        continue
                if self._stopped:
                    return
        except BaseException as e:  # propagate to consumer
            self.error = e
        finally:
            while not self._stopped:
                try:
                    self.q.put(self.done, timeout=0.2)
                    break
                except queue.Full:
                    continue

    def close(self):
        """Unblock and discard the producer (call when abandoning the
        iterator mid-stream, e.g. early end-trigger or step failure)."""
        self._stopped = True
        try:
            while True:
                self.q.get_nowait()
        except queue.Empty:
            pass

    def __iter__(self):
        return self

    def __next__(self):
        if self._stopped:
            raise StopIteration
        item = self.q.get()
        if item is self.done:
            if self.error is not None:
                raise self.error
            raise StopIteration
        return item
