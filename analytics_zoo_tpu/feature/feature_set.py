"""FeatureSet: the train-time dataset abstraction.

Reference: ``zoo/.../feature/FeatureSet.scala`` — an RDD-backed dataset with
memory tiers (DRAM / PMEM / DIRECT / DISK_AND_DRAM) feeding per-executor
MiniBatch iterators.  TPU-native redesign: samples live in host RAM (numpy,
possibly memory-mapped), a background thread prefetches minibatches, and each
batch is laid onto the device mesh with ``jax.device_put`` under the batch
sharding — the host→HBM copy overlaps the previous step's compute, replacing
the reference's BlockManager fetch phase.
"""

from __future__ import annotations

import hashlib
import json
import logging
import math
import mmap as mmap_mod
import os
import queue
import tempfile
import threading
import time
import weakref
from collections import OrderedDict
from typing import (Any, Callable, Dict, Iterator, List, Optional, Sequence,
                    Tuple)

import numpy as np

logger = logging.getLogger("analytics_zoo_tpu.feature")


class Sample:
    """One (features, labels) record; mirrors BigDL ``Sample`` marshalled via
    JTensor (pyzoo/zoo/common/utils.py:75)."""

    def __init__(self, features, labels=None):
        self.features = _as_list(features)
        self.labels = _as_list(labels) if labels is not None else None

    @staticmethod
    def from_ndarray(features, labels=None):
        return Sample(features, labels)


def _as_list(x):
    if isinstance(x, (list, tuple)):
        return [np.asarray(v) for v in x]
    return [np.asarray(x)]


class MiniBatch(tuple):
    """(inputs: tuple, targets, sample_weight) — pytree-friendly."""
    __slots__ = ()

    def __new__(cls, inputs, targets=None, weights=None):
        return super().__new__(cls, (tuple(inputs), targets, weights))

    def __getnewargs__(self):
        # without this, pickle rebuilds via MiniBatch.__new__(cls, self)
        # which re-nests the whole triple under ``inputs`` — silently
        return (self[0], self[1], self[2])

    @property
    def inputs(self):
        return self[0]

    @property
    def targets(self):
        return self[1]

    @property
    def weights(self):
        return self[2]


class FeatureSet:
    """Base: iterable of minibatches over host-resident data."""

    def size(self) -> int:
        raise NotImplementedError

    def num_batches(self, batch_size: int, drop_remainder: bool) -> int:
        n = self.size()
        return n // batch_size if drop_remainder else math.ceil(n / batch_size)

    def batches(self, batch_size: int, shuffle: bool = False,
                drop_remainder: bool = True, pad_remainder: bool = False,
                seed: int = 0) -> Iterator[MiniBatch]:
        raise NotImplementedError

    def transform(self, preprocessing) -> "FeatureSet":
        return TransformedFeatureSet(self, preprocessing)

    def __len__(self):
        return self.size()

    # -- factories (parity with FeatureSet.rdd / ImageSet / python
    #    zoo.feature.common.FeatureSet) --------------------------------
    @staticmethod
    def array(features, labels=None, weights=None) -> "ArrayFeatureSet":
        return ArrayFeatureSet(features, labels, weights)

    @staticmethod
    def sample_rdd(samples: Sequence[Sample], **kw) -> "ArrayFeatureSet":
        return FeatureSet.samples(samples)

    @staticmethod
    def samples(samples: Sequence[Sample]) -> "ArrayFeatureSet":
        feats, labels = stack_samples(samples)
        return ArrayFeatureSet(
            list(feats) if len(feats) > 1 else feats[0], labels)

    @staticmethod
    def generator(fn: Callable[[], Iterator], size: int,
                  batch_size_hint: Optional[int] = None):
        return GeneratorFeatureSet(fn, size)

    @staticmethod
    def rdd(data, memory_type: str = "DRAM", **kw) -> "FeatureSet":
        """Memory-tier factory (parity: ``FeatureSet.rdd``
        ``feature/FeatureSet.scala:423-455`` with DRAM | PMEM | DIRECT |
        DISK_AND_DRAM(n)).

        ``data``: a FeatureSet, a sequence of Samples, or for
        DISK_AND_DRAM a list of ``.npz`` shard paths. PMEM and DIRECT
        both map to the native host arena (``native/zoo_data.cpp``) —
        off-GC staging RAM replaces Optane.
        """
        mt = str(memory_type).upper()
        if mt.startswith("DISK_AND_DRAM"):
            num_slice = 1
            if "(" in mt:
                num_slice = int(mt.split("(")[1].rstrip(")"))
            return DiskFeatureSet(list(data), num_slice=num_slice)
        if isinstance(data, FeatureSet):
            fs = data
        else:
            fs = FeatureSet.samples(list(data))
        if mt in ("PMEM", "DIRECT"):
            if isinstance(fs, TransformedFeatureSet):
                # DIRECT tier for transformed pipelines = disk-backed
                # mmap'd arena beneath the DRAM prefix: batches past
                # cache_bytes spill to one file every process on the
                # host shares (docs/data-pipeline.md)
                fs.cache(
                    int(kw.get("cache_bytes", DEFAULT_DRAM_CACHE_BYTES)),
                    arena_path=kw.get("arena_path") or default_arena_path(),
                    arena_bytes=kw.get("arena_bytes"))
                return fs
            if isinstance(fs, ArrayFeatureSet):
                try:
                    return DirectFeatureSet(fs.features, fs.labels,
                                            fs.weights)
                except (ImportError, MemoryError):
                    # native arena unavailable/full: stage the arrays
                    # through disk-backed mmaps instead of silently
                    # staying in the GC'd DRAM heap
                    return MmapFeatureSet(fs.features, fs.labels,
                                          fs.weights,
                                          dir=kw.get("arena_path"))
        if mt == "DRAM" and isinstance(fs, TransformedFeatureSet):
            # DRAM tier = memoize the transformed batches (reference keeps
            # the post-transform MiniBatches resident; raw tiers already
            # live in host RAM here, so only transforms benefit)
            fs.cache(int(kw.get("cache_bytes", DEFAULT_DRAM_CACHE_BYTES)))
        return fs

    @staticmethod
    def disk(paths: Sequence[str], num_slice: int = 1) -> "DiskFeatureSet":
        return DiskFeatureSet(list(paths), num_slice=num_slice)

    @staticmethod
    def from_dataset(uri: str, columns: Optional[Sequence[str]] = None,
                     label_col: Optional[str] = None, num_slice: int = 1,
                     process_index: Optional[int] = None,
                     num_processes: Optional[int] = None
                     ) -> "FeatureSet":
        """Distributed ingestion over a partitioned dataset directory
        (parquet/arrow/npz/csv shards; ``file``/``hdfs``/``gs``/``s3``
        URIs): each host streams a disjoint, deterministic, size-balanced
        shard subset (see :mod:`feature.dataset`)."""
        from .dataset import ShardedDatasetFeatureSet
        return ShardedDatasetFeatureSet(
            uri, columns=columns, label_col=label_col, num_slice=num_slice,
            process_index=process_index, num_processes=num_processes)

    @staticmethod
    def files(paths: Sequence[str], num_slice: int = 1,
              columns: Optional[Sequence[str]] = None,
              label_col: Optional[str] = None,
              shard_per_host: bool = True) -> "ShardedFileFeatureSet":
        """Sharded npz/csv/parquet files, striped one stripe per host."""
        return ShardedFileFeatureSet(
            list(paths), num_slice=num_slice, columns=columns,
            label_col=label_col, shard_per_host=shard_per_host)


class ArrayFeatureSet(FeatureSet):
    """In-memory (host-RAM tier) dataset of numpy arrays."""

    def __init__(self, features, labels=None, weights=None):
        self.features: List[np.ndarray] = [np.asarray(f) for f in (
            features if isinstance(features, (list, tuple)) else [features])]
        n = self.features[0].shape[0]
        for f in self.features:
            assert f.shape[0] == n, "feature arrays disagree on batch dim"
        self.labels = None
        if labels is not None:
            self.labels = [np.asarray(l) for l in (
                labels if isinstance(labels, (list, tuple)) else [labels])]
            for l in self.labels:
                assert l.shape[0] == n
        self.weights = np.asarray(weights) if weights is not None else None
        self._n = n

    def size(self):
        return self._n

    def batches(self, batch_size, shuffle=False, drop_remainder=True,
                pad_remainder=False, seed=0):
        n = self._n
        idx = np.arange(n)
        if shuffle:
            np.random.default_rng(seed).shuffle(idx)
        end = (n // batch_size) * batch_size if drop_remainder else n
        for start in range(0, end, batch_size):
            take = idx[start:start + batch_size]
            pad = 0
            if take.shape[0] < batch_size and pad_remainder:
                pad = batch_size - take.shape[0]
                take = np.concatenate([take, np.repeat(take[-1:], pad)])
            xs = tuple(f[take] for f in self.features)
            ys = None
            if self.labels is not None:
                ys = [l[take] for l in self.labels]
                ys = ys[0] if len(ys) == 1 else tuple(ys)
            w = np.ones(take.shape[0], np.float32)
            if self.weights is not None:
                w = self.weights[take].astype(np.float32)
            if pad:
                w[-pad:] = 0.0
            yield MiniBatch(xs, ys, w)


class DirectFeatureSet(ArrayFeatureSet):
    """Samples staged in the native host arena (off-GC, 64-byte aligned).

    The PMEM/DIRECT tier equivalent (``feature/pmem/NativeArray.scala`` +
    ``PersistentMemoryAllocator.java:19``): sample bytes live outside the
    Python heap in one contiguous slab, and batch slices are zero-copy
    numpy views handed straight to ``jax.device_put``.
    """

    def __init__(self, features, labels=None, weights=None):
        from ..utils.native_loader import load_zoo_data

        lib = load_zoo_data()  # raises ImportError when unavailable
        feats = [np.asarray(f) for f in (
            features if isinstance(features, (list, tuple)) else [features])]
        labs = None
        if labels is not None:
            labs = [np.asarray(l) for l in (
                labels if isinstance(labels, (list, tuple)) else [labels])]
        def aligned(a):  # arena rounds every allocation up to 64 bytes
            return (a.nbytes + 63) & ~63

        total = sum(aligned(a) for a in feats) + \
            sum(aligned(a) for a in (labs or []))
        self._arena = lib.arena(max(total + 64, 4096))
        staged_feats = [self._arena.store(a).numpy() for a in feats]
        staged_labs = [self._arena.store(a).numpy() for a in labs] \
            if labs is not None else None
        super().__init__(staged_feats, staged_labs, weights)

    memory_type = "DIRECT"


class MmapFeatureSet(ArrayFeatureSet):
    """DIRECT-tier fallback when the native arena can't load: arrays are
    staged to ``.npy`` files and reopened ``mmap_mode="r"``, so sample
    bytes live in the page cache (off the GC'd Python heap, shared
    across processes mapping the same staging dir) instead of silently
    staying DRAM-resident."""

    def __init__(self, features, labels=None, weights=None,
                 dir: Optional[str] = None):
        self.staging_dir = dir or tempfile.mkdtemp(prefix="zoo_mmap_")
        os.makedirs(self.staging_dir, exist_ok=True)

        def stage(tag, a):
            a = np.asarray(a)
            p = os.path.join(self.staging_dir, f"{tag}.npy")
            np.save(p, a)
            return np.load(p, mmap_mode="r")

        feats = [np.asarray(f) for f in (
            features if isinstance(features, (list, tuple)) else [features])]
        staged_feats = [stage(f"x{i}", a) for i, a in enumerate(feats)]
        staged_labs = None
        if labels is not None:
            labs = [np.asarray(l) for l in (
                labels if isinstance(labels, (list, tuple)) else [labels])]
            staged_labs = [stage(f"y{i}", a) for i, a in enumerate(labs)]
        super().__init__(staged_feats, staged_labs, weights)

    memory_type = "DIRECT"


class DiskFeatureSet(FeatureSet):
    """Sliced-epoch dataset over ``.npz`` shards.

    Parity: ``DiskFeatureSet`` / DISK_AND_DRAM(n) (FeatureSet.scala:332)
    — only ``num_slice`` shards are resident at a time; an epoch streams
    through all shards. Shards hold arrays ``x0..xK`` (features) and
    optional ``y0..yK`` (labels).
    """

    def __init__(self, paths: Sequence[str], num_slice: int = 1):
        self.paths = list(paths)
        self.num_slice = max(1, num_slice)
        self._size_cache: Optional[List[int]] = None

    def _load_shard(self, path: str) -> Dict[str, np.ndarray]:
        """path -> {'x0'..: features, 'y0'..: labels}; overridable for
        other on-disk formats (ShardedFileFeatureSet). Paths go through
        utils.file_io, so hdfs://-style URIs work once a filesystem is
        registered (Utils/File parity)."""
        from ..utils import file_io
        import io as _io

        with np.load(_io.BytesIO(file_io.read_bytes(path))) as z:
            return {k: z[k] for k in z.files}

    def _load_group(self, group) -> List[Dict[str, np.ndarray]]:
        """Load one resident slice's shards, concurrently when the shared
        worker resolver says the host has headroom (shard reads are
        IO-bound, so threads overlap them even under the GIL); order is
        preserved."""
        paths = [self.paths[int(pi)] for pi in group]
        if len(paths) <= 1:
            return [self._load_shard(p) for p in paths]
        from .host_pipeline import resolve_transform_workers
        from concurrent.futures import ThreadPoolExecutor

        workers = max(1, min(len(paths), resolve_transform_workers(None)))
        if workers == 1:
            return [self._load_shard(p) for p in paths]
        with ThreadPoolExecutor(max_workers=workers,
                                thread_name_prefix="zoo-shard") as pool:
            return list(pool.map(self._load_shard, paths))

    @property
    def _sizes(self) -> List[int]:
        if self._size_cache is None:
            self._size_cache = [self._load_shard(p)["x0"].shape[0]
                                for p in self.paths]
        return self._size_cache

    @staticmethod
    def write_shard(path: str, features, labels=None):
        """Helper to produce shard files in the expected layout."""
        feats = features if isinstance(features, (list, tuple)) \
            else [features]
        arrays = {f"x{i}": np.asarray(a) for i, a in enumerate(feats)}
        if labels is not None:
            labs = labels if isinstance(labels, (list, tuple)) else [labels]
            arrays.update({f"y{i}": np.asarray(a)
                           for i, a in enumerate(labs)})
        np.savez(path, **arrays)

    def size(self):
        return sum(self._sizes)

    def batches(self, batch_size, shuffle=False, drop_remainder=True,
                pad_remainder=False, seed=0):
        order = np.arange(len(self.paths))
        if shuffle:
            np.random.default_rng(seed).shuffle(order)
        def numkey(k):
            return (k[0], int(k[1:]))

        carry: Optional[List[List[np.ndarray]]] = None  # [xs, ys]
        groups = [order[s:s + self.num_slice]
                  for s in range(0, len(order), self.num_slice)]
        sizes_seen: Dict[int, int] = {}
        for gi, group in enumerate(groups):
            feats_acc: Dict[str, List[np.ndarray]] = {}
            for pi, shard in zip(group, self._load_group(group)):
                sizes_seen[int(pi)] = int(shard["x0"].shape[0])
                for k, v in shard.items():
                    feats_acc.setdefault(k, []).append(v)
            if self._size_cache is None and \
                    len(sizes_seen) == len(self.paths):
                # size() after one epoch costs nothing: sizes were
                # collected while streaming (no second full read)
                self._size_cache = [sizes_seen[i]
                                    for i in range(len(self.paths))]
            merged = {k: np.concatenate(v) for k, v in feats_acc.items()}
            xs = [merged[k] for k in sorted(merged, key=numkey)
                  if k.startswith("x")]
            ys = [merged[k] for k in sorted(merged, key=numkey)
                  if k.startswith("y")]
            if carry is not None:  # remainder samples from the last group
                xs = [np.concatenate([c, a]) for c, a in zip(carry[0], xs)]
                if ys:
                    ys = [np.concatenate([c, a])
                          for c, a in zip(carry[1], ys)]
            last = gi == len(groups) - 1
            n = xs[0].shape[0]
            # keep the tail for the next group so drop_remainder only
            # applies once per epoch, matching a flat dataset's count
            keep = n if last else (n // batch_size) * batch_size
            carry = None if last else [[a[keep:] for a in xs],
                                       [a[keep:] for a in ys]]
            slice_fs = ArrayFeatureSet([a[:keep] for a in xs],
                                       [a[:keep] for a in ys] if ys
                                       else None)
            yield from slice_fs.batches(
                batch_size, shuffle=shuffle,
                drop_remainder=drop_remainder,
                pad_remainder=pad_remainder, seed=seed + gi)


class GeneratorFeatureSet(FeatureSet):
    def __init__(self, fn, size):
        self.fn = fn
        self._size = size

    def size(self):
        return self._size

    def batches(self, batch_size, shuffle=False, drop_remainder=True,
                pad_remainder=False, seed=0):
        buf_x, buf_y = [], []
        for item in self.fn():
            x, y = item if isinstance(item, tuple) and len(item) == 2 \
                else (item, None)
            buf_x.append(x)
            buf_y.append(y)
            if len(buf_x) == batch_size:
                yield _stack_batch(buf_x, buf_y, batch_size)
                buf_x, buf_y = [], []
        if buf_x and not drop_remainder:
            yield _stack_batch(buf_x, buf_y, batch_size if pad_remainder
                               else len(buf_x), pad=pad_remainder)


def stack_samples(samples: Sequence[Sample]):
    """Stack Samples into (features_tuple, labels); the single shared
    batching helper (used by FeatureSet.samples and SampleToMiniBatch)."""
    samples = list(samples)
    if not samples:
        raise ValueError("empty sample collection")
    n_feat = len(samples[0].features)
    feats = tuple(np.stack([s.features[i] for s in samples])
                  for i in range(n_feat))
    labels = None
    if samples[0].labels is not None:
        labs = [np.stack([s.labels[i] for s in samples])
                for i in range(len(samples[0].labels))]
        labels = labs[0] if len(labs) == 1 else labs
    return feats, labels


def minibatch_len(batch: MiniBatch) -> int:
    return len(batch.weights) if batch.weights is not None else \
        len(batch.inputs[0])


def pad_minibatch(batch: MiniBatch, target: int) -> MiniBatch:
    """Pad a MiniBatch to ``target`` samples by repeating the last sample
    with zero weight. Loss/metrics are weight-aware so the padding does not
    bias them; note BatchNorm running stats are NOT weight-aware — training
    batch sizes should be a multiple of the data-parallel size to avoid
    padded samples entering normalization statistics."""
    n = minibatch_len(batch)
    if target <= n:
        return batch
    reps = target - n

    def pad(a):
        a = np.asarray(a)
        return np.concatenate([a, np.repeat(a[-1:], reps, 0)])

    xs = tuple(pad(x) for x in batch.inputs)
    ys = batch.targets
    if ys is not None:
        ys = [pad(y) for y in ys] if isinstance(ys, (list, tuple)) \
            else pad(ys)
    w = batch.weights if batch.weights is not None else \
        np.ones(n, np.float32)
    w = np.concatenate([np.asarray(w), np.zeros(reps, np.float32)])
    return MiniBatch(xs, ys, w)


def _stack_batch(buf_x, buf_y, batch_size, pad=False):
    n = len(buf_x)
    multi = isinstance(buf_x[0], (list, tuple))
    if multi:
        xs = tuple(np.stack([b[i] for b in buf_x])
                   for i in range(len(buf_x[0])))
    else:
        xs = (np.stack(buf_x),)
    ys = None
    if buf_y[0] is not None:
        ys = np.stack(buf_y)
    batch = MiniBatch(xs, ys, np.ones(n, np.float32))
    if pad and n < batch_size:
        batch = pad_minibatch(batch, batch_size)
    return batch


DEFAULT_DRAM_CACHE_BYTES = 2 << 30  # 2 GiB; FeatureSet.rdd cache_bytes kw


class TransformStats:
    """Thread-safe counters for host-side transform cost.

    One instance lives on each TransformedFeatureSet (``stats()``); the
    staged host pipeline reads the same counters for its telemetry, so
    "seconds spent transforming" is reported once no matter how many
    workers ran the Preprocessing chain.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.batches = 0
        self.seconds = 0.0
        self.cache_hits = 0
        self.arena_hits = 0
        self.worker_busy: Dict[int, float] = {}
        self.worker_items: Dict[int, int] = {}

    def record(self, seconds: float, batches: int = 1):
        with self._lock:
            self.batches += batches
            self.seconds += seconds

    def record_hit(self, batches: int = 1):
        with self._lock:
            self.cache_hits += batches

    def record_arena_hit(self, batches: int = 1):
        with self._lock:
            self.arena_hits += batches
            self.cache_hits += batches

    def record_worker(self, wid: int, seconds: float, items: int = 1):
        """Per-worker busy time (process backend reports it from the
        worker side, so queue/hand-off overhead is excluded)."""
        with self._lock:
            self.worker_busy[wid] = self.worker_busy.get(wid, 0.0) + seconds
            self.worker_items[wid] = self.worker_items.get(wid, 0) + items

    def worker_busy_snapshot(self) -> Dict[int, float]:
        """Cumulative busy seconds per worker; the InfeedMonitor diffs
        snapshots across a logging window for utilization telemetry."""
        with self._lock:
            return dict(self.worker_busy)

    def as_dict(self) -> Dict[str, Any]:
        with self._lock:
            return {"batches_transformed": self.batches,
                    "transform_seconds": round(self.seconds, 6),
                    "cache_hits": self.cache_hits,
                    "arena_hits": self.arena_hits,
                    "worker_items": dict(self.worker_items)}


def minibatch_nbytes(batch: MiniBatch) -> int:
    """Host-RAM footprint of a MiniBatch (cache-budget accounting)."""

    def add(x):
        if x is None:
            return 0
        if isinstance(x, (list, tuple)):
            return sum(add(v) for v in x)
        return np.asarray(x).nbytes

    return add(tuple(batch))


def default_arena_path() -> str:
    """Where the DIRECT arena lives when the caller doesn't say:
    ``ZOO_TPU_DIRECT_ARENA`` if set, else a per-user file in the temp
    dir — stable across processes of the same user, so pool workers and
    serving workers share one cache by default."""
    p = os.environ.get("ZOO_TPU_DIRECT_ARENA")
    if p:
        return p
    uid = os.getuid() if hasattr(os, "getuid") else 0
    return os.path.join(tempfile.gettempdir(), f"zoo_tpu_{uid}.arena")


class DirectArena:
    """Disk-backed memory-mapped cache arena — the real DIRECT tier.

    The DRAM tier memoizes transformed batches in the Python heap; this
    arena is the next rung of the reference's memory-tier ladder
    (FeatureSet.scala DIRECT/PMEM): batches past ``cache_bytes`` spill
    to one append-only file that every process on the host can mmap, so
    N infeed/serving workers share ONE transformed copy of the dataset
    instead of N.

    On-disk format (all host-endian, numpy dtype strings):

    - ``<path>`` — array bytes back-to-back, each 64-byte aligned, in
      epoch order. Append-only; never rewritten in place.
    - ``<path>.index.json`` — the only source of truth for what's
      readable: per-signature batch metas (absolute offset, shape,
      dtype per array + the MiniBatch structure template) plus an LRU
      list. Committed atomically (tmp + rename) *after* the data file
      is flushed, so concurrent readers see complete epochs or nothing.
    - ``<path>.lock`` — single-writer lockfile (O_EXCL, pid inside;
      stale locks from dead writers are stolen). Readers never lock.

    Same signature machinery as the DRAM tier: a signature is the batch
    geometry ``(batch_size, drop_remainder, pad_remainder)`` plus a
    dataset fingerprint; LRU eviction applies when the byte budget is
    exceeded (logical: the entry leaves the index; file space is
    reclaimed when the arena empties and is truncated).
    """

    def __init__(self, path: str, budget_bytes: Optional[int] = None):
        self.path = path
        self.index_path = path + ".index.json"
        self.lock_path = path + ".lock"
        self.budget = int(budget_bytes) if budget_bytes else None
        self._mm: Optional[mmap_mod.mmap] = None
        self._mm_size = 0
        self._index_mtime: Optional[float] = None
        self._index: Dict[str, Any] = {"version": 1, "next_offset": 0,
                                       "signatures": {}, "lru": []}
        self._load_index(force=True)

    # ---- index ------------------------------------------------------
    def _load_index(self, force: bool = False):
        try:
            st = os.stat(self.index_path)
        except OSError:
            return
        if not force and st.st_mtime_ns == self._index_mtime:
            return
        try:
            with open(self.index_path) as f:
                self._index = json.load(f)
            self._index_mtime = st.st_mtime_ns
        except (OSError, ValueError):
            pass  # mid-rename race: keep the previous view

    def _store_index(self):
        tmp = self.index_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._index, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.index_path)
        try:
            self._index_mtime = os.stat(self.index_path).st_mtime_ns
        except OSError:
            pass

    # ---- read path --------------------------------------------------
    def has(self, sig_key: str, fingerprint: str) -> bool:
        self._load_index()
        entry = self._index["signatures"].get(sig_key)
        return entry is not None and entry["fp"] == fingerprint

    def batch_metas(self, sig_key: str) -> List[Dict[str, Any]]:
        entry = self._index["signatures"][sig_key]
        if sig_key in self._index["lru"]:
            self._index["lru"].remove(sig_key)
            self._index["lru"].append(sig_key)
        return entry["batches"]

    def _mapping(self) -> mmap_mod.mmap:
        need = int(self._index["next_offset"])
        if self._mm is None or self._mm_size < need:
            # the old mapping (if any) stays alive under existing views;
            # new reads go through the re-mmap covering the grown file
            with open(self.path, "rb") as f:
                self._mm = mmap_mod.mmap(f.fileno(), need,
                                         access=mmap_mod.ACCESS_READ)
            self._mm_size = need
        return self._mm

    def read_batch(self, meta: Dict[str, Any]) -> MiniBatch:
        """Rebuild one batch as zero-copy views into the arena mapping
        (read-only; the page cache is the shared cross-process copy)."""
        from .infeed_worker import rebuild_batch

        mm = self._mapping()
        arrays = []
        for off, shape, dt in meta["a"]:
            shape = tuple(shape)
            count = int(np.prod(shape)) if shape else 1
            arrays.append(np.frombuffer(
                mm, dtype=np.dtype(dt), count=count,
                offset=int(off)).reshape(shape))
        return rebuild_batch(meta["t"], arrays)

    # ---- write path -------------------------------------------------
    def try_writer(self, sig_key: str,
                   fingerprint: str) -> Optional["_ArenaWriter"]:
        """Acquire the single-writer role, or None (another live process
        is writing — the caller streams uncached; its epoch commits)."""
        try:
            fd = os.open(self.lock_path,
                         os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            try:
                with open(self.lock_path) as f:
                    pid = int(f.read().strip() or 0)
                os.kill(pid, 0)  # raises when the writer is gone
                return None
            except (OSError, ValueError):
                try:  # stale lock from a dead writer: steal it
                    os.unlink(self.lock_path)
                except OSError:
                    pass
                try:
                    fd = os.open(self.lock_path,
                                 os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                except FileExistsError:
                    return None
        with os.fdopen(fd, "w") as f:
            f.write(str(os.getpid()))
        self._load_index()
        return _ArenaWriter(self, sig_key, fingerprint)

    def _release_lock(self):
        try:
            os.unlink(self.lock_path)
        except OSError:
            pass

    def _evict_over_budget(self, incoming: int,
                           allow_truncate: bool = True):
        if self.budget is None:
            return
        sigs = self._index["signatures"]

        def live() -> int:
            return sum(e["bytes"] for e in sigs.values())

        while self._index["lru"] and live() + incoming > self.budget:
            victim = self._index["lru"].pop(0)
            e = sigs.pop(victim, None)
            if e is not None:
                logger.info(
                    "DIRECT arena: evicted signature %s (%.1f MiB)",
                    victim, e["bytes"] / 2**20)
        if not sigs and allow_truncate:
            # the arena emptied: reclaim the file space for real (never
            # mid-commit — the incoming epoch's bytes sit at the tail)
            self._index["next_offset"] = 0
            try:
                with open(self.path, "r+b") as f:
                    f.truncate(0)
            except OSError:
                pass


class _ArenaWriter:
    """One epoch's append session against the arena (lock held)."""

    def __init__(self, arena: DirectArena, sig_key: str, fingerprint: str):
        self.arena = arena
        self.sig_key = sig_key
        self.fingerprint = fingerprint
        self.start_offset = int(arena._index["next_offset"])
        self.offset = self.start_offset
        self.metas: List[Dict[str, Any]] = []
        self.nbytes = 0
        self.ok = True
        self._done = False
        self._f = open(arena.path, "ab")
        if self._f.tell() > self.offset:
            # uncommitted garbage from an aborted writer: overwrite it
            self._f.close()
            self._f = open(arena.path, "r+b")
            self._f.truncate(self.offset)
            self._f = open(arena.path, "ab")

    def append(self, batch: MiniBatch):
        """Spill one transformed batch; a batch the flattener can't take
        (non-ndarray leaves) voids the whole session — a partial epoch
        in the index would replay as the whole dataset."""
        from .infeed_worker import flatten_batch, slot_nbytes

        if not self.ok:
            return
        arrays, template = flatten_batch(batch)
        if arrays is None:
            self.ok = False
            logger.warning("DIRECT arena: batch not arena-cacheable; "
                           "signature %s will not spill", self.sig_key)
            return
        metas = []
        for a in arrays:
            pad = -self._f.tell() % 64
            if pad:
                self._f.write(b"\0" * pad)
            off = self._f.tell()
            self._f.write(a.tobytes())
            metas.append([off, list(a.shape), a.dtype.str])
        self.metas.append({"t": template, "a": metas})
        self.nbytes += slot_nbytes(arrays)

    def commit(self) -> Optional[List[Dict[str, Any]]]:
        """Flush data, then atomically publish the signature. Returns
        the batch metas (readable immediately), or None if voided."""
        if self._done:
            return None
        self._done = True
        try:
            if not self.ok:
                self._f.close()
                return None
            self._f.flush()
            os.fsync(self._f.fileno())
            end = self._f.tell()
            self._f.close()
            idx = self.arena._index
            idx["signatures"].pop(self.sig_key, None)
            if self.sig_key in idx["lru"]:
                idx["lru"].remove(self.sig_key)
            self.arena._evict_over_budget(self.nbytes,
                                          allow_truncate=False)
            idx["signatures"][self.sig_key] = {
                "fp": self.fingerprint, "bytes": self.nbytes,
                "batches": self.metas}
            idx["lru"].append(self.sig_key)
            idx["next_offset"] = max(int(idx["next_offset"]), end)
            self.arena._store_index()
            return self.metas
        finally:
            self.arena._release_lock()

    def abort(self):
        """Interrupted epoch: drop the appended bytes (truncate back) and
        publish nothing."""
        if self._done:
            return
        self._done = True
        try:
            self._f.close()
            with open(self.arena.path, "r+b") as f:
                f.truncate(self.start_offset)
        except OSError:
            pass
        finally:
            self.arena._release_lock()


class TransformedFeatureSet(FeatureSet):
    """Applies a Preprocessing chain per batch on the host, off the hot path
    when wrapped by the prefetcher.

    ``num_workers > 0`` runs the chain for several batches concurrently on
    an ordered thread pool (MTSampleToMiniBatch parity); ``cache()`` turns
    on the DRAM tier (``FeatureSet.rdd(..., memory_type="DRAM")`` parity):
    transformed batches are memoized on the first complete epoch under a
    byte budget and replayed — batch-granular reshuffle by the epoch seed —
    on later epochs, with LRU eviction across batch signatures.
    """

    def __init__(self, base: FeatureSet, preprocessing,
                 num_workers: int = 0):
        self.base = base
        self.preprocessing = preprocessing
        self.num_workers = num_workers
        self._stats = TransformStats()
        self._cache_budget = 0  # bytes; 0 = DRAM tier off
        self._cache: "OrderedDict[tuple, Tuple[list, int]]" = OrderedDict()
        self._cache_used = 0
        self._cache_disabled: set = set()  # signatures over budget alone
        self._arena: Optional[DirectArena] = None
        self._arena_metas: Dict[tuple, List[Dict[str, Any]]] = {}
        self._fp: Optional[str] = None

    def size(self):
        return self.base.size()

    def stats(self) -> TransformStats:
        return self._stats

    def cache(self, max_bytes: int = DEFAULT_DRAM_CACHE_BYTES,
              arena_path: Optional[str] = None,
              arena_bytes: Optional[int] = None
              ) -> "TransformedFeatureSet":
        """Enable the cache-tier ladder: transformed batches memoize in
        host RAM up to ``max_bytes`` (the DRAM tier). With
        ``arena_path`` the DIRECT tier opens beneath it: *every* batch
        of a cached signature also lands in the disk arena — the
        cross-process source of truth — and replay serves the hot
        prefix from RAM with the spill tail mmap'd from the arena, so
        datasets past ``max_bytes`` still replay with zero
        re-transforms (and other processes on the host read the same
        arena instead of re-transforming their own copy)."""
        self._cache_budget = int(max_bytes)
        if arena_path:
            self._arena = DirectArena(arena_path, budget_bytes=arena_bytes)
        return self

    def _fingerprint(self) -> str:
        """Cheap dataset identity for cross-process arena hits: dataset
        type/size/geometry + the Preprocessing chain's type. Two
        processes building the same pipeline agree; a changed dataset
        or chain misses and re-transforms."""
        if self._fp is not None:
            return self._fp
        parts = [type(self.base).__name__, str(self.base.size()),
                 type(self.preprocessing).__name__]
        base = self.base
        if isinstance(base, ArrayFeatureSet):
            for a in base.features:
                parts.append(f"x{a.shape}{a.dtype}")
            for a in (base.labels or []):
                parts.append(f"y{a.shape}{a.dtype}")
        if isinstance(base, DiskFeatureSet):
            parts.extend(os.path.basename(p) for p in base.paths)
        self._fp = hashlib.sha1(
            "|".join(parts).encode()).hexdigest()[:16]
        return self._fp

    def _apply_timed(self, batch: MiniBatch) -> MiniBatch:
        t0 = time.perf_counter()
        out = self.preprocessing(batch)
        self._stats.record(time.perf_counter() - t0)
        return out

    def _evict_for(self, incoming_bytes: int):
        while self._cache and \
                self._cache_used + incoming_bytes > self._cache_budget:
            sig, (_, nbytes) = self._cache.popitem(last=False)
            self._cache_used -= nbytes
            logger.info(
                "DRAM cache: evicted signature %s (%.1f MiB) to fit "
                "%.1f MiB", sig, nbytes / 2**20, incoming_bytes / 2**20)

    def batches(self, batch_size, shuffle=False, drop_remainder=True,
                pad_remainder=False, seed=0, num_workers=None,
                backend=None):
        sig = (batch_size, bool(drop_remainder), bool(pad_remainder))
        sig_key = f"{batch_size}:{int(sig[1])}:{int(sig[2])}"
        caching = bool(self._cache_budget) or self._arena is not None
        if caching and sig in self._cache:
            # replay: DRAM hot prefix, arena-mmap'd spill tail
            cached, _ = self._cache[sig]
            self._cache.move_to_end(sig)  # LRU touch
            metas = self._arena_metas.get(sig, [])
            order = np.arange(max(len(metas), len(cached)))
            if shuffle:
                # sample-level shuffle happened before the transform was
                # memoized; replay epochs reshuffle at batch granularity
                # with the fresh epoch seed (documented tradeoff)
                np.random.default_rng(seed).shuffle(order)
            for i in order:
                if i < len(cached):
                    self._stats.record_hit()
                    yield cached[i]
                else:
                    self._stats.record_arena_hit()
                    yield self._arena.read_batch(metas[i])
            return
        if caching and self._arena is not None \
                and sig not in self._cache_disabled \
                and self._arena.has(sig_key, self._fingerprint()):
            # replay from the arena alone: another process (or an
            # earlier incarnation of this one) transformed this
            # signature — zero re-transforms, shared page-cache bytes
            metas = self._arena.batch_metas(sig_key)
            order = np.arange(len(metas))
            if shuffle:
                np.random.default_rng(seed).shuffle(order)
            for i in order:
                self._stats.record_arena_hit()
                yield self._arena.read_batch(metas[i])
            return
        base_it = self.base.batches(
            batch_size, shuffle=shuffle, drop_remainder=drop_remainder,
            pad_remainder=pad_remainder, seed=seed)
        workers = self.num_workers if num_workers is None else num_workers
        if workers and workers < 0:
            from .host_pipeline import resolve_transform_workers
            workers = resolve_transform_workers(workers)
        if workers and workers > 0:
            from .host_pipeline import (ParallelTransformIterator,
                                        ProcessTransformPool,
                                        resolve_infeed_backend)
            if resolve_infeed_backend(backend, self.preprocessing) \
                    == "process":
                # the chain itself is pickled to the workers, not
                # _apply_timed (TransformStats holds a threading.Lock);
                # workers report their transform seconds back instead
                it: Iterator[MiniBatch] = ProcessTransformPool(
                    base_it, self.preprocessing, num_workers=workers,
                    stats=self._stats)
            else:
                it = ParallelTransformIterator(
                    base_it, self._apply_timed, num_workers=workers)
        else:
            it = (self._apply_timed(b) for b in base_it)
        if not caching or sig in self._cache_disabled:
            yield from it
            return
        writer = None
        if self._arena is not None:
            writer = self._arena.try_writer(sig_key, self._fingerprint())
        acc: Optional[List[MiniBatch]] = []
        acc_bytes = 0
        dram_full = False
        complete = False
        try:
            for out in it:
                if writer is not None:
                    # every batch of the signature goes to the arena —
                    # disk is the cross-process truth; DRAM memoizes
                    # only the hot prefix under the byte budget
                    writer.append(out)
                if acc is not None and not dram_full:
                    nb = minibatch_nbytes(out)
                    if acc_bytes + nb > self._cache_budget:
                        if writer is not None and writer.ok:
                            dram_full = True  # tail spills to the arena
                        elif self._arena is not None:
                            # transient: the arena writer was busy (or
                            # this batch isn't arena-cacheable); retry
                            # the spill on the next epoch
                            acc = None
                        else:
                            logger.info(
                                "DRAM cache: signature %s exceeds budget "
                                "(%.1f MiB > %.1f MiB); caching disabled "
                                "for it", sig, (acc_bytes + nb) / 2**20,
                                self._cache_budget / 2**20)
                            self._cache_disabled.add(sig)
                            acc = None
                    else:
                        acc_bytes += nb
                        acc.append(out)
                yield out
            complete = acc is not None or \
                (writer is not None and writer.ok)
        finally:
            close = getattr(it, "close", None)
            if close is not None:
                close()
            if complete:
                # only full epochs commit: an early break or error must
                # not memoize a truncated epoch as the whole dataset
                metas = writer.commit() if writer is not None else None
                if metas is not None:
                    self._arena_metas[sig] = metas
                if acc is not None and (metas is not None
                                        or not dram_full):
                    # a DRAM prefix whose arena tail failed to commit
                    # must not memoize: it would replay as the dataset
                    self._evict_for(acc_bytes)
                    self._cache[sig] = (acc, acc_bytes)
                    self._cache_used += acc_bytes
            elif writer is not None:
                writer.abort()


class ShardedFileFeatureSet(DiskFeatureSet):
    """Sharded files -> per-host streaming infeed.

    The SURVEY's hardest data-layer problem ((a): Spark-partition ->
    infeed streaming without host OOM): the reference hides it inside
    JVM-local MiniBatch iterators over cached RDD partitions
    (NNEstimator.scala:382 getDataSet + FeatureSet memory tiers). Here
    file shards play the role of partitions: each HOST keeps only the
    shards striped to it (``paths[i]`` with ``i % num_processes ==
    process_index``), an epoch streams ``num_slice`` shards at a time
    through the DiskFeatureSet machinery, and the engine's
    ``make_array_from_process_local_data`` path assembles the global batch
    — so no host ever materializes the dataset (contrast: the round-1/2
    ``df[col].tolist()`` NNFrames ingest).

    Formats: ``.npz`` (DiskFeatureSet layout), ``.csv`` / ``.parquet``
    (pandas; ``columns`` selects feature columns, ``label_col`` the label).
    """

    def __init__(self, paths: Sequence[str], num_slice: int = 1,
                 columns: Optional[Sequence[str]] = None,
                 label_col: Optional[str] = None,
                 shard_per_host: bool = True,
                 process_index: Optional[int] = None,
                 num_processes: Optional[int] = None):
        if shard_per_host:
            if process_index is None or num_processes is None:
                import jax
                process_index = jax.process_index()
                num_processes = jax.process_count()
            if num_processes > 1:
                paths = [p for i, p in enumerate(paths)
                         if i % num_processes == process_index]
                if not paths:
                    raise ValueError(
                        f"no shards for process {process_index}: provide "
                        f">= {num_processes} files (one per host)")
        super().__init__(paths, num_slice=num_slice)
        self.columns = list(columns) if columns else None
        self.label_col = label_col

    def _load_shard(self, path: str) -> Dict[str, np.ndarray]:
        lower = path.lower()
        if lower.endswith(".npz"):
            return super()._load_shard(path)
        import io as _io

        import pandas as pd

        from ..utils import file_io

        buf = _io.BytesIO(file_io.read_bytes(path))
        if lower.endswith(".parquet") or lower.endswith(".pq"):
            df = pd.read_parquet(buf)
        elif lower.endswith(".csv"):
            df = pd.read_csv(buf)
        else:
            raise ValueError(f"unsupported shard format: {path}")
        cols = self.columns or [c for c in df.columns
                                if c != self.label_col]
        out = {"x0": df[cols].to_numpy(np.float32)}
        if self.label_col is not None and self.label_col in df.columns:
            out["y0"] = df[self.label_col].to_numpy()
        return out


# Live pipeline-stage registry: every closeable infeed stage
# (PrefetchIterator, ParallelTransformIterator, DeviceStagingIterator)
# registers itself so launcher-driven shutdown (zoo-launch SIGTERM ->
# launcher.worker handler) can close them all — a killed worker must not
# hang in concurrent.futures' atexit join on still-busy transform-pool
# threads. WeakSet: normal close()/GC drops entries automatically.
_LIVE_PIPELINES: "weakref.WeakSet" = weakref.WeakSet()


def register_pipeline(obj) -> None:
    """Track a closeable pipeline stage for process-wide teardown."""
    _LIVE_PIPELINES.add(obj)


def shutdown_all_pipelines() -> int:
    """Close every live pipeline stage; returns how many were closed.

    Idempotent and safe mid-stream: each stage's ``close()`` already
    handles being called while a producer is running.
    """
    closed = 0
    for obj in list(_LIVE_PIPELINES):
        try:
            obj.close()
            closed += 1
        except Exception:  # noqa: BLE001 - teardown must not raise
            logger.warning("pipeline close failed during shutdown",
                           exc_info=True)
        _LIVE_PIPELINES.discard(obj)
    return closed


class PrefetchIterator:
    """Background-thread prefetch of host minibatches (double buffering the
    host side; ``jax.device_put`` overlap covers the device side). Replaces
    the reference's PMEM/DRAM cache tiers + MTSampleToMiniBatch."""

    def __init__(self, it: Iterator, depth: int = 2):
        self.it = it
        self.q: "queue.Queue" = queue.Queue(maxsize=depth)
        self.done = object()
        self.error = None
        self._stopped = False
        register_pipeline(self)
        self.thread = threading.Thread(target=self._worker, daemon=True)
        self.thread.start()

    def _worker(self):
        try:
            for item in self.it:
                while not self._stopped:
                    try:
                        self.q.put(item, timeout=0.2)
                        break
                    except queue.Full:
                        continue
                if self._stopped:
                    return
        except BaseException as e:  # propagate to consumer
            self.error = e
        finally:
            while not self._stopped:
                try:
                    self.q.put(self.done, timeout=0.2)
                    break
                except queue.Full:
                    continue

    def close(self, timeout: float = 5.0):
        """Unblock and discard the producer (call when abandoning the
        iterator mid-stream, e.g. early end-trigger or step failure).

        Joins the worker (bounded wait) so a producer blocked in ``put``
        cannot re-insert items after the drain, then closes the upstream
        iterator — only once the worker is provably out of it (closing a
        generator mid-execution from another thread raises ValueError).
        """
        self._stopped = True
        try:
            while True:
                self.q.get_nowait()
        except queue.Empty:
            pass
        self.thread.join(timeout)
        try:  # drop anything re-inserted between drain and worker exit
            while True:
                self.q.get_nowait()
        except queue.Empty:
            pass
        upstream_close = getattr(self.it, "close", None)
        if upstream_close is not None and not self.thread.is_alive():
            upstream_close()

    def __iter__(self):
        return self

    def __next__(self):
        if self._stopped:
            raise StopIteration
        if self.error is not None:
            # surface producer failure immediately rather than after the
            # already-queued batches and the done sentinel drain out
            self._stopped = True
            err, self.error = self.error, None
            raise err
        item = self.q.get()
        if item is self.done:
            self._stopped = True
            if self.error is not None:
                err, self.error = self.error, None
                raise err
            raise StopIteration
        return item
