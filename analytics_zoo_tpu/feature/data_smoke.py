"""Data-pipeline smoke: serial vs staged host pipeline on a synthetic
preprocessing-heavy epoch, asserting identical batches either way.

CI/tooling entry (``scripts/data-smoke``): builds an ArrayFeatureSet with a
deliberately slow Preprocessing chain (simulating decode/augment cost that
releases the GIL, as cv2/BLAS do), streams one epoch through (a) the serial
in-line path and (b) the full staged pipeline (transform pool + prefetch +
device staging with identity puts), and checks bit-identical batch content
and ordering plus a second DRAM-cached epoch.  Three further legs cover the
process-based infeed (docs/data-pipeline.md):

- ``process``: the same epoch through ``ProcessTransformPool`` (spawned
  workers + shared-memory rings) on a GIL-holding pure-Python chain,
  bit-identical to the serial reference;
- ``direct``: an arena-backed cache with a DRAM budget smaller than the
  epoch — the spill tail replays from the disk arena with zero
  re-transforms, and a second *process* (``--arena-reader``) replays the
  whole epoch from the arena without transforming anything;
- ``chaos``: ``ZOO_TPU_FAULT=infeed-worker:kill@N`` kills one worker
  mid-epoch; the pool respawns it and the epoch must still be complete,
  duplicate-free and bit-identical.

Exit 0 on success, 1 on any mismatch, printing one JSON line of pipeline
stats either way.

Usage::

    python -m analytics_zoo_tpu.feature.data_smoke [--batches 24]
        [--batch 32] [--transform-ms 4] [--workers 2] [--skip-process]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time


def cpu_bound_transform(batch):
    """Deterministic, picklable, GIL-*holding* transform: a pure-Python
    loop standing in for PIL-style decode work. Declared module-level so
    spawned infeed workers can import it by reference."""
    from .feature_set import MiniBatch

    acc = 0
    for i in range(200):
        acc += i * i
    scale = 2.0 if acc else 0.0  # the loop is real but the output fixed
    return MiniBatch(tuple(x * scale for x in batch.inputs),
                     batch.targets, batch.weights)


def _build_base(args):
    import numpy as np

    from .feature_set import FeatureSet

    n = args.batches * args.batch
    feats = np.arange(n * 4, dtype=np.float32).reshape(n, 4)
    labels = np.arange(n, dtype=np.float32)
    return FeatureSet.array(feats, labels)


def _build_direct(args, arena):
    from .common import LambdaPreprocessing

    base = _build_base(args)
    tfs = base.transform(
        LambdaPreprocessing(cpu_bound_transform, cpu_bound=True))
    # DRAM budget ~25% of the epoch: the tail must spill to the arena
    epoch_bytes = args.batches * args.batch * 4 * 6
    tfs.cache(max(1, epoch_bytes // 4), arena_path=arena)
    return tfs


def _batches_equal(ref, got, errors, tag):
    import numpy as np

    if len(got) != len(ref):
        errors.append(f"{tag}: batch count {len(got)} != {len(ref)}")
        return
    for i, (a, b) in enumerate(zip(ref, got)):
        for xa, xb in zip(a.inputs, b.inputs):
            if not np.array_equal(xa, xb):
                errors.append(f"{tag}: batch {i} inputs differ")
                return
        if not np.array_equal(a.targets, b.targets):
            errors.append(f"{tag}: batch {i} targets differ")
            return


def _arena_reader_main(args) -> int:
    """Second process of the ``direct`` leg: replay the epoch purely from
    the shared arena — zero transforms allowed."""
    tfs = _build_direct(args, args.arena_reader)
    got = list(tfs.batches(args.batch, shuffle=False))
    s = tfs.stats().as_dict()
    errors = []
    if len(got) != args.batches:
        errors.append(f"reader: {len(got)} batches != {args.batches}")
    if s["batches_transformed"] != 0:
        errors.append(f"reader re-transformed: {s}")
    if s["arena_hits"] != args.batches:
        errors.append(f"reader arena_hits {s['arena_hits']}")
    print(json.dumps({"arena_reader": s, "errors": errors}))
    return 1 if errors else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="data-smoke")
    ap.add_argument("--batches", type=int, default=24)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--transform-ms", type=float, default=4.0,
                    help="simulated per-batch transform cost")
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--skip-process", action="store_true",
                    help="thread + DRAM legs only (no spawned pools)")
    ap.add_argument("--arena-reader", metavar="PATH",
                    help=argparse.SUPPRESS)  # internal: direct-leg proc 2
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    if args.arena_reader:
        return _arena_reader_main(args)

    import numpy as np  # noqa: F401  (used via helpers)

    from .common import LambdaPreprocessing
    from .feature_set import FeatureSet, MiniBatch
    from .host_pipeline import DeviceStagingIterator, build_host_pipeline

    base = _build_base(args)

    def slow_transform(batch: MiniBatch) -> MiniBatch:
        # GIL-releasing stand-in for decode/augment (sleep, like cv2's
        # C++ loops, lets other workers run)
        time.sleep(args.transform_ms / 1e3)
        return MiniBatch(tuple(x * 2.0 for x in batch.inputs),
                         batch.targets, batch.weights)

    def one_epoch_serial(fs):
        t0 = time.perf_counter()
        out = list(fs.batches(args.batch, shuffle=True, seed=7))
        return out, time.perf_counter() - t0

    def one_epoch_staged(fs, backend=None):
        t0 = time.perf_counter()
        it = build_host_pipeline(
            fs, args.batch, shuffle=True, drop_remainder=True, seed=7,
            transform_workers=args.workers, prefetch_depth=2,
            infeed_backend=backend)
        staging = DeviceStagingIterator(
            it, lambda b: b, lambda bs: list(bs), depth=2)
        out = [host for _dev, host in staging]
        staging.close()
        it.close()
        return out, time.perf_counter() - t0

    serial_fs = base.transform(LambdaPreprocessing(slow_transform))
    staged_fs = FeatureSet.rdd(
        base.transform(LambdaPreprocessing(slow_transform)),
        memory_type="DRAM")

    ref, serial_s = one_epoch_serial(serial_fs)
    got, staged_s = one_epoch_staged(staged_fs)
    cached, cached_s = one_epoch_staged(staged_fs)  # epoch 2: DRAM replay

    errors: list = []
    _batches_equal(ref, got, errors, "staged")
    if len(cached) != len(ref):
        errors.append(f"cached epoch count {len(cached)} != {len(ref)}")
    stats = staged_fs.stats().as_dict()
    if stats["cache_hits"] < len(ref):
        errors.append(f"DRAM cache never hit: {stats}")

    out = {
        "batches": len(ref),
        "serial_s": round(serial_s, 4),
        "staged_s": round(staged_s, 4),
        "cached_epoch_s": round(cached_s, 4),
        "staged_speedup": round(serial_s / max(staged_s, 1e-9), 2),
        "cached_speedup": round(serial_s / max(cached_s, 1e-9), 2),
        "transform_stats": stats,
        "errors": errors,
    }

    if not args.skip_process:
        # --- process leg: spawned pool, shared-memory rings ------------
        chain = LambdaPreprocessing(cpu_bound_transform, cpu_bound=True)
        proc_ref = list(base.transform(chain)
                        .batches(args.batch, shuffle=True, seed=7))
        proc_fs = base.transform(chain)
        proc_out, proc_s = one_epoch_staged(proc_fs, backend="process")
        _batches_equal(proc_ref, proc_out, errors, "process")
        pstats = proc_fs.stats().as_dict()
        if not pstats["worker_items"]:
            errors.append(f"process leg recorded no worker items: {pstats}")
        out["process_s"] = round(proc_s, 4)
        out["process_stats"] = pstats

        # --- direct leg: DRAM prefix + disk arena tail -----------------
        with tempfile.TemporaryDirectory() as d:
            arena = os.path.join(d, "smoke.arena")
            dfs = _build_direct(args, arena)
            d_ref = list(dfs.batches(args.batch, shuffle=False))
            replay = list(dfs.batches(args.batch, shuffle=False))
            _batches_equal(d_ref, replay, errors, "direct-replay")
            dstats = dfs.stats().as_dict()
            if dstats["batches_transformed"] != args.batches:
                errors.append(
                    f"direct leg re-transformed on replay: {dstats}")
            if dstats["arena_hits"] == 0:
                errors.append(f"direct leg never hit the arena: {dstats}")
            out["direct_stats"] = dstats
            # second process replays the same arena concurrently with
            # this one still holding mappings open
            r = subprocess.run(
                [sys.executable, "-m",
                 "analytics_zoo_tpu.feature.data_smoke",
                 "--arena-reader", arena,
                 "--batches", str(args.batches),
                 "--batch", str(args.batch)],
                capture_output=True, text=True, timeout=300)
            out["arena_reader"] = (r.stdout or "").strip()[-500:]
            if r.returncode != 0:
                errors.append(
                    f"arena reader failed: {(r.stderr or '')[-500:]}")

        # --- chaos leg: kill a worker mid-epoch ------------------------
        with tempfile.TemporaryDirectory() as d:
            env_before = {k: os.environ.get(k)
                          for k in ("ZOO_TPU_FAULT", "ZOO_TPU_FAULT_STATE")}
            os.environ["ZOO_TPU_FAULT"] = "infeed-worker:kill@2"
            os.environ["ZOO_TPU_FAULT_STATE"] = d
            try:
                cfs = base.transform(chain)
                chaos_out, _ = one_epoch_staged(cfs, backend="process")
            finally:
                for k, v in env_before.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v
            _batches_equal(proc_ref, chaos_out, errors, "chaos")
            if not os.path.exists(
                    os.path.join(d, "fired.infeed-worker_kill_2")):
                errors.append("chaos leg: fault never fired")
        out["chaos_batches"] = len(chaos_out)

    out["errors"] = errors
    print(json.dumps(out))
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
