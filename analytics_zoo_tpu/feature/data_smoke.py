"""Data-pipeline smoke: serial vs staged host pipeline on a synthetic
preprocessing-heavy epoch, asserting identical batches either way.

CI/tooling entry (``scripts/data-smoke``): builds an ArrayFeatureSet with a
deliberately slow Preprocessing chain (simulating decode/augment cost that
releases the GIL, as cv2/BLAS do), streams one epoch through (a) the serial
in-line path and (b) the full staged pipeline (transform pool + prefetch +
device staging with identity puts), and checks bit-identical batch content
and ordering plus a second DRAM-cached epoch.  Exit 0 on success, 1 on any
mismatch, printing one JSON line of pipeline stats either way.

Usage::

    python -m analytics_zoo_tpu.feature.data_smoke [--batches 24]
        [--batch 32] [--transform-ms 4] [--workers 2]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="data-smoke")
    ap.add_argument("--batches", type=int, default=24)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--transform-ms", type=float, default=4.0,
                    help="simulated per-batch transform cost")
    ap.add_argument("--workers", type=int, default=2)
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import numpy as np

    from .common import LambdaPreprocessing
    from .feature_set import FeatureSet, MiniBatch
    from .host_pipeline import DeviceStagingIterator, build_host_pipeline

    n = args.batches * args.batch
    feats = np.arange(n * 4, dtype=np.float32).reshape(n, 4)
    labels = np.arange(n, dtype=np.float32)
    base = FeatureSet.array(feats, labels)

    def slow_transform(batch: MiniBatch) -> MiniBatch:
        # GIL-releasing stand-in for decode/augment (sleep, like cv2's
        # C++ loops, lets other workers run)
        time.sleep(args.transform_ms / 1e3)
        return MiniBatch(tuple(x * 2.0 for x in batch.inputs),
                         batch.targets, batch.weights)

    def one_epoch_serial(fs):
        t0 = time.perf_counter()
        out = list(fs.batches(args.batch, shuffle=True, seed=7))
        return out, time.perf_counter() - t0

    def one_epoch_staged(fs):
        t0 = time.perf_counter()
        it = build_host_pipeline(
            fs, args.batch, shuffle=True, drop_remainder=True, seed=7,
            transform_workers=args.workers, prefetch_depth=2)
        staging = DeviceStagingIterator(
            it, lambda b: b, lambda bs: list(bs), depth=2)
        out = [host for _dev, host in staging]
        staging.close()
        it.close()
        return out, time.perf_counter() - t0

    serial_fs = base.transform(LambdaPreprocessing(slow_transform))
    staged_fs = FeatureSet.rdd(
        base.transform(LambdaPreprocessing(slow_transform)),
        memory_type="DRAM")

    ref, serial_s = one_epoch_serial(serial_fs)
    got, staged_s = one_epoch_staged(staged_fs)
    cached, cached_s = one_epoch_staged(staged_fs)  # epoch 2: DRAM replay

    errors = []
    if len(got) != len(ref):
        errors.append(f"batch count {len(got)} != {len(ref)}")
    for i, (a, b) in enumerate(zip(ref, got)):
        for xa, xb in zip(a.inputs, b.inputs):
            if not np.array_equal(xa, xb):
                errors.append(f"batch {i}: inputs differ")
                break
    if len(cached) != len(ref):
        errors.append(f"cached epoch count {len(cached)} != {len(ref)}")
    stats = staged_fs.stats().as_dict()
    if stats["cache_hits"] < len(ref):
        errors.append(f"DRAM cache never hit: {stats}")

    out = {
        "batches": len(ref),
        "serial_s": round(serial_s, 4),
        "staged_s": round(staged_s, 4),
        "cached_epoch_s": round(cached_s, 4),
        "staged_speedup": round(serial_s / max(staged_s, 1e-9), 2),
        "cached_speedup": round(serial_s / max(cached_s, 1e-9), 2),
        "transform_stats": stats,
        "errors": errors,
    }
    print(json.dumps(out))
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
