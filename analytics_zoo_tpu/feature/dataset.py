"""Distributed dataset ingestion: a partitioned parquet/arrow directory
becomes a per-host disjoint shard stream.

Reference analogue: ``NNEstimator.scala:382-414`` turns a Spark DataFrame
into a cached, partitioned FeatureSet whose MiniBatch iterators are
executor-local (``FeatureSet.scala:423-455``) — shard locality is the
platform seam that makes "point the estimator at a cluster-sized table"
work.  TPU rebuild: the "table" is a directory of shard files (the layout
every Spark/Beam/Ray job already writes), discovered through
:mod:`utils.file_io` — so ``file:``/``hdfs:``/``gs:``/``s3:`` URIs all
work once :func:`utils.arrow_fs.register_arrow_filesystem` has run — and
each host reads a **disjoint, deterministic, size-balanced** subset of the
shards derived from ``(process_id, num_processes)``.  Record batches then
stream through the existing staged host pipeline (transform pool -> DRAM
cache tier -> device-ahead staging) with epoch reshuffle at shard
granularity and the InfeedWait/InputBound telemetry intact.

Entry points::

    fs = FeatureSet.from_dataset("hdfs://warehouse/clicks", label_col="y")
    model = NNEstimator(net, "mse").fit("file:///data/train_parquet")

Under ``zoo-launch --hosts N`` every process computes the same assignment
from the same sorted listing, so no coordination is needed to agree on
who reads what.
"""

from __future__ import annotations

import heapq
import logging
import os
import posixpath
from typing import Dict, List, NamedTuple, Optional, Sequence

import numpy as np

from ..utils import file_io
from .feature_set import ShardedFileFeatureSet

logger = logging.getLogger("analytics_zoo_tpu.feature")

#: shard file extensions recognized during directory discovery, in the
#: order the reference ecosystem emits them (Spark parquet part files,
#: arrow/feather IPC, the rebuild's own npz spill shards, csv exports)
SHARD_EXTENSIONS = (".parquet", ".pq", ".arrow", ".feather", ".npz", ".csv")


class DatasetShard(NamedTuple):
    """One discovered shard file: URI + size in bytes (0 if unknown)."""

    path: str
    size: int


def discover_shards(uri: str,
                    extensions: Sequence[str] = SHARD_EXTENSIONS
                    ) -> List[DatasetShard]:
    """List the shard files of a dataset URI, sorted by name.

    ``uri`` may be a single shard file or a directory of them.  Hidden
    entries and Spark/Hadoop markers (``_SUCCESS``, ``.crc``, anything
    ``_``/``.``-prefixed) are skipped.  The listing is sorted so every
    host that can see the same store derives the same shard order — the
    precondition for coordination-free assignment.
    """
    uri = uri.rstrip("/")
    if not file_io.exists(uri):
        raise FileNotFoundError(f"dataset uri does not exist: {uri}")
    lower = uri.lower()
    if any(lower.endswith(ext) for ext in extensions):
        return [DatasetShard(uri, file_size(uri))]
    names = [n for n in file_io.listdir(uri)
             if not n.startswith(("_", "."))
             and any(n.lower().endswith(ext) for ext in extensions)]
    shards = [DatasetShard(f"{uri}/{n}", 0) for n in sorted(names)]
    if not shards:
        raise ValueError(
            f"no dataset shards under {uri!r}: expected files with one of "
            f"{list(extensions)} (Spark-style partitioned directory or a "
            f"single shard file)")
    return [DatasetShard(s.path, file_size(s.path)) for s in shards]


def file_size(uri: str) -> int:
    """Size in bytes through the file_io seam; 0 when the backing
    filesystem cannot answer (assignment then falls back to counts)."""
    try:
        return file_io.file_size(uri)
    except Exception:  # noqa: BLE001 - size is a balance hint only
        return 0


def assign_shards(sizes: Sequence[int],
                  num_processes: int) -> List[List[int]]:
    """Deterministic, disjoint, size-balanced shard assignment.

    Greedy LPT: visit shards largest-first (ties broken by index) and
    give each to the currently lightest-loaded host (ties broken by
    host id).  Guarantees:

    - **disjoint + covering**: every shard index appears in exactly one
      host's list;
    - **deterministic**: a pure function of ``(sizes, num_processes)`` —
      every host computes the same answer with no coordination;
    - **balanced within one shard**: max and min host loads differ by at
      most the largest single shard (with equal sizes, shard *counts*
      differ by at most one).

    Unknown sizes (0) are treated as equal so assignment degrades to
    balanced round-robin counts.  ``n_shards < num_processes`` leaves the
    surplus hosts with empty lists — callers decide whether that is an
    error (a training host with nothing to feed must not silently sit in
    a collective).
    """
    if num_processes < 1:
        raise ValueError(f"num_processes must be >= 1, got {num_processes}")
    sizes = [int(s) for s in sizes]
    if any(s < 0 for s in sizes):
        raise ValueError("negative shard size")
    if sizes and all(s == 0 for s in sizes):
        sizes = [1] * len(sizes)
    order = sorted(range(len(sizes)), key=lambda i: (-sizes[i], i))
    # heap of (load, shards_held, host) — shards_held keeps equal-size
    # datasets round-robin instead of piling early shards on host 0
    heap = [(0, 0, p) for p in range(num_processes)]
    heapq.heapify(heap)
    assignment: List[List[int]] = [[] for _ in range(num_processes)]
    for i in order:
        load, held, p = heapq.heappop(heap)
        assignment[p].append(i)
        heapq.heappush(heap, (load + max(sizes[i], 1), held + 1, p))
    # each host streams its shards in listing order (epoch reshuffle is a
    # seeded permutation on top, identical across runs with the same seed)
    return [sorted(a) for a in assignment]


def _default_topology() -> tuple:
    """(process_index, num_processes) — the ``zoo-launch`` env contract
    when present (valid even before jax.distributed is initialized),
    otherwise the live JAX runtime."""
    pid = os.environ.get("ZOO_TPU_PROCESS_ID")
    nproc = os.environ.get("ZOO_TPU_NUM_PROCESSES")
    if pid is not None and nproc is not None:
        return int(pid), int(nproc)
    import jax

    return jax.process_index(), jax.process_count()


class ShardedDatasetFeatureSet(ShardedFileFeatureSet):
    """A partitioned dataset directory streamed with per-host shard sets.

    Builds on :class:`ShardedFileFeatureSet` (per-shard streaming, epoch
    reshuffle at shard granularity, ``num_slice`` residency bound) but
    replaces the modulo stripe with the size-balanced
    :func:`assign_shards` plan over a *discovered* listing, and adds
    arrow IPC (`.arrow`/`.feather`) plus list-column parquet support.

    ``columns``/``label_col`` select features/label; by default every
    non-label column is a feature.  Scalar numeric columns are packed
    into one ``(n, k)`` float32 matrix; a list/tensor-valued column
    becomes its own feature tensor (stacked along the batch dim).
    """

    def __init__(self, uri: str,
                 columns: Optional[Sequence[str]] = None,
                 label_col: Optional[str] = None,
                 num_slice: int = 1,
                 process_index: Optional[int] = None,
                 num_processes: Optional[int] = None):
        shards = discover_shards(uri)
        if process_index is None or num_processes is None:
            process_index, num_processes = _default_topology()
        if not 0 <= process_index < num_processes:
            raise ValueError(
                f"process_index {process_index} out of range for "
                f"num_processes {num_processes}")
        plan = assign_shards([s.size for s in shards], num_processes)
        mine = plan[process_index]
        if not mine:
            raise ValueError(
                f"no shards for process {process_index}/{num_processes}: "
                f"dataset {uri!r} has only {len(shards)} shard(s); "
                f"repartition it into >= {num_processes} files (one per "
                f"host) or launch fewer hosts")
        super().__init__([shards[i].path for i in mine],
                         num_slice=num_slice, columns=columns,
                         label_col=label_col, shard_per_host=False)
        self.uri = uri
        self.process_index = process_index
        self.num_processes = num_processes
        self.all_shards = shards
        self.local_shards = [posixpath.basename(shards[i].path)
                             for i in mine]
        local_bytes = sum(shards[i].size for i in mine)
        logger.info(
            "dataset %s: process %d/%d assigned %d/%d shards (%s; %.1f MB "
            "of %.1f MB)", uri, process_index, num_processes, len(mine),
            len(shards), ",".join(self.local_shards), local_bytes / 1e6,
            sum(s.size for s in shards) / 1e6)

    def _load_shard(self, path: str) -> Dict[str, np.ndarray]:
        lower = path.lower()
        if lower.endswith((".parquet", ".pq")):
            import io as _io

            import pyarrow.parquet as pq

            table = pq.read_table(_io.BytesIO(file_io.read_bytes(path)))
            return self._table_to_arrays(table)
        if lower.endswith((".arrow", ".feather")):
            import io as _io

            import pyarrow as pa

            buf = _io.BytesIO(file_io.read_bytes(path))
            try:
                table = pa.ipc.open_file(buf).read_all()
            except pa.ArrowInvalid:
                buf.seek(0)  # stream-format IPC (and feather v1) fallback
                import pyarrow.feather as feather
                table = feather.read_table(buf)
            return self._table_to_arrays(table)
        return super()._load_shard(path)  # npz / csv

    def _table_to_arrays(self, table) -> Dict[str, np.ndarray]:
        """pyarrow Table -> the DiskFeatureSet ``{'x0'.., 'y0'}`` layout.

        Scalar numeric columns merge (in schema order) into one float32
        matrix; list-valued columns each become a stacked tensor of their
        own so image/sequence features survive ingestion.
        """
        cols = list(self.columns) if self.columns else \
            [c for c in table.column_names if c != self.label_col]
        missing = [c for c in cols if c not in table.column_names]
        if missing:
            raise ValueError(
                f"columns {missing} not in dataset (has "
                f"{table.column_names})")
        scalars: List[np.ndarray] = []
        tensors: List[np.ndarray] = []
        for c in cols:
            a = table.column(c).to_numpy(zero_copy_only=False)
            if a.dtype == object:  # list<...> column: per-row tensors
                tensors.append(np.stack(
                    [np.asarray(v, np.float32) for v in a]))
            else:
                scalars.append(np.asarray(a, np.float32))
        xs: List[np.ndarray] = []
        if scalars:
            xs.append(scalars[0][:, None] if len(scalars) == 1
                      else np.stack(scalars, axis=1))
        xs.extend(tensors)
        if not xs:
            raise ValueError(f"no feature columns selected from {cols}")
        out = {f"x{i}": a for i, a in enumerate(xs)}
        if self.label_col is not None and \
                self.label_col in table.column_names:
            y = table.column(self.label_col).to_numpy(zero_copy_only=False)
            if y.dtype == object:
                y = np.stack([np.asarray(v, np.float32) for v in y])
            out["y0"] = y
        return out


def write_parquet_shards(uri: str, features: np.ndarray,
                         labels: Optional[np.ndarray] = None,
                         num_shards: int = 8,
                         feature_prefix: str = "f",
                         label_col: str = "label") -> List[str]:
    """Write ``(features, labels)`` as a partitioned parquet directory —
    the fixture-side helper for smokes/tests and the inverse of
    :func:`discover_shards` (scalar feature columns ``f0..fK``)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    features = np.asarray(features)
    if features.ndim == 1:
        features = features[:, None]
    n = features.shape[0]
    file_io.makedirs(uri)
    bounds = np.linspace(0, n, num_shards + 1).astype(int)
    paths = []
    for s in range(num_shards):
        lo, hi = bounds[s], bounds[s + 1]
        cols = {f"{feature_prefix}{j}": features[lo:hi, j]
                for j in range(features.shape[1])}
        if labels is not None:
            cols[label_col] = np.asarray(labels)[lo:hi]
        table = pa.table(cols)
        path = f"{uri.rstrip('/')}/part-{s:05d}.parquet"
        import io as _io

        buf = _io.BytesIO()
        pq.write_table(table, buf)
        file_io.write_bytes(path, buf.getvalue())
        paths.append(path)
    return paths
