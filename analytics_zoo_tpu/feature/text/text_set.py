"""TextSet: text corpus abstraction with the tokenize→normalize→word2idx→
shapeSequence→sample pipeline and relation (ranking) dataset builders.

Parity: ``zoo/.../feature/text/TextSet.scala:43-247`` (read:290,
readCSV:345, readParquet:372, fromRelationPairs:399, fromRelationLists:503)
and ``pyzoo/zoo/feature/text/text_set.py``.

TPU design: local in-memory corpus; "distributed" = per-host shard (see
image_set.py). Word-index generation is a host-side pass; samples feed the
FeatureSet prefetcher.
"""

from __future__ import annotations

import csv
import os
from collections import Counter
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..common import Relation, Relations
from ..feature_set import ArrayFeatureSet, FeatureSet
from .text_feature import TextFeature
from .transformer import (Normalizer, SequenceShaper, TextFeatureToSample,
                          Tokenizer, WordIndexer)


class TextSet:
    def __init__(self, features: List[TextFeature]):
        self.features = features
        self.word_index: Optional[Dict[str, int]] = None

    # -- factories -----------------------------------------------------
    @classmethod
    def array(cls, features: Sequence[TextFeature]) -> "LocalTextSet":
        return LocalTextSet(list(features))

    @classmethod
    def read(cls, path: str, shard_index: int = 0,
             num_shards: int = 1) -> "TextSet":
        """Read a folder whose immediate sub-dirs are category names, each
        containing text files (TextSet.scala:290-330). Labels are
        zero-based sorted category indices."""
        cats = sorted(d for d in os.listdir(path)
                      if os.path.isdir(os.path.join(path, d)))
        feats = []
        for label, cat in enumerate(cats):
            for fn in sorted(os.listdir(os.path.join(path, cat))):
                fp = os.path.join(path, cat, fn)
                if not os.path.isfile(fp):
                    continue
                with open(fp, encoding="utf-8", errors="ignore") as f:
                    feats.append(TextFeature(f.read(), label, uri=fp))
        feats = feats[shard_index::num_shards]
        return LocalTextSet(feats) if num_shards == 1 else \
            DistributedTextSet(feats, shard_index, num_shards)

    @classmethod
    def read_csv(cls, path: str) -> "LocalTextSet":
        """csv rows uri,text (TextSet.scala:345)."""
        feats = []
        with open(path, newline="", encoding="utf-8") as f:
            for row in csv.reader(f):
                if len(row) >= 2:
                    feats.append(TextFeature(row[1], uri=row[0]))
        return LocalTextSet(feats)

    @classmethod
    def read_parquet(cls, path: str) -> "LocalTextSet":
        import pyarrow.parquet as pq

        d = pq.read_table(path).to_pydict()
        return LocalTextSet([TextFeature(t, uri=str(u))
                             for u, t in zip(d["uri"], d["text"])])

    # -- relation builders (ranking) ------------------------------------
    @classmethod
    def from_relation_pairs(cls, relations: Sequence[Relation],
                            corpus1: "TextSet", corpus2: "TextSet",
                            seed: Optional[int] = 0) -> "LocalTextSet":
        """Pairwise training set (TextSet.scala:399-483): for each relation
        pair, feature is the (2, len1+len2) stack of [text1 ++ text2_pos]
        and [text1 ++ text2_neg], label [[1], [0]]."""
        map1 = corpus1._indices_by_uri("corpus1")
        map2 = corpus2._indices_by_uri("corpus2")
        pairs = Relations.generate_relation_pairs(relations, seed)
        feats = []
        for p in pairs:
            i1 = map1[p.id1]
            pos, neg = map2[p.id2_positive], map2[p.id2_negative]
            assert len(pos) == len(neg), \
                "corpus2 contains texts with different lengths, please " \
                "shape_sequence first"
            feature = np.stack([np.concatenate([i1, pos]),
                                np.concatenate([i1, neg])]).astype(np.float32)
            tf = TextFeature(uri=p.id1 + p.id2_positive + p.id2_negative)
            from ..feature_set import Sample
            tf[TextFeature.sample] = Sample(
                feature, np.array([[1.0], [0.0]], np.float32))
            feats.append(tf)
        return LocalTextSet(feats)

    @classmethod
    def from_relation_lists(cls, relations: Sequence[Relation],
                            corpus1: "TextSet",
                            corpus2: "TextSet") -> "LocalTextSet":
        """Listwise evaluation set (TextSet.scala:503-560): one TextFeature
        per id1 with feature (listLength, len1+len2) and label
        (listLength, 1)."""
        map1 = corpus1._indices_by_uri("corpus1")
        map2 = corpus2._indices_by_uri("corpus2")
        by_id1: Dict[str, List[Relation]] = {}
        for r in relations:
            by_id1.setdefault(r.id1, []).append(r)
        feats = []
        from ..feature_set import Sample
        for id1, rels in by_id1.items():
            i1 = map1[id1]
            rows = [np.concatenate([i1, map2[r.id2]]) for r in rels]
            labels = np.array([[float(r.label)] for r in rels], np.float32)
            tf = TextFeature(uri=id1 + "".join(r.id2 for r in rels))
            tf[TextFeature.sample] = Sample(
                np.stack(rows).astype(np.float32), labels)
            feats.append(tf)
        return LocalTextSet(feats)

    def _indices_by_uri(self, name: str) -> Dict[str, np.ndarray]:
        out = {}
        for f in self.features:
            idx = f.get_indices()
            assert idx is not None, \
                f"{name} hasn't been transformed from word to index yet, " \
                "please word2idx first"
            out[f.get_uri()] = idx
        return out

    # -- surface -------------------------------------------------------
    def is_local(self):
        return isinstance(self, LocalTextSet)

    def is_distributed(self):
        return isinstance(self, DistributedTextSet)

    def to_local(self):
        ts = LocalTextSet(self.features)
        ts.word_index = self.word_index
        return ts

    def to_distributed(self, shard_index=0, num_shards=1):
        ts = DistributedTextSet(self.features, shard_index, num_shards)
        ts.word_index = self.word_index
        return ts

    def transform(self, transformer, num_workers: int = 0) -> "TextSet":
        """Apply a text transformer to every feature. ``num_workers > 0``
        runs it on an ordered thread pool (``ZOO_TPU_TRANSFORM_WORKERS``
        sets the default) — worthwhile for chains that release the GIL or
        do numpy-heavy shaping on large corpora."""
        if num_workers == 0:
            env = os.environ.get("ZOO_TPU_TRANSFORM_WORKERS")
            if env:
                num_workers = int(env)
        if num_workers and num_workers > 0 and len(self.features) > 1:
            from concurrent.futures import ThreadPoolExecutor
            with ThreadPoolExecutor(max_workers=num_workers,
                                    thread_name_prefix="zoo-text") as pool:
                self.features = list(pool.map(transformer.apply,
                                              self.features))
        else:
            self.features = [transformer.apply(f) for f in self.features]
        return self

    def get_texts(self):
        return [f.get_text() for f in self.features]

    def get_uris(self):
        return [f.get_uri() for f in self.features]

    def get_labels(self):
        return [f.get_label() for f in self.features]

    def get_predicts(self):
        return [(f.get_uri(), f.get_predict()) for f in self.features]

    def get_samples(self):
        return [f.get_sample() for f in self.features]

    def random_split(self, weights: Sequence[float], seed: int = 0):
        rng = np.random.default_rng(seed)
        idx = rng.permutation(len(self.features))
        total = float(sum(weights))
        out, start = [], 0
        for w in weights[:-1]:
            n = int(len(idx) * w / total)
            out.append([self.features[i] for i in idx[start:start + n]])
            start += n
        out.append([self.features[i] for i in idx[start:]])
        sets = []
        for chunk in out:
            ts = type(self)(chunk)
            ts.word_index = self.word_index
            sets.append(ts)
        return sets

    def __len__(self):
        return len(self.features)

    # -- pipeline sugar (TextSet.scala:120-247) -------------------------
    def tokenize(self) -> "TextSet":
        return self.transform(Tokenizer())

    def normalize(self) -> "TextSet":
        return self.transform(Normalizer())

    def word2idx(self, remove_topN: int = 0, max_words_num: int = -1,
                 min_freq: int = 1,
                 existing_map: Optional[Dict[str, int]] = None) -> "TextSet":
        self.generate_word_index_map(remove_topN, max_words_num, min_freq,
                                     existing_map)
        return self.transform(WordIndexer(self.word_index))

    def shape_sequence(self, len: int, trunc_mode: str = "pre",
                       pad_element: int = 0) -> "TextSet":
        return self.transform(SequenceShaper(len, trunc_mode, pad_element))

    def generate_sample(self) -> "TextSet":
        return self.transform(TextFeatureToSample())

    def generate_word_index_map(self, remove_topN: int = 0,
                                max_words_num: int = -1, min_freq: int = 1,
                                existing_map: Optional[Dict[str, int]] = None
                                ) -> Dict[str, int]:
        """Frequency-ranked word index starting from 1 (0 = OOV), with
        optional head removal / cap / frequency floor
        (TextSet.scala:125-186)."""
        counter: Counter = Counter()
        for f in self.features:
            tokens = f.get_tokens()
            assert tokens is not None, "please tokenize first"
            counter.update(tokens)
        freq = [(w, c) for w, c in counter.most_common() if c >= min_freq]
        freq = freq[remove_topN:]
        if max_words_num > 0:
            freq = freq[:max_words_num]
        index = dict(existing_map) if existing_map else {}
        next_idx = max(index.values()) + 1 if index else 1
        for w, _ in freq:
            if w not in index:
                index[w] = next_idx
                next_idx += 1
        self.word_index = index
        return index

    def get_word_index(self) -> Optional[Dict[str, int]]:
        return self.word_index

    def set_word_index(self, vocab: Dict[str, int]) -> "TextSet":
        self.word_index = vocab
        return self

    def save_word_index(self, path: str):
        assert self.word_index, "word_index not generated yet"
        with open(path, "w", encoding="utf-8") as f:
            for w, i in self.word_index.items():
                f.write(f"{w} {i}\n")

    def load_word_index(self, path: str) -> "TextSet":
        index = {}
        with open(path, encoding="utf-8") as f:
            for line in f:
                parts = line.rsplit(" ", 1)
                if len(parts) == 2:
                    index[parts[0]] = int(parts[1])
        self.word_index = index
        return self

    # -- to training data ----------------------------------------------
    def to_feature_set(self) -> FeatureSet:
        samples = self.get_samples()
        assert all(s is not None for s in samples), \
            "please generate_sample first"
        return FeatureSet.samples(samples)

    to_dataset = to_feature_set


class LocalTextSet(TextSet):
    pass


class DistributedTextSet(TextSet):
    def __init__(self, features, shard_index: int = 0, num_shards: int = 1):
        super().__init__(features)
        self.shard_index = int(shard_index)
        self.num_shards = int(num_shards)
