"""Preprocessing chains + sample adapters + relations.

Parity: ``zoo/.../feature/common/*.scala`` (Preprocessing.scala:82 ``->``
composition, adapters in FeatureLabelPreprocessing/ToTuple/...,
Relations.scala) and ``pyzoo/zoo/feature/common.py``.

TPU design: preprocessing is host-side numpy — it runs in the prefetch
thread(s) off the device hot path; a chain is a plain function composition,
not a serialized JVM transformer graph.
"""

from __future__ import annotations

import csv
import random
from dataclasses import dataclass
from typing import Any, Callable, Iterable, List, Optional, Sequence

import numpy as np

from .feature_set import MiniBatch, Sample


class Preprocessing:
    """Composable transformer: ``(a >> b)(x) == b(a(x))``.

    Parity: ``Preprocessing[A, B]`` with ``->`` composition
    (feature/common/Preprocessing.scala:82). Subclasses implement
    ``apply(x)`` (one element). ``__call__`` on an iterator maps lazily.
    """

    #: Declares the chain dominated by GIL-holding Python compute (pure-
    #: Python loops, PIL decode, ...) rather than GIL-releasing numpy
    #: kernels. The ``auto`` infeed backend moves such chains out of
    #: process (host_pipeline.resolve_infeed_backend); numpy-dominated
    #: chains stay on threads, where the hand-off is cheaper.
    cpu_bound = False

    def apply(self, x):
        raise NotImplementedError(type(self).__name__)

    def __call__(self, x):
        # Only true iterators/generators are mapped lazily; plain lists are
        # single elements (SeqToTensor([1,2,3]) must yield one tensor).
        if hasattr(x, "__next__"):
            return (self.apply(e) for e in x)
        return self.apply(x)

    def __rshift__(self, other: "Preprocessing") -> "ChainedPreprocessing":
        return ChainedPreprocessing([self, other])

    # alias matching the scala operator name in docs
    def and_then(self, other):
        return self >> other


class ChainedPreprocessing(Preprocessing):
    """Parity: ChainedPreprocessing (pyzoo feature/common.py)."""

    def __init__(self, transformers: Sequence[Preprocessing]):
        flat: List[Preprocessing] = []
        for t in transformers:
            if isinstance(t, ChainedPreprocessing):
                flat.extend(t.transformers)
            else:
                flat.append(t)
        self.transformers = flat

    @property
    def cpu_bound(self):  # type: ignore[override]
        return any(getattr(t, "cpu_bound", False) for t in self.transformers)

    def apply(self, x):
        for t in self.transformers:
            x = t.apply(x)
        return x


class LambdaPreprocessing(Preprocessing):
    def __init__(self, fn: Callable, cpu_bound: bool = False):
        self.fn = fn
        self.cpu_bound = cpu_bound

    def apply(self, x):
        return self.fn(x)


class ScalarToTensor(Preprocessing):
    def apply(self, x):
        return np.asarray(x, np.float32).reshape(())


class SeqToTensor(Preprocessing):
    """A sequence of numbers -> ndarray of given size (SeqToTensor.scala)."""

    def __init__(self, size: Optional[Sequence[int]] = None):
        self.size = None if size is None else tuple(int(s) for s in size)

    def apply(self, x):
        arr = np.asarray(x, np.float32)
        if self.size:
            arr = arr.reshape(self.size)
        return arr


class SeqToMultipleTensors(Preprocessing):
    """Splits a flat sequence into several tensors of the given sizes."""

    def __init__(self, sizes: Sequence[Sequence[int]]):
        self.sizes = [tuple(int(s) for s in sz) for sz in sizes]

    def apply(self, x):
        arr = np.asarray(x, np.float32).reshape(-1)
        outs, off = [], 0
        for sz in self.sizes:
            n = int(np.prod(sz))
            outs.append(arr[off:off + n].reshape(sz))
            off += n
        return outs


class ArrayToTensor(Preprocessing):
    def __init__(self, size: Optional[Sequence[int]] = None):
        self.size = None if size is None else tuple(int(s) for s in size)

    def apply(self, x):
        arr = np.asarray(x, np.float32)
        if self.size:
            arr = arr.reshape(self.size)
        return arr


class MLlibVectorToTensor(Preprocessing):
    """Accepts anything exposing ``toArray`` (pyspark/MLlib vectors) or a
    plain sequence (MLlibVectorToTensor.scala)."""

    def __init__(self, size: Optional[Sequence[int]] = None):
        self.size = None if size is None else tuple(int(s) for s in size)

    def apply(self, x):
        arr = np.asarray(x.toArray() if hasattr(x, "toArray") else x,
                         np.float32)
        if self.size:
            arr = arr.reshape(self.size)
        return arr


class TensorToSample(Preprocessing):
    def apply(self, x):
        return Sample(x)


class FeatureLabelPreprocessing(Preprocessing):
    """Applies a feature chain and a label chain to a (feature, label) pair
    and produces a Sample (FeatureLabelTransformer.scala)."""

    def __init__(self, feature_preprocessing: Preprocessing,
                 label_preprocessing: Optional[Preprocessing] = None):
        self.feature_preprocessing = feature_preprocessing
        self.label_preprocessing = label_preprocessing

    def apply(self, x):
        feat, label = x
        f = self.feature_preprocessing.apply(feat)
        lbl = None
        if label is not None:
            lbl = self.label_preprocessing.apply(label) \
                if self.label_preprocessing else np.asarray(label, np.float32)
        return Sample(f, lbl)


class ToTuple(Preprocessing):
    """feature -> (feature, None) (ToTuple.scala)."""

    def apply(self, x):
        return (x, None)


class FeatureToTupleAdapter(Preprocessing):
    def __init__(self, preprocessing: Preprocessing):
        self.preprocessing = preprocessing

    def apply(self, x):
        return (self.preprocessing.apply(x[0]), x[1])


class BigDLAdapter(Preprocessing):
    """Parity shim: wraps any callable as a Preprocessing."""

    def __init__(self, transformer):
        self.transformer = transformer

    def apply(self, x):
        return self.transformer(x)


class SampleToMiniBatch(Preprocessing):
    """Batches an iterable of Samples into MiniBatches. Parity:
    ``MTSampleToMiniBatch`` (feature/common/MTSampleToMiniBatch.scala) —
    the multi-threading moves to the FeatureSet prefetcher."""

    def __init__(self, batch_size: int, drop_remainder: bool = False):
        self.batch_size = int(batch_size)
        self.drop_remainder = drop_remainder

    def apply(self, x):
        raise TypeError("SampleToMiniBatch operates on iterators; "
                        "call it, don't apply it")

    def __call__(self, samples: Iterable[Sample]):
        buf: List[Sample] = []
        for s in samples:
            buf.append(s)
            if len(buf) == self.batch_size:
                yield self._stack(buf)
                buf = []
        if buf and not self.drop_remainder:
            yield self._stack(buf)

    @staticmethod
    def _stack(buf: List[Sample]):
        from .feature_set import stack_samples

        xs, ys = stack_samples(buf)
        return MiniBatch(xs, ys, np.ones(len(buf), np.float32))


# ---------------------------------------------------------------------------
# Relations (QA ranking datasets) — feature/common/Relations.scala
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Relation:
    id1: str
    id2: str
    label: int


@dataclass(frozen=True)
class RelationPair:
    """id1 with one positive and one negative id2."""

    id1: str
    id2_positive: str
    id2_negative: str


class Relations:
    @staticmethod
    def read(path: str) -> List[Relation]:
        """Reads relations from csv (columns id1,id2,label, with or without
        header) or parquet (Relations.scala:40-76)."""
        if path.endswith(".parquet"):
            return Relations.read_parquet(path)
        out = []
        with open(path, newline="", encoding="utf-8") as f:
            rows = list(csv.reader(f))
        if rows and rows[0][:3] in (["id1", "id2", "label"],):
            rows = rows[1:]
        for r in rows:
            if len(r) < 3:
                continue
            out.append(Relation(r[0], r[1], int(float(r[2]))))
        return out

    @staticmethod
    def read_parquet(path: str) -> List[Relation]:
        import pyarrow.parquet as pq

        tbl = pq.read_table(path)
        d = tbl.to_pydict()
        return [Relation(str(a), str(b), int(c))
                for a, b, c in zip(d["id1"], d["id2"], d["label"])]

    @staticmethod
    def generate_relation_pairs(relations: Sequence[Relation],
                                seed: Optional[int] = None
                                ) -> List[RelationPair]:
        """For each id1, pair every positive id2 with a random negative id2
        (Relations.scala:80-112)."""
        rng = random.Random(seed)
        by_id1: dict = {}
        for r in relations:
            pos, neg = by_id1.setdefault(r.id1, ([], []))
            (pos if r.label > 0 else neg).append(r.id2)
        pairs = []
        for id1, (pos, neg) in by_id1.items():
            if not neg:
                continue
            for p in pos:
                pairs.append(RelationPair(id1, p, rng.choice(neg)))
        return pairs
