"""Staged host input pipeline: transform pool -> prefetch -> device staging.

Reference analogue: ``MTSampleToMiniBatch`` (multi-threaded batch assembly)
plus the FeatureSet DRAM tier kept the JVM side of the infeed busy; the TPU
rebuild stages the host side as three decoupled layers so the compiled step
never waits on input:

1. ``ParallelTransformIterator`` — an ordered, bounded-in-flight thread pool
   running the Preprocessing chain for several batches concurrently
   (``ZooConfig.transform_workers``).
2. ``PrefetchIterator`` (feature_set.py) — a background thread that keeps
   ``prefetch_depth`` transformed batches queued on the host.
3. ``DeviceStagingIterator`` — keeps up to ``device_ahead`` dispatch chunks
   already ``jax.device_put`` onto the mesh data sharding, so the H2D copy
   of batch N+1 overlaps the device compute of batch N (device_put is
   async-dispatch: staging costs host time only for the numpy stacking).

All host-side blocking is accounted into an ``InfeedMonitor`` so the engine
can emit input-wait and input-bound-fraction telemetry per logging window.
"""

from __future__ import annotations

import logging
import os
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Iterator, List, Optional, Tuple

import time

import numpy as np

from .feature_set import (FeatureSet, MiniBatch, PrefetchIterator,
                          TransformedFeatureSet, minibatch_len,
                          register_pipeline)

logger = logging.getLogger("analytics_zoo_tpu.feature")


class ParallelTransformIterator:
    """Ordered multi-worker transform pool with bounded in-flight batches.

    Pulls raw batches from ``base_it`` on the consumer thread (the base
    generator is never touched from pool threads), submits ``fn(batch)``
    to a thread pool, and yields results in submission order. At most
    ``num_workers + 2`` batches are in flight, bounding host RAM while
    keeping every worker busy. A worker exception is re-raised on the
    very next ``__next__`` for the failed batch's position.
    """

    def __init__(self, base_it: Iterator, fn: Callable[[Any], Any],
                 num_workers: int = 2, max_in_flight: Optional[int] = None):
        self._base = iter(base_it)
        self._fn = fn
        self.num_workers = max(1, int(num_workers))
        self._pool = ThreadPoolExecutor(
            max_workers=self.num_workers,
            thread_name_prefix="zoo-transform")
        self._futures: deque = deque()
        self._max_in_flight = max_in_flight or self.num_workers + 2
        self._exhausted = False
        self._closed = False
        register_pipeline(self)
        self._fill()

    def _fill(self):
        while not self._exhausted and \
                len(self._futures) < self._max_in_flight:
            try:
                item = next(self._base)
            except StopIteration:
                self._exhausted = True
                break
            self._futures.append(self._pool.submit(self._fn, item))

    def __iter__(self):
        return self

    def __next__(self):
        if self._closed:
            raise StopIteration
        if not self._futures:
            self.close()
            raise StopIteration
        fut = self._futures.popleft()
        try:
            out = fut.result()
        except BaseException:
            self.close()
            raise
        self._fill()
        return out

    def close(self):
        if self._closed:
            return
        self._closed = True
        for f in self._futures:
            f.cancel()
        self._futures.clear()
        self._pool.shutdown(wait=False)
        base_close = getattr(self._base, "close", None)
        if base_close is not None:
            base_close()


class StagedChunk:
    """One dispatch unit handed to the engine.

    ``stacked`` is the (k, batch, ...) device super-batch when the chunk
    filled a full fused dispatch (engine runs the k-step scan program);
    otherwise ``singles`` holds per-batch device batches (engine reuses
    the single-step program — epoch tails and k == 1). ``hosts`` keeps
    the pre-put host copies so a k-change can restage without re-reading
    the input pipeline, and so predict() can count real samples.
    """

    __slots__ = ("k", "stacked", "singles", "hosts")

    def __init__(self, k: int, stacked, singles, hosts: List[MiniBatch]):
        self.k = k
        self.stacked = stacked
        self.singles = singles
        self.hosts = hosts

    @property
    def real_counts(self) -> List[int]:
        """Per-batch count of real (non-padding) samples: zero-weight rows
        are the pad_remainder filler; weight-less batches are all real.
        Lets evaluate()/predict() unpad fused outputs without touching the
        device copies."""
        counts = []
        for h in self.hosts:
            w = h.weights
            counts.append(minibatch_len(h) if w is None else
                          int(np.sum(np.asarray(w) > 0)))
        return counts


class DeviceStagingIterator:
    """Keeps up to ``depth`` dispatch chunks already on the device mesh.

    ``put_one`` / ``put_stacked`` are the engine's placement rules
    (``_put_batch`` / ``_put_stacked``) — pad to the dp multiple, lay the
    batch axis over the data sharding — so staged batches are laid out
    exactly as the compiled step expects. ``next_chunk(k)`` recomputes
    per call: the engine's fused dispatch size can shrink at trigger
    boundaries, in which case already-staged chunks are dissolved back
    into the pending host queue (order preserved) and restaged at the
    new k; the dropped device copies are the cost of a rare event.
    """

    def __init__(self, host_it: Iterator[MiniBatch],
                 put_one: Callable[[MiniBatch], Any],
                 put_stacked: Callable[[List[MiniBatch]], Any],
                 depth: int = 2, monitor=None):
        self._host_it = iter(host_it)
        self._put_one = put_one
        self._put_stacked = put_stacked
        self.depth = max(1, int(depth))
        self.monitor = monitor
        self._staged: deque = deque()       # StagedChunk, oldest first
        self._pending: deque = deque()      # host batches awaiting staging
        self._eof = False
        register_pipeline(self)

    def _fetch_host(self) -> Optional[MiniBatch]:
        if self._pending:
            return self._pending.popleft()
        if self._eof:
            return None
        t0 = time.perf_counter()
        try:
            hb = next(self._host_it)
        except StopIteration:
            self._eof = True
            return None
        finally:
            if self.monitor is not None:
                self.monitor.input_wait(time.perf_counter() - t0)
        return hb

    def _stage_one(self, k: int) -> bool:
        hosts: List[MiniBatch] = []
        while len(hosts) < k:
            hb = self._fetch_host()
            if hb is None:
                break
            hosts.append(hb)
        if not hosts:
            return False
        # a full chunk stacks into the (k, batch, ...) super-batch only
        # when every batch has the same length: a non-dropped, non-padded
        # remainder (drop_remainder=False, pad_remainder=False) lands mid-
        # chunk with a shorter batch axis and must take the singles path
        # rather than np.stack raising
        uniform = len({minibatch_len(h) for h in hosts}) == 1
        if k > 1 and len(hosts) == k and uniform:
            # stacking needs one tree structure across the chunk: a padded
            # remainder carries a weights array while full batches carry
            # None — materialize ones (the semantic equivalent of None)
            # so the stacked super-batch has a single treedef
            if any(h.weights is not None for h in hosts) and \
                    not all(h.weights is not None for h in hosts):
                hosts = [h if h.weights is not None else
                         MiniBatch(h.inputs, h.targets,
                                   np.ones(minibatch_len(h), np.float32))
                         for h in hosts]
            chunk = StagedChunk(k, self._put_stacked(hosts), None, hosts)
        else:
            chunk = StagedChunk(
                k, None, [self._put_one(h) for h in hosts], hosts)
        self._staged.append(chunk)
        return True

    def _restage(self, k: int):
        """Dispatch size changed: return staged hosts to the front of the
        pending queue in original order and drop their device copies."""
        while self._staged:
            chunk = self._staged.pop()
            self._pending.extendleft(reversed(chunk.hosts))

    def next_chunk(self, k: int) -> Optional[StagedChunk]:
        if self._staged and self._staged[0].k != k:
            self._restage(k)
        while len(self._staged) < self.depth:
            if not self._stage_one(k):
                break
        if not self._staged:
            return None
        return self._staged.popleft()

    def __iter__(self):
        """k == 1 convenience stream (evaluate/predict): yields
        (device_batch, host_batch) pairs."""
        while True:
            chunk = self.next_chunk(1)
            if chunk is None:
                return
            yield chunk.singles[0], chunk.hosts[0]

    def close(self):
        self._staged.clear()
        self._pending.clear()
        host_close = getattr(self._host_it, "close", None)
        if host_close is not None:
            host_close()


def resolve_transform_workers(transform_workers: int) -> int:
    """Resolve the transform-pool size: >= 0 is taken literally (0 =
    serial in the prefetch thread); negative means auto — size the
    decode/transform pool from the host core count so the host half can
    keep pace with the model's consumption rate. The auto pool is
    clamped to [2, 8]: below 2 a single worker cannot hide per-batch
    transform latency behind the device step, above 8 the ordered
    hand-off queue is the bottleneck, not the pool."""
    if transform_workers >= 0:
        return int(transform_workers)
    return max(2, min(8, os.cpu_count() or 2))


def build_host_pipeline(fs: FeatureSet, batch_size: int, *,
                        shuffle: bool = False, drop_remainder: bool = True,
                        pad_remainder: bool = False, seed: int = 0,
                        transform_workers: int = -1,
                        prefetch_depth: int = 2) -> PrefetchIterator:
    """Host half of the staged pipeline: (parallel) transform + prefetch.

    Returns a closeable iterator of host MiniBatches; wrap it in a
    ``DeviceStagingIterator`` for the device half. ``transform_workers``
    only applies when ``fs`` carries a Preprocessing chain
    (TransformedFeatureSet); raw array slicing is already cheap. The
    default (-1) auto-sizes the pool from the host core count
    (:func:`resolve_transform_workers`).
    """
    transform_workers = resolve_transform_workers(transform_workers)
    kw = dict(shuffle=shuffle, drop_remainder=drop_remainder,
              pad_remainder=pad_remainder, seed=seed)
    if transform_workers > 0 and isinstance(fs, TransformedFeatureSet):
        it = fs.batches(batch_size, num_workers=transform_workers, **kw)
    else:
        it = fs.batches(batch_size, **kw)
    return PrefetchIterator(it, depth=prefetch_depth)
