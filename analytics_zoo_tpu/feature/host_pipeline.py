"""Staged host input pipeline: transform pool -> prefetch -> device staging.

Reference analogue: ``MTSampleToMiniBatch`` (multi-threaded batch assembly)
plus the FeatureSet DRAM tier kept the JVM side of the infeed busy; the TPU
rebuild stages the host side as three decoupled layers so the compiled step
never waits on input:

1. ``ParallelTransformIterator`` — an ordered, bounded-in-flight thread pool
   running the Preprocessing chain for several batches concurrently
   (``ZooConfig.transform_workers``).
2. ``PrefetchIterator`` (feature_set.py) — a background thread that keeps
   ``prefetch_depth`` transformed batches queued on the host.
3. ``DeviceStagingIterator`` — keeps up to ``device_ahead`` dispatch chunks
   already ``jax.device_put`` onto the mesh data sharding, so the H2D copy
   of batch N+1 overlaps the device compute of batch N (device_put is
   async-dispatch: staging costs host time only for the numpy stacking).

All host-side blocking is accounted into an ``InfeedMonitor`` so the engine
can emit input-wait and input-bound-fraction telemetry per logging window.
"""

from __future__ import annotations

import logging
import multiprocessing as mp
import os
import pickle
import queue as queue_mod
import threading
import weakref
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import time

import numpy as np

from ..utils import telemetry
from ..utils.telemetry import span
from .feature_set import (FeatureSet, MiniBatch, PrefetchIterator,
                          TransformedFeatureSet, minibatch_len,
                          register_pipeline)
from .infeed_worker import rebuild_batch, worker_main

logger = logging.getLogger("analytics_zoo_tpu.feature")


class ParallelTransformIterator:
    """Ordered multi-worker transform pool with bounded in-flight batches.

    Pulls raw batches from ``base_it`` on the consumer thread (the base
    generator is never touched from pool threads), submits ``fn(batch)``
    to a thread pool, and yields results in submission order. At most
    ``num_workers + 2`` batches are in flight, bounding host RAM while
    keeping every worker busy. A worker exception is re-raised on the
    very next ``__next__`` for the failed batch's position.
    """

    def __init__(self, base_it: Iterator, fn: Callable[[Any], Any],
                 num_workers: int = 2, max_in_flight: Optional[int] = None):
        self._base = iter(base_it)
        self._fn = fn
        self.num_workers = max(1, int(num_workers))
        self._pool = ThreadPoolExecutor(
            max_workers=self.num_workers,
            thread_name_prefix="zoo-transform")
        self._futures: deque = deque()
        self._max_in_flight = max_in_flight or self.num_workers + 2
        self._exhausted = False
        self._closed = False
        register_pipeline(self)
        self._fill()

    def _fill(self):
        while not self._exhausted and \
                len(self._futures) < self._max_in_flight:
            try:
                item = next(self._base)
            except StopIteration:
                self._exhausted = True
                break
            self._futures.append(self._pool.submit(self._run, item))

    def _run(self, item):
        # runs on a pool thread: the span lands on the zoo-transform
        # thread's timeline in the exported trace
        with span("infeed/transform"):
            return self._fn(item)

    def __iter__(self):
        return self

    def __next__(self):
        if self._closed:
            raise StopIteration
        if not self._futures:
            self.close()
            raise StopIteration
        fut = self._futures.popleft()
        try:
            out = fut.result()
        except BaseException:
            self.close()
            raise
        self._fill()
        return out

    def close(self):
        if self._closed:
            return
        self._closed = True
        for f in self._futures:
            f.cancel()
        self._futures.clear()
        self._pool.shutdown(wait=False)
        base_close = getattr(self._base, "close", None)
        if base_close is not None:
            base_close()


DEFAULT_SLOT_BYTES = 8 << 20    # ZOO_TPU_INFEED_SLOT_BYTES
DEFAULT_SLOTS_PER_WORKER = 4    # ZOO_TPU_INFEED_SLOTS


class _RingSegment:
    """Lifecycle of one worker's shared-memory ring.

    numpy does not pin the buffer export of the ``SharedMemory``
    memoryview, so ``shm.close()`` really unmaps even while zero-copy
    views are alive — touching them afterwards is a segfault, not an
    exception. The segment therefore refcounts outstanding batch leases:
    ``retire()`` (pool close) unlinks the name immediately — no /dev/shm
    entry survives the pool — but the unmap is deferred until the last
    consumer-held view is garbage collected.
    """

    __slots__ = ("shm", "_active", "_retired", "_lock")

    def __init__(self, shm):
        self.shm = shm
        self._active = 0
        self._retired = False
        self._lock = threading.Lock()

    def lease(self):
        with self._lock:
            self._active += 1

    def unlease(self):
        with self._lock:
            self._active -= 1
            last = self._retired and self._active == 0
        if last:
            self._unmap()

    def retire(self):
        with self._lock:
            if self._retired:
                return
            self._retired = True
            drained = self._active == 0
        try:
            self.shm.unlink()
        except Exception:  # noqa: BLE001 - already unlinked
            pass
        if drained:
            self._unmap()

    def _unmap(self):
        try:
            self.shm.close()
        except Exception:  # noqa: BLE001
            pass


class _SlotLease:
    """One leased ring slot: returned to the worker's free queue (and
    unleased from the segment) when the last zero-copy view wrapped from
    it is garbage collected."""

    __slots__ = ("free_q", "segment", "slot", "count", "lock")

    def __init__(self, free_q, segment: "_RingSegment", slot: int,
                 count: int):
        self.free_q = free_q
        self.segment = segment
        self.slot = slot
        self.count = count
        self.lock = threading.Lock()
        segment.lease()

    def release_one(self):
        with self.lock:
            self.count -= 1
            if self.count > 0:
                return
        try:
            self.free_q.put_nowait(self.slot)
        except Exception:  # noqa: BLE001 - pool torn down; segment gone
            pass
        self.segment.unlease()


class _Worker:
    """Parent-side record of one spawned transform worker. Queues and the
    ring segment outlive the process: a respawned replacement reattaches
    to the same ones, so unclaimed tasks and free slots carry over."""

    __slots__ = ("wid", "proc", "task_q", "free_q", "segment", "assigned")

    def __init__(self, wid, task_q, free_q, segment):
        self.wid = wid
        self.proc = None
        self.task_q = task_q
        self.free_q = free_q
        self.segment = segment
        self.assigned: set = set()


class _RemoteError:
    """Marks a ready-slot as a worker failure to re-raise in order."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


def _reap_pool(procs, segments):
    """close()/GC backstop: put down workers and retire every ring
    segment (unlink now, unmap when the last consumer view drops).
    Module-level — weakref.finalize must not resurrect the pool."""
    for p in procs:
        try:
            if p.is_alive():
                p.terminate()
        except Exception:  # noqa: BLE001
            pass
    for p in procs:
        try:
            p.join(timeout=1.0)
            if p.is_alive():
                p.kill()
        except Exception:  # noqa: BLE001
            pass
    for seg in segments:
        seg.retire()


class ProcessTransformPool:
    """Ordered multi-process transform pool with shared-memory hand-off.

    The iterator contract is :class:`ParallelTransformIterator`'s
    exactly — results in submission order, bounded in-flight, a worker
    failure re-raised at the failed batch's position on the very next
    ``__next__``, idempotent mid-stream ``close()`` — but the transform
    runs in N spawned processes, so GIL-holding Python chains scale with
    cores instead of serializing. Each worker returns batches through
    its own ``multiprocessing.shared_memory`` ring: the parent wraps the
    slot bytes in numpy views (zero copies on the hot path) and the slot
    recycles when the consumer drops the batch (weakref lease). Batches
    that don't fit a slot — or arrive while the consumer retains every
    lease, e.g. a caching tier — fall back to pickling through the
    result queue: slower, never wrong, never deadlocked.

    Respawn-on-death rides the launcher supervision seam
    (:class:`~analytics_zoo_tpu.launcher.supervisor.Respawner`): a
    worker killed mid-batch is restarted on the same queues + ring, its
    unacknowledged batches are resubmitted, and late duplicates are
    dropped by sequence number — the stream stays complete,
    duplicate-free and ordered. Ring segments are unlinked in
    ``close()``'s finally (plus a GC finalizer backstop): no /dev/shm
    leak survives the pool.
    """

    def __init__(self, base_it: Iterator, preprocessing,
                 num_workers: int = 2, max_in_flight: Optional[int] = None,
                 stats=None, slot_bytes: Optional[int] = None,
                 slots_per_worker: Optional[int] = None, respawner=None):
        from multiprocessing import shared_memory

        from ..launcher.supervisor import Respawner

        self._base = iter(base_it)
        self.num_workers = max(1, int(num_workers))
        self._max_in_flight = max_in_flight or self.num_workers + 2
        self._stats = stats
        try:
            self._payload = pickle.dumps(preprocessing, -1)
        except Exception as e:
            raise ValueError(
                "infeed backend 'process' needs a picklable Preprocessing "
                "chain (module-level functions; no lambdas or closures): "
                f"{e}") from e
        self._slot_bytes = int(slot_bytes or os.environ.get(
            "ZOO_TPU_INFEED_SLOT_BYTES", DEFAULT_SLOT_BYTES))
        self._slots = int(slots_per_worker or os.environ.get(
            "ZOO_TPU_INFEED_SLOTS", DEFAULT_SLOTS_PER_WORKER))
        self._respawner = respawner or Respawner(max_per_child=3)
        self._ctx = mp.get_context("spawn")  # fork after jax is unsafe
        self._result_q = self._ctx.Queue()
        self._tasks: Dict[int, Any] = {}    # seq -> raw batch (requeue)
        self._ready: Dict[int, Any] = {}    # seq -> batch | _RemoteError
        self._seq_submit = 0
        self._seq_emit = 0
        self._rr = 0
        self._exhausted = False
        self._closed = False
        self._fatal: Optional[BaseException] = None
        self._close_lock = threading.Lock()
        self.shm_batches = 0
        self.pickled_batches = 0
        self._all_procs: List = []
        self._workers: Dict[int, _Worker] = {}
        for wid in range(self.num_workers):
            shm = shared_memory.SharedMemory(
                create=True, size=self._slot_bytes * self._slots)
            w = _Worker(wid, self._ctx.Queue(), self._ctx.Queue(),
                        _RingSegment(shm))
            for s in range(self._slots):
                w.free_q.put(s)
            self._workers[wid] = w
        self._finalizer = weakref.finalize(
            self, _reap_pool, self._all_procs,
            [w.segment for w in self._workers.values()])
        for w in self._workers.values():
            self._start_proc(w)
        register_pipeline(self)
        self._fill()

    @property
    def respawns(self) -> int:
        return self._respawner.total_respawns

    def pool_stats(self) -> Dict[str, int]:
        return {"shm_batches": self.shm_batches,
                "pickled_batches": self.pickled_batches,
                "respawns": self.respawns}

    def _start_proc(self, w: _Worker):
        p = self._ctx.Process(
            target=worker_main,
            args=(w.wid, w.segment.shm.name, self._slot_bytes,
                  self._payload, w.task_q, self._result_q, w.free_q),
            daemon=True, name=f"zoo-infeed-{w.wid}")
        p.start()
        w.proc = p
        self._all_procs.append(p)

    def _fill(self):
        while not self._exhausted and \
                len(self._tasks) + len(self._ready) < self._max_in_flight:
            try:
                item = next(self._base)
            except StopIteration:
                self._exhausted = True
                break
            seq = self._seq_submit
            self._seq_submit += 1
            w = self._workers[self._rr % self.num_workers]
            self._rr += 1
            self._tasks[seq] = item
            w.assigned.add(seq)
            w.task_q.put((seq, item))

    def _note_time(self, wid: int, elapsed: float):
        if self._stats is not None:
            self._stats.record(elapsed)
            self._stats.record_worker(wid, elapsed)

    def _wrap(self, w: _Worker, slot: int, metas, template) -> MiniBatch:
        """Wrap one ring slot's bytes in numpy views — the zero-copy hot
        path. Each view carries a finalizer on the shared lease; the
        slot returns to the worker only after every view is gone."""
        if not metas:
            try:
                w.free_q.put_nowait(slot)
            except Exception:  # noqa: BLE001
                pass
            return rebuild_batch(template, [])
        lease = _SlotLease(w.free_q, w.segment, slot, len(metas))
        base = slot * self._slot_bytes
        arrays = []
        for off, shape, dt in metas:
            arr = np.ndarray(shape, np.dtype(dt), buffer=w.segment.shm.buf,
                             offset=base + off)
            weakref.finalize(arr, lease.release_one)
            arrays.append(arr)
        return rebuild_batch(template, arrays)

    def _handle(self, msg):
        kind, wid, seq = msg[0], msg[1], msg[2]
        if kind == "spans":
            # telemetry side-channel: replay the worker's span events
            # under its real pid so the trace shows a per-worker timeline
            telemetry.ingest_events(
                msg[3], pid=seq, process_name=f"zoo-infeed-{wid}")
            return
        if kind == "fatal":
            # the worker can't run at all (chain failed to unpickle in
            # the spawned interpreter): surface on the next __next__
            self._fatal = pickle.loads(msg[3])
            return
        w = self._workers[wid]
        if seq not in self._tasks:
            # late duplicate after a respawn resubmission: drop it, but
            # hand its slot straight back so the ring doesn't shrink
            if kind == "shm":
                try:
                    w.free_q.put_nowait(msg[3])
                except Exception:  # noqa: BLE001
                    pass
            return
        del self._tasks[seq]
        w.assigned.discard(seq)
        if kind == "shm":
            _, _, _, slot, metas, template, elapsed = msg
            self._ready[seq] = self._wrap(w, slot, metas, template)
            self.shm_batches += 1
            self._note_time(wid, elapsed)
        elif kind == "pkl":
            self._ready[seq] = pickle.loads(msg[3])
            self.pickled_batches += 1
            self._note_time(wid, msg[4])
        else:  # "err"
            self._ready[seq] = _RemoteError(pickle.loads(msg[3]))

    def _check_workers(self):
        """Respawn dead workers on their existing queues + ring and
        resubmit their unacknowledged batches. Raises RuntimeError (via
        the Respawner budget) when deaths look structural."""
        for wid, w in list(self._workers.items()):
            if self._closed or w.proc is None or w.proc.is_alive():
                continue
            self._respawner.note_death(
                f"infeed-{wid}", f"exit code {w.proc.exitcode}")
            logger.warning(
                "infeed worker %d died (exit %s); respawning and "
                "resubmitting %d batch(es)", wid, w.proc.exitcode,
                len(w.assigned))
            self._start_proc(w)
            for seq in sorted(w.assigned):
                w.task_q.put((seq, self._tasks[seq]))

    def __iter__(self):
        return self

    def __next__(self):
        if self._closed:
            raise StopIteration
        if self._seq_emit not in self._ready and not self._tasks \
                and self._exhausted:
            self.close()
            raise StopIteration
        while self._seq_emit not in self._ready:
            if self._fatal is not None:
                err, self._fatal = self._fatal, None
                self.close()
                raise err
            try:
                msg = self._result_q.get(timeout=0.2)
            except queue_mod.Empty:
                try:
                    self._check_workers()
                except BaseException:
                    self.close()
                    raise
                continue
            self._handle(msg)
        out = self._ready.pop(self._seq_emit)
        if isinstance(out, _RemoteError):
            self.close()
            raise out.exc
        self._seq_emit += 1
        self._fill()
        return out

    def close(self):
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        try:
            for w in self._workers.values():
                try:
                    w.task_q.put_nowait(None)
                except Exception:  # noqa: BLE001
                    pass
            for w in self._workers.values():
                if w.proc is not None:
                    w.proc.join(timeout=1.0)
            self._tasks.clear()
            self._ready.clear()
            base_close = getattr(self._base, "close", None)
            if base_close is not None:
                base_close()
        finally:
            # segments must not outlive the pool no matter how teardown
            # went: _reap_pool terminates stragglers and unlinks every
            # ring (idempotent with the GC backstop)
            self._finalizer()
            for w in self._workers.values():
                for q in (w.task_q, w.free_q):
                    try:
                        q.close()
                        q.cancel_join_thread()
                    except Exception:  # noqa: BLE001
                        pass
            try:
                self._result_q.close()
                self._result_q.cancel_join_thread()
            except Exception:  # noqa: BLE001
                pass


class StagedChunk:
    """One dispatch unit handed to the engine.

    ``stacked`` is the (k, batch, ...) device super-batch when the chunk
    filled a full fused dispatch (engine runs the k-step scan program);
    otherwise ``singles`` holds per-batch device batches (engine reuses
    the single-step program — epoch tails and k == 1). ``hosts`` keeps
    the pre-put host copies so a k-change can restage without re-reading
    the input pipeline, and so predict() can count real samples.
    """

    __slots__ = ("k", "stacked", "singles", "hosts")

    def __init__(self, k: int, stacked, singles, hosts: List[MiniBatch]):
        self.k = k
        self.stacked = stacked
        self.singles = singles
        self.hosts = hosts

    @property
    def real_counts(self) -> List[int]:
        """Per-batch count of real (non-padding) samples: zero-weight rows
        are the pad_remainder filler; weight-less batches are all real.
        Lets evaluate()/predict() unpad fused outputs without touching the
        device copies."""
        counts = []
        for h in self.hosts:
            w = h.weights
            counts.append(minibatch_len(h) if w is None else
                          int(np.sum(np.asarray(w) > 0)))
        return counts


class DeviceStagingIterator:
    """Keeps up to ``depth`` dispatch chunks already on the device mesh.

    ``put_one`` / ``put_stacked`` are the engine's placement rules
    (``_put_batch`` / ``_put_stacked``) — pad to the dp multiple, lay the
    batch axis over the data sharding — so staged batches are laid out
    exactly as the compiled step expects. ``next_chunk(k)`` recomputes
    per call: the engine's fused dispatch size can shrink at trigger
    boundaries, in which case already-staged chunks are dissolved back
    into the pending host queue (order preserved) and restaged at the
    new k; the dropped device copies are the cost of a rare event.
    """

    def __init__(self, host_it: Iterator[MiniBatch],
                 put_one: Callable[[MiniBatch], Any],
                 put_stacked: Callable[[List[MiniBatch]], Any],
                 depth: int = 2, monitor=None):
        self._host_it = iter(host_it)
        self._put_one = put_one
        self._put_stacked = put_stacked
        self.depth = max(1, int(depth))
        self.monitor = monitor
        self._staged: deque = deque()       # StagedChunk, oldest first
        self._pending: deque = deque()      # host batches awaiting staging
        self._eof = False
        register_pipeline(self)

    def _fetch_host(self) -> Optional[MiniBatch]:
        if self._pending:
            return self._pending.popleft()
        if self._eof:
            return None
        t0 = time.perf_counter()
        try:
            with span("infeed/wait"):
                hb = next(self._host_it)
        except StopIteration:
            self._eof = True
            return None
        finally:
            if self.monitor is not None:
                self.monitor.input_wait(time.perf_counter() - t0)
        return hb

    def _stage_one(self, k: int) -> bool:
        hosts: List[MiniBatch] = []
        while len(hosts) < k:
            hb = self._fetch_host()
            if hb is None:
                break
            hosts.append(hb)
        if not hosts:
            return False
        # a full chunk stacks into the (k, batch, ...) super-batch only
        # when every batch has the same length: a non-dropped, non-padded
        # remainder (drop_remainder=False, pad_remainder=False) lands mid-
        # chunk with a shorter batch axis and must take the singles path
        # rather than np.stack raising
        uniform = len({minibatch_len(h) for h in hosts}) == 1
        if k > 1 and len(hosts) == k and uniform:
            # stacking needs one tree structure across the chunk: a padded
            # remainder carries a weights array while full batches carry
            # None — materialize ones (the semantic equivalent of None)
            # so the stacked super-batch has a single treedef
            if any(h.weights is not None for h in hosts) and \
                    not all(h.weights is not None for h in hosts):
                hosts = [h if h.weights is not None else
                         MiniBatch(h.inputs, h.targets,
                                   np.ones(minibatch_len(h), np.float32))
                         for h in hosts]
            chunk = StagedChunk(k, self._put_stacked(hosts), None, hosts)
        else:
            chunk = StagedChunk(
                k, None, [self._put_one(h) for h in hosts], hosts)
        self._staged.append(chunk)
        return True

    def _restage(self, k: int):
        """Dispatch size changed: return staged hosts to the front of the
        pending queue in original order and drop their device copies."""
        while self._staged:
            chunk = self._staged.pop()
            self._pending.extendleft(reversed(chunk.hosts))

    def next_chunk(self, k: int) -> Optional[StagedChunk]:
        if self._staged and self._staged[0].k != k:
            self._restage(k)
        while len(self._staged) < self.depth:
            if not self._stage_one(k):
                break
        if not self._staged:
            return None
        return self._staged.popleft()

    def __iter__(self):
        """k == 1 convenience stream (evaluate/predict): yields
        (device_batch, host_batch) pairs."""
        while True:
            chunk = self.next_chunk(1)
            if chunk is None:
                return
            yield chunk.singles[0], chunk.hosts[0]

    def close(self):
        self._staged.clear()
        self._pending.clear()
        host_close = getattr(self._host_it, "close", None)
        if host_close is not None:
            host_close()


def resolve_transform_workers(
        transform_workers: Optional[int] = None) -> int:
    """Resolve the transform/decode worker count — THE resolver, consulted
    by every pool in the package (thread and process infeed backends,
    image-pipeline decoders, sharded-dataset readers) so
    ``ZOO_TPU_TRANSFORM_WORKERS`` means one thing everywhere.

    ``None`` reads ``ZOO_TPU_TRANSFORM_WORKERS`` (default auto); >= 0 is
    taken literally (0 = serial in the prefetch thread); negative means
    auto — size the pool from the host core count so the host half can
    keep pace with the model's consumption rate. The auto pool is
    clamped to [2, 8]: below 2 a single worker cannot hide per-batch
    transform latency behind the device step, above 8 the ordered
    hand-off queue is the bottleneck, not the pool."""
    if transform_workers is None:
        transform_workers = int(
            os.environ.get("ZOO_TPU_TRANSFORM_WORKERS") or -1)
    if transform_workers >= 0:
        return int(transform_workers)
    return max(2, min(8, os.cpu_count() or 2))


INFEED_BACKENDS = ("auto", "thread", "process")


def resolve_infeed_backend(backend: Optional[str] = None,
                           preprocessing=None) -> str:
    """Pick the transform-pool backend: ``thread`` or ``process``.

    Explicit wins: ``backend`` argument, else ``ZOO_TPU_INFEED_BACKEND``,
    else ``auto`` — an explicit ``"auto"`` (the ZooConfig default the
    engine always passes) also defers to the env var, so
    ``ZOO_TPU_INFEED_BACKEND=process`` reaches an unmodified training
    script. Auto chooses ``process`` only when it can actually
    pay off: the Preprocessing chain declares itself CPU-bound Python
    (``cpu_bound=True`` — GIL-holding work that threads serialize), the
    chain survives pickling (spawned workers must reconstruct it), and
    the host has more than one core. Everything else stays on threads,
    where numpy's GIL-releasing kernels already scale and the hand-off
    is cheaper.
    """
    b = (backend or "auto").strip().lower()
    if b == "auto":
        b = (os.environ.get("ZOO_TPU_INFEED_BACKEND") or
             "auto").strip().lower()
    if b not in INFEED_BACKENDS:
        raise ValueError(
            f"ZOO_TPU_INFEED_BACKEND={b!r}: expected one of "
            f"{INFEED_BACKENDS}")
    if b != "auto":
        return b
    if preprocessing is None or \
            not getattr(preprocessing, "cpu_bound", False):
        return "thread"
    if (os.cpu_count() or 1) < 2:
        return "thread"
    try:
        pickle.dumps(preprocessing)
    except Exception:  # noqa: BLE001 - closures/lambdas in the chain
        logger.info("infeed auto backend: cpu_bound chain is not "
                    "picklable; staying on threads")
        return "thread"
    return "process"


def build_host_pipeline(fs: FeatureSet, batch_size: int, *,
                        shuffle: bool = False, drop_remainder: bool = True,
                        pad_remainder: bool = False, seed: int = 0,
                        transform_workers: Optional[int] = -1,
                        prefetch_depth: int = 2,
                        infeed_backend: Optional[str] = None
                        ) -> PrefetchIterator:
    """Host half of the staged pipeline: (parallel) transform + prefetch.

    Returns a closeable iterator of host MiniBatches; wrap it in a
    ``DeviceStagingIterator`` for the device half. ``transform_workers``
    only applies when ``fs`` carries a Preprocessing chain
    (TransformedFeatureSet); raw array slicing is already cheap. The
    default (-1) auto-sizes the pool from the host core count
    (:func:`resolve_transform_workers`); ``infeed_backend`` selects
    thread vs process transform workers
    (:func:`resolve_infeed_backend`).
    """
    transform_workers = resolve_transform_workers(transform_workers)
    kw = dict(shuffle=shuffle, drop_remainder=drop_remainder,
              pad_remainder=pad_remainder, seed=seed)
    if transform_workers > 0 and isinstance(fs, TransformedFeatureSet):
        it = fs.batches(batch_size, num_workers=transform_workers,
                        backend=infeed_backend, **kw)
    else:
        it = fs.batches(batch_size, **kw)
    return PrefetchIterator(it, depth=prefetch_depth)
