"""Spawn-side half of the process infeed backend.

``ProcessTransformPool`` (host_pipeline.py) ships raw batches to N
``multiprocessing`` workers; each worker runs the pickled Preprocessing
chain and returns the transformed batch through a per-worker
``multiprocessing.shared_memory`` ring — the parent wraps the slot bytes
in numpy views with zero copies. This module is everything that runs
(or is shared) on the worker side, kept import-light: workers are
spawned (fork after jax initialises is unsafe), so every import here is
paid once per worker at startup — numpy and the feature package, never
jax.

Wire protocol (one message per task, on the shared result queue):

``("shm", wid, seq, slot, metas, template, elapsed)``
    The batch's arrays live in worker ``wid``'s ring at ``slot``;
    ``metas`` is ``[(byte_offset, shape, dtype_str), ...]`` per array
    and ``template`` rebuilds the MiniBatch structure around them.
``("pkl", wid, seq, payload, elapsed)``
    Fallback when the batch exceeds the slot size, contains non-ndarray
    leaves, or no slot was free (the consumer is holding every lease —
    e.g. a caching tier retaining the whole epoch): the batch travels
    pickled through the queue. Correctness is identical; only the
    zero-copy property is lost, and only for that batch.
``("err", wid, seq, payload)``
    The transform raised; the parent re-raises at batch ``seq``'s
    position in the output stream.
``("fatal", wid, -1, payload)``
    The worker cannot run at all (the Preprocessing chain failed to
    unpickle — e.g. it references names the spawned interpreter cannot
    import). The parent surfaces this immediately instead of burning
    the respawn budget on a structurally-broken worker.
``("spans", wid, pid, events)``
    Telemetry only (shipped when ``ZOO_TPU_TELEMETRY`` is on, inherited
    through the spawn env): compact span-event tuples recorded around
    this worker's transforms. The parent ingests them under the
    worker's own pid so the exported Chrome trace shows a timeline per
    infeed worker process.
"""

from __future__ import annotations

import os
import pickle
import time
import traceback
from typing import Any, List, Optional, Tuple

import numpy as np

_ALIGN = 64  # match the native arena / TPU lane alignment


def _aligned(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


def flatten_batch(batch) -> Tuple[Optional[List[np.ndarray]], Any]:
    """MiniBatch -> (contiguous arrays, structure template), or
    ``(None, None)`` when the value cannot take the shared-memory path
    (not a MiniBatch, or a leaf is an object array / not array-like)."""
    from .feature_set import MiniBatch

    if not isinstance(batch, MiniBatch):
        return None, None
    arrays: List[np.ndarray] = []

    def take(x) -> int:
        a = np.asarray(x)
        if a.dtype.hasobject:
            raise TypeError("object dtype")
        arrays.append(np.ascontiguousarray(a))
        return len(arrays) - 1

    try:
        xs = [take(x) for x in batch.inputs]
        t = batch.targets
        if t is None:
            ty: Tuple = ("none",)
        elif isinstance(t, (list, tuple)):
            kind = "list" if isinstance(t, list) else "tuple"
            ty = (kind, [take(v) for v in t])
        else:
            ty = ("arr", take(t))
        w = None if batch.weights is None else take(batch.weights)
    except (TypeError, ValueError):
        return None, None
    return arrays, (xs, ty, w)


def rebuild_batch(template, arrays: List[np.ndarray]):
    """Inverse of :func:`flatten_batch` over any array sequence (the
    parent passes zero-copy shared-memory views)."""
    from .feature_set import MiniBatch

    xs_idx, ty, w = template
    xs = tuple(arrays[i] for i in xs_idx)
    if ty[0] == "none":
        t = None
    elif ty[0] == "arr":
        t = arrays[ty[1]]
    else:
        seq = [arrays[i] for i in ty[1]]
        t = seq if ty[0] == "list" else tuple(seq)
    return MiniBatch(xs, t, None if w is None else arrays[w])


def slot_nbytes(arrays: List[np.ndarray]) -> int:
    """Bytes the arrays occupy in a slot (each array 64-byte aligned)."""
    return sum(_aligned(a.nbytes) for a in arrays)


def write_slot(buf, base: int, arrays: List[np.ndarray]) -> List[Tuple]:
    """Pack ``arrays`` into ``buf`` starting at byte ``base``; returns
    the metas list for the wire message. Caller checks the total fits."""
    metas = []
    off = 0
    for a in arrays:
        dst = np.ndarray(a.shape, a.dtype, buffer=buf, offset=base + off)
        dst[...] = a
        metas.append((off, a.shape, a.dtype.str))
        off += _aligned(a.nbytes)
    return metas


def _attach_ring(shm_name: str):
    """Attach the parent-owned segment without the resource tracker
    adopting it: in 3.10 an attaching ``SharedMemory`` registers with the
    (inherited) tracker, which would unlink the parent's segment when
    this worker exits and spam KeyErrors at parent unlink time. The
    no-op patch is worker-local and workers create no shm of their own."""
    from multiprocessing import resource_tracker, shared_memory

    orig = resource_tracker.register
    resource_tracker.register = lambda *a, **k: None
    try:
        return shared_memory.SharedMemory(name=shm_name)
    finally:
        resource_tracker.register = orig


def _encode_error(e: BaseException) -> bytes:
    try:
        return pickle.dumps(e)
    except Exception:  # noqa: BLE001 - unpicklable exception state
        return pickle.dumps(RuntimeError(
            f"{type(e).__name__}: {e}\n{traceback.format_exc()}"))


def _acquire_slot(free_q, timeout: float = 0.05) -> Optional[int]:
    import queue as _q

    try:
        return free_q.get_nowait()
    except _q.Empty:
        pass
    try:
        return free_q.get(timeout=timeout)
    except _q.Empty:
        return None


def worker_main(wid: int, shm_name: Optional[str], slot_bytes: int,
                fn_payload: bytes, task_q, result_q, free_q) -> None:
    """Entry point of one spawned transform worker.

    Pulls ``(seq, raw_batch)`` tasks until the ``None`` sentinel, runs
    the unpickled Preprocessing chain, and ships results per the module
    protocol. The ``infeed-worker`` fault site fires here — after the
    transform, before the result ships — so an injected kill genuinely
    loses a batch mid-flight and the parent must recover it.
    """
    from ..utils import faults, telemetry

    tracing = telemetry.enabled()
    if tracing:
        # spans recorded here are drained into compact tuples and shipped
        # on the result queue; the parent replays them under this pid
        telemetry.enable_forwarding()

    def _ship_spans() -> None:
        evs = telemetry.drain_events()
        if evs:
            result_q.put(("spans", wid, os.getpid(), evs))

    try:
        fn = pickle.loads(fn_payload)
    except BaseException as e:  # noqa: BLE001 - surface, don't respawn
        result_q.put(("fatal", wid, -1, _encode_error(e)))
        return
    shm = _attach_ring(shm_name) if shm_name else None
    items = 0
    try:
        while True:
            task = task_q.get()
            if task is None:
                break
            seq, batch = task
            t0 = time.perf_counter()
            try:
                with telemetry.span("infeed/transform", seq=seq, wid=wid):
                    out = fn(batch)
                items += 1
                faults.check("infeed-worker", items)
            except BaseException as e:  # noqa: BLE001 - ship to parent
                result_q.put(("err", wid, seq, _encode_error(e)))
                if tracing:
                    _ship_spans()
                continue
            elapsed = time.perf_counter() - t0
            shipped = False
            if shm is not None:
                arrays, template = flatten_batch(out)
                if arrays is not None and slot_nbytes(arrays) <= slot_bytes:
                    slot = _acquire_slot(free_q)
                    if slot is not None:
                        with telemetry.span("infeed/slot_write", seq=seq):
                            metas = write_slot(shm.buf, slot * slot_bytes,
                                               arrays)
                        result_q.put(("shm", wid, seq, slot, metas,
                                      template, elapsed))
                        shipped = True
            if not shipped:
                result_q.put(("pkl", wid, seq, pickle.dumps(out, -1),
                              elapsed))
            if tracing:
                _ship_spans()
    finally:
        if tracing:
            _ship_spans()
        if shm is not None:
            shm.close()
