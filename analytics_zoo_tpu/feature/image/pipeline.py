"""Streaming parallel image input pipeline.

The hard part of feeding ResNet-class training on TPU is not augment
correctness (``preprocessing.py`` covers that) but *throughput*: at 0.3
MFU a v5e chip consumes ~1,300 img/s, and the reference hides this cost
inside JVM-local MiniBatch iterators backed by OpenCV threads
(``zoo/.../feature/image/ImageSet.scala:46-140``, SURVEY §7 hard-part
(c)). This module is the TPU-native equivalent: decode + augment +
collate runs in a pool of workers (cv2's C++ decode releases the GIL, so
threads scale; a process pool is available for augment chains that are
GIL-bound), and finished host batches flow through a bounded in-flight
window — double buffering against the training step so the accelerator
never waits. The consumer-side stall is measured, not guessed:
``stats.infeed_wait_s`` is the exact time ``batches()`` blocked on the
pool, the number that must stay ~0 for the MFU target to be reachable.

Design notes (TPU-first):
- one task = one whole minibatch (collated in the worker): the IPC/sync
  cost is per-batch, not per-image, and the trainer receives arrays that
  are already layout-final (NHWC float32/bfloat16-ready).
- bounded in-flight window (default 2x workers) instead of an unbounded
  imap: a slow consumer must backpressure the decoders, or a fast decode
  pool happily buffers the whole epoch in host RAM.
- the pipeline is a FeatureSet, so ``SPMDTrainer``/``Model.fit`` consume
  it exactly like any other dataset (prefetch + async device_put on top).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import ProcessPoolExecutor, ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence

import numpy as np

try:
    import cv2
except Exception:  # pragma: no cover
    cv2 = None

from ..feature_set import FeatureSet, MiniBatch

__all__ = ["ImagePipelineFeatureSet", "decode_batch", "PipelineStats"]


@dataclass
class PipelineStats:
    """Consumer-visible throughput accounting for one ``batches()`` pass."""

    batches: int = 0
    images: int = 0
    infeed_wait_s: float = 0.0   # time the consumer blocked on the pool
    elapsed_s: float = 0.0
    worker_decode_s: float = 0.0  # summed across workers (wall / pool-par)

    def throughput(self) -> float:
        return self.images / self.elapsed_s if self.elapsed_s > 0 else 0.0

    def input_bound_fraction(self) -> float:
        """Share of the pass's wall time the consumer spent blocked on the
        pool — same definition as the engine's InputBoundFraction scalar."""
        if self.elapsed_s <= 0:
            return 0.0
        return min(1.0, self.infeed_wait_s / self.elapsed_s)

    def as_dict(self) -> dict:
        return {"batches": self.batches, "images": self.images,
                "infeed_wait_s": round(self.infeed_wait_s, 4),
                "elapsed_s": round(self.elapsed_s, 4),
                "throughput_img_s": round(self.throughput(), 1),
                "input_bound_fraction": round(self.input_bound_fraction(),
                                              4)}


def _decode_one(path: str, height: int, width: int,
                augment: Optional[Callable], to_chw: bool,
                mean, std) -> np.ndarray:
    """bytes -> HWC float32 (or CHW when ``to_chw``). cv2 decodes BGR;
    we keep the reference's BGR convention (OpenCVMethod parity) — the
    normalization constants passed by callers are BGR-ordered too."""
    data = np.fromfile(path, np.uint8)
    if cv2 is not None:
        img = cv2.imdecode(data, cv2.IMREAD_COLOR)
    else:  # pragma: no cover - decode fallback without cv2
        from PIL import Image
        import io
        img = np.asarray(Image.open(io.BytesIO(data.tobytes()))
                         .convert("RGB"))[:, :, ::-1]
    if img is None:
        raise ValueError(f"undecodable image: {path}")
    # float32 BEFORE resize: matches the eager ImageSet path
    # (ImageBytesToMat converts first) — uint8 resize rounds differently
    img = np.asarray(img, np.float32)
    if (img.shape[0], img.shape[1]) != (height, width):
        if cv2 is not None:
            img = cv2.resize(img, (width, height),
                             interpolation=cv2.INTER_LINEAR)
        else:  # pragma: no cover
            ys = np.linspace(0, img.shape[0] - 1, height).astype(np.int64)
            xs = np.linspace(0, img.shape[1] - 1, width).astype(np.int64)
            img = img[ys][:, xs]
    if augment is not None:
        img = augment(img)
    if mean is not None:
        img = img - np.asarray(mean, np.float32)
    if std is not None:
        img = img / np.asarray(std, np.float32)
    if to_chw:
        img = np.transpose(img, (2, 0, 1))
    return img


def decode_batch(paths: Sequence[str], labels, height: int, width: int,
                 augment=None, to_chw: bool = False, mean=None, std=None):
    """Worker task: decode+augment+collate one minibatch. Returns
    (stacked NHWC/NCHW float32, labels or None, worker_seconds)."""
    t0 = time.perf_counter()
    imgs = [_decode_one(p, height, width, augment, to_chw, mean, std)
            for p in paths]
    xs = np.stack(imgs)
    ys = None if labels is None else np.asarray(labels)
    return xs, ys, time.perf_counter() - t0


class ImagePipelineFeatureSet(FeatureSet):
    """File-backed images decoded on the fly by a worker pool.

    Unlike ``ImageSet.read`` (which materializes every decoded image
    up front — fine for fixtures, fatal for ImageNet), this holds only
    paths + labels and streams ready minibatches.

    Parameters
    ----------
    augment: a picklable callable ``HWC float32 -> HWC float32`` applied
        per image in the worker (e.g. a ``ChainedPreprocessing`` of the
        2D ops); random augments must draw from numpy's per-process RNG.
    backend: "thread" (default — cv2 releases the GIL for decode/resize)
        or "process" (python-heavy augment chains).
    in_flight: max batches decoded ahead of the consumer (the double
        buffer depth). Defaults to ``2 * num_workers``.
    """

    def __init__(self, paths: Sequence[str], labels=None, *,
                 height: int, width: int,
                 num_workers: Optional[int] = None,
                 augment: Optional[Callable] = None,
                 data_format: str = "tf",
                 mean=None, std=None,
                 backend: str = "thread",
                 in_flight: Optional[int] = None):
        self.paths: List[str] = [str(p) for p in paths]
        self.labels = None if labels is None else np.asarray(labels)
        if self.labels is not None and len(self.labels) != len(self.paths):
            raise ValueError("labels/paths length mismatch")
        self.height, self.width = int(height), int(width)
        self.augment = augment
        self.to_chw = data_format in ("th", "NCHW", "nchw")
        self.mean, self.std = mean, std
        # same knob as the engine's transform pool so one env var sizes
        # the whole host pipeline (the shared resolver reads
        # ZOO_TPU_TRANSFORM_WORKERS and auto-sizes from the core count)
        from ..host_pipeline import resolve_transform_workers
        self.num_workers = max(1, resolve_transform_workers(num_workers))
        self.backend = backend
        self.in_flight = int(in_flight or 2 * self.num_workers)
        self.stats = PipelineStats()

    @classmethod
    def read_folder(cls, root: str, one_based_label: bool = True, **kw):
        """Labeled directory tree (class-per-subdir), like
        ``ImageSet._read_with_label`` but without decoding anything."""
        import glob as _glob
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        label_map = {c: i + (1 if one_based_label else 0)
                     for i, c in enumerate(classes)}
        paths, labels = [], []
        for c in classes:
            for p in sorted(_glob.glob(os.path.join(root, c, "*"))):
                if p.lower().endswith((".jpg", ".jpeg", ".png", ".bmp")):
                    paths.append(p)
                    labels.append(label_map[c])
        fs = cls(paths, np.asarray(labels, np.float32), **kw)
        fs.label_map = label_map
        return fs

    def size(self) -> int:
        return len(self.paths)

    def _make_pool(self):
        if self.backend == "process":
            return ProcessPoolExecutor(max_workers=self.num_workers)
        return ThreadPoolExecutor(max_workers=self.num_workers,
                                  thread_name_prefix="zoo-img")

    def batches(self, batch_size: int, shuffle: bool = False,
                drop_remainder: bool = True, pad_remainder: bool = False,
                seed: int = 0):
        idx = np.arange(len(self.paths))
        if shuffle:
            np.random.default_rng(seed).shuffle(idx)
        n = len(idx)
        if drop_remainder:
            n = (n // batch_size) * batch_size
        starts = list(range(0, n, batch_size))
        stats = PipelineStats()
        self.stats = stats
        t_start = time.perf_counter()
        pool = self._make_pool()
        try:
            pending: deque = deque()
            submit_iter = iter(starts)

            def submit_next():
                s = next(submit_iter, None)
                if s is None:
                    return False
                sel = idx[s:s + batch_size]
                pad = 0
                if len(sel) < batch_size and pad_remainder:
                    # pad by repeating the last sample with ZERO weight
                    # (the ArrayFeatureSet contract: the trainer's
                    # evaluate/predict mask pads via weights > 0)
                    pad = batch_size - len(sel)
                    sel = np.concatenate([sel, np.repeat(sel[-1:], pad)])
                pending.append((pad, pool.submit(
                    decode_batch, [self.paths[i] for i in sel],
                    None if self.labels is None else self.labels[sel],
                    self.height, self.width, self.augment, self.to_chw,
                    self.mean, self.std)))
                return True

            for _ in range(self.in_flight):
                if not submit_next():
                    break
            while pending:
                pad, fut = pending.popleft()
                t0 = time.perf_counter()
                xs, ys, wsec = fut.result()
                stats.infeed_wait_s += time.perf_counter() - t0
                stats.worker_decode_s += wsec
                submit_next()
                stats.batches += 1
                stats.images += len(xs) - pad
                w = np.ones(len(xs), np.float32)
                if pad:
                    w[-pad:] = 0.0
                yield MiniBatch([xs], ys, w)
        finally:
            try:
                pool.shutdown(wait=False, cancel_futures=True)
            except Exception:  # interpreter teardown: modules half-gone
                pass
            stats.elapsed_s = time.perf_counter() - t_start
