from .image_feature import ImageFeature
from .pipeline import ImagePipelineFeatureSet, PipelineStats
from .image_set import DistributedImageSet, ImageSet, LocalImageSet
from .preprocessing import (ImageAspectScale, ImageBrightness,
                            ImageBytesToMat, ImageCenterCrop,
                            ImageChannelNormalize, ImageChannelOrder,
                            ImageColorJitter, ImageContrast, ImageExpand,
                            ImageFeatureToSample, ImageFeatureToTensor,
                            ImageFiller, ImageFixedCrop, ImageHFlip,
                            ImageHue, ImageMatToFloats, ImageMatToTensor,
                            ImageMirror, ImagePixelBytesToMat,
                            ImagePixelNormalize, ImagePreprocessing,
                            ImageRandomAspectScale, ImageRandomCrop,
                            ImageRandomPreprocessing, ImageResize,
                            ImageSaturation, ImageSetToSample,
                            PerImageNormalize)

__all__ = [
    "ImageFeature", "ImageSet", "LocalImageSet", "DistributedImageSet",
    "ImagePreprocessing", "ImageBytesToMat", "ImagePixelBytesToMat",
    "ImageResize", "ImageBrightness", "ImageContrast", "ImageChannelNormalize",
    "PerImageNormalize", "ImageMatToTensor", "ImageMatToFloats",
    "ImageSetToSample", "ImageHue", "ImageSaturation", "ImageChannelOrder",
    "ImageColorJitter", "ImageAspectScale", "ImageRandomAspectScale",
    "ImagePixelNormalize", "ImageRandomCrop", "ImageCenterCrop",
    "ImageFixedCrop", "ImageExpand", "ImageFiller", "ImageHFlip",
    "ImageMirror", "ImageFeatureToTensor", "ImageFeatureToSample",
    "ImageRandomPreprocessing", "ImagePipelineFeatureSet", "PipelineStats",
]
