"""ZooModel base.

Parity: ``zoo/.../models/common/ZooModel.scala`` + ``KerasZooModel`` and the
python mirror ``pyzoo/zoo/models/common/zoo_model.py`` — a built-in model
owns an internal Keras graph (``self.model``) and forwards the training
surface; ``saveModel``/``loadModel`` round-trips the whole model.
"""

from __future__ import annotations

import os
import pickle
from typing import Optional

import numpy as np


class ZooModel:
    """Base for the built-in model zoo; subclasses set ``self.model`` to a
    KerasNet built in ``build_model``."""

    model = None

    # -- training surface forwarded to the internal KerasNet -----------
    def compile(self, optimizer, loss, metrics=None):
        return self.model.compile(optimizer, loss, metrics)

    def fit(self, x, y=None, batch_size=32, nb_epoch=10,
            validation_data=None, **kw):
        return self.model.fit(x, y, batch_size=batch_size, nb_epoch=nb_epoch,
                              validation_data=validation_data, **kw)

    def evaluate(self, x, y=None, batch_size=32):
        return self.model.evaluate(x, y, batch_size=batch_size)

    def predict(self, x, batch_size=128, distributed=True):
        return self.model.predict(x, batch_size=batch_size)

    def predict_classes(self, x, batch_size=128, zero_based_label=True):
        return self.model.predict_classes(
            x, batch_size=batch_size, zero_based_label=zero_based_label)

    def set_tensorboard(self, log_dir, app_name):
        self.model.set_tensorboard(log_dir, app_name)

    def set_checkpoint(self, path, over_write=True, trigger=None):
        self.model.set_checkpoint(path, over_write=over_write,
                                  trigger=trigger)

    def set_constant_gradient_clipping(self, min_value, max_value):
        self.model.set_constant_gradient_clipping(min_value, max_value)

    def set_gradient_clipping_by_l2_norm(self, clip_norm):
        self.model.set_gradient_clipping_by_l2_norm(clip_norm)

    def get_weights(self):
        return self.model.get_weights()

    def set_weights(self, weights):
        self.model.set_weights(weights)

    def summary(self):
        return self.model.summary()

    # -- persistence ---------------------------------------------------
    def save_model(self, path, weight_path=None, over_write=False):
        """Saves the zoo-model wrapper (config) + internal Keras model."""
        if os.path.exists(path) and not over_write:
            raise IOError(f"{path} exists; pass over_write=True")
        os.makedirs(path, exist_ok=True)
        self.model.save_model(os.path.join(path, "keras"), over_write=True)
        meta = {"class": type(self).__name__,
                "module": type(self).__module__,
                "config": getattr(self, "_zoo_config", {})}
        with open(os.path.join(path, "zoo_model.pkl"), "wb") as f:
            pickle.dump(meta, f)

    saveModel = save_model

    @classmethod
    def load_model(cls, path, weight_path=None):
        import importlib

        from ..pipeline.api.keras.models import KerasNet

        with open(os.path.join(path, "zoo_model.pkl"), "rb") as f:
            meta = pickle.load(f)
        module = importlib.import_module(meta["module"])
        klass = getattr(module, meta["class"])
        obj = klass.__new__(klass)
        obj._zoo_config = dict(meta["config"])
        for k, v in meta["config"].items():
            setattr(obj, k, v)
        obj.model = KerasNet.load_model(os.path.join(path, "keras"))
        return obj

    def _record_config(self, **kwargs):
        self._zoo_config = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)


KerasZooModel = ZooModel


class Ranker:
    """Validation with ranking metrics for matching models (parity:
    ``pyzoo/zoo/models/common/ranker.py`` ``evaluateNDCG``/``evaluateMAP``
    — each TextFeature in the TextSet holds ONE query's candidate batch:
    features ``(listLength, d)``, labels ``(listLength, 1)``, exactly what
    ``TextSet.from_relation_lists`` builds). Mix into a model exposing
    ``predict``.
    """

    def _ranking_groups(self, x):
        if hasattr(x, "features"):           # a TextSet
            for tf_ in x.features:
                sample = tf_.get_sample()
                assert sample is not None, \
                    "TextFeature has no sample; run from_relation_lists " \
                    "(or generate_sample) first"
                yield (np.asarray(sample.features[0]),
                       np.asarray(sample.labels[0]).reshape(-1))
        else:                                 # [(features, labels), ...]
            for feats, labels in x:
                yield np.asarray(feats), np.asarray(labels).reshape(-1)

    def _ranked_relevance(self, feats, labels, threshold):
        scores = np.asarray(
            self.predict(feats, batch_size=max(len(feats), 1))).reshape(-1)
        order = np.argsort(-scores, kind="stable")
        return (labels > threshold).astype(np.float64)[order]

    def evaluate_ndcg(self, x, k: int, threshold: float = 0.0) -> float:
        """Mean NDCG@k over the query groups of ``x``. Queries with no
        positive record contribute 0 (reference semantics)."""
        vals = []
        for feats, labels in self._ranking_groups(x):
            rel = self._ranked_relevance(feats, labels, threshold)
            gains = rel[:k]
            discounts = np.log2(np.arange(2, len(gains) + 2))
            dcg = float((gains / discounts).sum())
            ideal = np.sort(rel)[::-1][:k]
            idcg = float((ideal / discounts[:len(ideal)]).sum())
            vals.append(dcg / idcg if idcg > 0 else 0.0)
        return float(np.mean(vals)) if vals else 0.0

    def evaluate_map(self, x, threshold: float = 0.0) -> float:
        """Mean average precision over the query groups of ``x``."""
        vals = []
        for feats, labels in self._ranking_groups(x):
            rel = self._ranked_relevance(feats, labels, threshold)
            if rel.sum() == 0:
                vals.append(0.0)
                continue
            prec = np.cumsum(rel) / np.arange(1, len(rel) + 1)
            vals.append(float((prec * rel).sum() / rel.sum()))
        return float(np.mean(vals)) if vals else 0.0
