"""TextMatcher base.

Parity: ``zoo/.../models/textmatching/TextMatcher.scala`` — common surface
for text-matching models: query length, vocab/embedding configuration and the
'ranking' vs 'classification' target mode.
"""

from __future__ import annotations

import numpy as np

from ..common import Ranker, ZooModel


class TextMatcher(ZooModel, Ranker):
    TARGET_MODES = ("ranking", "classification")

    def __init__(self, text1_length, vocab_size, embed_size=300,
                 embed_weights=None, train_embed=True, target_mode="ranking"):
        if target_mode not in self.TARGET_MODES:
            raise ValueError(
                f"target_mode must be one of {self.TARGET_MODES}, "
                f"got {target_mode}")
        self.text1_length = int(text1_length)
        self.vocab_size = int(vocab_size)
        self.embed_size = int(embed_size)
        self.embed_weights = None if embed_weights is None else \
            np.asarray(embed_weights, np.float32)
        self.train_embed = bool(train_embed)
        self.target_mode = target_mode
