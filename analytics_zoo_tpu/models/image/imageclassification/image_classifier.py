"""ImageClassifier model family.

Parity: ``zoo/.../models/image/imageclassification/ImageClassifier.scala``
— the reference downloads pretrained BigDL graphs by tag
("analytics-zoo_resnet-50_imagenet_0.1.0"); this rebuild constructs the
architectures natively (NCHW, bfloat16-friendly, XLA-fused) and keeps the
same ``predict_image_set`` + label-output pipeline. Weights train from
scratch or import via ``Net.load_tf`` / ``Net.load_onnx``.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from ....pipeline.api.keras.layers import (Activation, AveragePooling2D,
                                           BatchNormalization, Convolution2D,
                                           Dense, Dropout, Flatten,
                                           GlobalAveragePooling2D, Input,
                                           MaxPooling2D, ZeroPadding2D)
from ....pipeline.api.keras.layers.merge import Add, Concatenate
from ....pipeline.api.keras.models import Model, Sequential
from ..common import (ImageConfigure, ImageModel, LabelOutput,
                      imagenet_preprocess)

backbones: Dict[str, Callable] = {}


def _backbone(name):
    def deco(fn):
        backbones[name] = fn
        return fn
    return deco


def _conv_bn(x, filters, k, stride=1, pad="same", name=None,
             activation="relu", fmt="th"):
    x = Convolution2D(filters, k, k, subsample=(stride, stride),
                      border_mode=pad, bias=False, name=name,
                      dim_ordering=fmt)(x)
    x = BatchNormalization(axis=1 if fmt == "th" else -1,
                           name=None if name is None else name + "_bn")(x)
    if activation:
        x = Activation(activation)(x)
    return x


@_backbone("lenet")
def _lenet(class_num, shape=(1, 28, 28)):
    model = Sequential()
    model.add(Convolution2D(6, 5, 5, activation="tanh", input_shape=shape,
                            border_mode="same"))
    model.add(MaxPooling2D((2, 2)))
    model.add(Convolution2D(12, 5, 5, activation="tanh"))
    model.add(MaxPooling2D((2, 2)))
    model.add(Flatten())
    model.add(Dense(100, activation="tanh"))
    model.add(Dense(class_num, activation="softmax"))
    return model


@_backbone("vgg-16")
def _vgg16(class_num, shape=(3, 224, 224)):
    model = Sequential()
    cfg = [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
           512, 512, 512, "M", 512, 512, 512, "M"]
    first = True
    for v in cfg:
        if v == "M":
            model.add(MaxPooling2D((2, 2)))
        else:
            kw = {"input_shape": shape} if first else {}
            model.add(Convolution2D(v, 3, 3, activation="relu",
                                    border_mode="same", **kw))
            first = False
    model.add(Flatten())
    model.add(Dense(4096, activation="relu"))
    model.add(Dropout(0.5))
    model.add(Dense(4096, activation="relu"))
    model.add(Dropout(0.5))
    model.add(Dense(class_num, activation="softmax"))
    return model


@_backbone("mobilenet")
def _mobilenet(class_num, shape=(3, 224, 224), alpha=1.0):
    from ....pipeline.api.keras.layers.convolutional import \
        SeparableConvolution2D

    def depth(d):
        return max(8, int(d * alpha))

    inp = Input(shape=shape)
    x = _conv_bn(inp, depth(32), 3, stride=2)
    for filters, stride in [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1),
                            (512, 2), (512, 1), (512, 1), (512, 1), (512, 1),
                            (512, 1), (1024, 2), (1024, 1)]:
        x = SeparableConvolution2D(
            depth(filters), 3, 3, subsample=(stride, stride),
            border_mode="same", bias=False)(x)
        x = BatchNormalization()(x)
        x = Activation("relu")(x)
    x = GlobalAveragePooling2D()(x)
    out = Dense(class_num, activation="softmax")(x)
    return Model(inp, out)


def _res_block(x, filters, stride=1, conv_shortcut=False, fmt="th"):
    bn_axis = 1 if fmt == "th" else -1
    shortcut = x
    if conv_shortcut:
        shortcut = Convolution2D(4 * filters, 1, 1,
                                 subsample=(stride, stride),
                                 bias=False, dim_ordering=fmt)(x)
        shortcut = BatchNormalization(axis=bn_axis)(shortcut)
    y = _conv_bn(x, filters, 1, stride=stride, fmt=fmt)
    y = _conv_bn(y, filters, 3, pad="same", fmt=fmt)
    y = Convolution2D(4 * filters, 1, 1, bias=False, dim_ordering=fmt)(y)
    y = BatchNormalization(axis=bn_axis)(y)
    y = Add()([y, shortcut])
    return Activation("relu")(y)


@_backbone("resnet-50")
def _resnet50(class_num, shape=(3, 224, 224), data_format="th"):
    """data_format "tf" builds the NHWC variant (input (224, 224, 3)):
    XLA TPU's native conv layout, so no per-conv relayouts — an on-chip
    A/B knob for the conv-layout cost of the reference's NCHW ordering
    (tools/tpu_perf_session.py leg ``resnet_layout``)."""
    fmt = "tf" if str(data_format).lower() in ("tf", "nhwc", "channels_last") \
        else "th"
    shape = tuple(shape)
    if fmt == "tf" and shape[0] in (1, 3) and shape[-1] not in (1, 3):
        # a clearly channels-first shape with the NHWC format: swap rather
        # than silently building H=3 W=96 C=96 nonsense
        shape = shape[1:] + shape[:1]
    inp = Input(shape=shape)
    x = ZeroPadding2D((3, 3), dim_ordering=fmt)(inp)
    x = _conv_bn(x, 64, 7, stride=2, pad="valid", fmt=fmt)
    x = MaxPooling2D((3, 3), strides=(2, 2), border_mode="same",
                     dim_ordering=fmt)(x)
    for stage, (filters, blocks) in enumerate(
            [(64, 3), (128, 4), (256, 6), (512, 3)]):
        for b in range(blocks):
            stride = 2 if (b == 0 and stage > 0) else 1
            x = _res_block(x, filters, stride=stride,
                           conv_shortcut=(b == 0), fmt=fmt)
    x = GlobalAveragePooling2D(dim_ordering=fmt)(x)
    out = Dense(class_num, activation="softmax")(x)
    return Model(inp, out)


@_backbone("squeezenet")
def _squeezenet(class_num, shape=(3, 224, 224)):
    def fire(x, squeeze, expand):
        s = Convolution2D(squeeze, 1, 1, activation="relu")(x)
        e1 = Convolution2D(expand, 1, 1, activation="relu")(s)
        e3 = Convolution2D(expand, 3, 3, activation="relu",
                           border_mode="same")(s)
        return Concatenate(axis=1)([e1, e3])

    inp = Input(shape=shape)
    x = Convolution2D(64, 3, 3, subsample=(2, 2), activation="relu")(inp)
    x = MaxPooling2D((3, 3), strides=(2, 2))(x)
    x = fire(x, 16, 64)
    x = fire(x, 16, 64)
    x = MaxPooling2D((3, 3), strides=(2, 2))(x)
    x = fire(x, 32, 128)
    x = fire(x, 32, 128)
    x = MaxPooling2D((3, 3), strides=(2, 2))(x)
    x = fire(x, 48, 192)
    x = fire(x, 48, 192)
    x = fire(x, 64, 256)
    x = fire(x, 64, 256)
    x = Dropout(0.5)(x)
    x = Convolution2D(class_num, 1, 1, activation="relu")(x)
    x = GlobalAveragePooling2D()(x)
    out = Activation("softmax")(x)
    return Model(inp, out)


class ImageClassifier(ImageModel):
    """(ImageClassifier.scala parity) build by architecture tag."""

    def __init__(self, class_num: int = 1000, model_name: str = "resnet-50",
                 dataset: str = "imagenet", input_shape=None,
                 label_map: Optional[dict] = None, data_format: str = "th"):
        key = model_name.lower()
        if key not in backbones:
            raise ValueError(
                f"unknown model {model_name}; have {sorted(backbones)}")
        fmt = _norm_format(data_format)
        if fmt == "tf" and key != "resnet-50":
            raise ValueError(
                "data_format='tf' (NHWC) is only supported for resnet-50; "
                f"{key} builds NCHW")
        self._record_config(class_num=class_num, model_name=key,
                            dataset=dataset, input_shape=input_shape,
                            data_format=fmt)
        kwargs = {} if input_shape is None else {"shape": tuple(input_shape)}
        if fmt == "tf":
            kwargs["data_format"] = "tf"
        self.model = backbones[key](class_num, **kwargs)
        self.config = ImageConfigure(
            pre_processor=_default_preprocess(key, input_shape, fmt),
            post_processor=LabelOutput(label_map))

    @classmethod
    def load_model(cls, path, weight_path=None):
        obj = super().load_model(path, weight_path)
        obj.config = ImageConfigure(
            pre_processor=_default_preprocess(
                obj.model_name, obj.input_shape,
                getattr(obj, "data_format", "th")),
            post_processor=LabelOutput(None))
        return obj


def _norm_format(data_format: str) -> str:
    fmt = str(data_format).lower()
    if fmt in ("th", "nchw", "channels_first"):
        return "th"
    if fmt in ("tf", "nhwc", "channels_last"):
        return "tf"
    raise ValueError(f"unknown data_format {data_format!r}; "
                     "use 'th'/'NCHW' or 'tf'/'NHWC'")


def _default_preprocess(key: str, input_shape, fmt: str = "th"):
    """Crop size follows the graph's actual input, not a fixed 224; the
    emitted tensor layout follows the graph's data format."""
    if key == "lenet":
        return None
    if input_shape is None:
        size = 224
    else:
        # crop is square; take the spatial edge for either layout
        size = int(input_shape[-1] if fmt == "th" else input_shape[0])
    return imagenet_preprocess(
        size, format="NCHW" if fmt == "th" else "NHWC")
