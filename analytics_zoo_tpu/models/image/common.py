"""Image model common layer.

Parity: ``zoo/.../models/image/common/`` — ``ImageModel`` (predictImageSet),
``ImageConfigure`` (per-model preprocessing/postprocessing registry), and
the label-output postprocessing used by the classifier zoo.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ...feature.common import ChainedPreprocessing, Preprocessing
from ...feature.image.image_feature import ImageFeature
from ...feature.image.image_set import ImageSet
from ...feature.image.preprocessing import (ImageCenterCrop,
                                            ImageChannelNormalize,
                                            ImageMatToTensor, ImageResize,
                                            ImageSetToSample)
from ..common import ZooModel


class ImageConfigure:
    """Bundle of pre/post processing + batching for one model flavor
    (ImageConfigure.scala parity)."""

    _REGISTRY: Dict[str, "ImageConfigure"] = {}

    def __init__(self, pre_processor: Optional[Preprocessing] = None,
                 post_processor: Optional[Callable] = None,
                 batch_per_partition: int = 4,
                 label_map: Optional[Dict[int, str]] = None,
                 feature_padding_param=None):
        self.pre_processor = pre_processor
        self.post_processor = post_processor
        self.batch_per_partition = batch_per_partition
        self.label_map = label_map

    @classmethod
    def register(cls, name: str, configure: "ImageConfigure"):
        cls._REGISTRY[name.lower()] = configure

    @classmethod
    def parse(cls, name: str) -> Optional["ImageConfigure"]:
        """Look up by model tag, e.g. "imageclassification_imagenet"
        (ImageConfigure.parse parity)."""
        return cls._REGISTRY.get(name.lower())


def imagenet_preprocess(size: int = 224,
                        mean=(123.68, 116.779, 103.939),
                        format: str = "NCHW") -> Preprocessing:
    """Standard imagenet eval chain: resize-256 → center-crop → normalize
    → NCHW (or NHWC) tensor (the reference's default classifier
    preprocessing).

    The resize edge scales with the crop (256/224 ratio) so crops larger
    than 256 still fit inside the resized image."""
    edge = max(256, int(round(size * 256 / 224)))
    return ChainedPreprocessing([
        ImageResize(edge, edge),
        ImageCenterCrop(size, size),
        ImageChannelNormalize(*mean),
        ImageMatToTensor(format=format),
        ImageSetToSample(),
    ])


class LabelOutput:
    """Attach top-probability class + name to each prediction
    (LabelOutput.scala parity)."""

    def __init__(self, label_map: Optional[Dict[int, str]] = None,
                 clses: str = "clses", probs: str = "probs",
                 top_n: int = 5):
        self.label_map = label_map or {}
        self.clses = clses
        self.probs = probs
        self.top_n = top_n

    def __call__(self, feature: ImageFeature, output: np.ndarray):
        probs = np.asarray(output).reshape(-1)
        order = np.argsort(probs)[::-1][:self.top_n]
        feature[self.clses] = [self.label_map.get(int(i), str(int(i)))
                               for i in order]
        feature[self.probs] = probs[order].astype(np.float32)
        return feature


class ImageModel(ZooModel):
    """Base for image models (ImageModel.scala parity):
    ``predict_image_set`` runs preprocessing → batched device predict →
    per-feature postprocessing."""

    def predict_image_set(self, image_set: ImageSet,
                          configure: Optional[ImageConfigure] = None,
                          batch_size: int = 16) -> ImageSet:
        cfg = configure or getattr(self, "config", None)
        data = image_set
        if cfg is not None and cfg.pre_processor is not None:
            data = data.transform(cfg.pre_processor)
        feats = data.to_local().features
        arrays = np.stack([f.get_sample().features[0] for f in feats])
        preds = np.asarray(self.predict(arrays, batch_size=batch_size))
        for feat, pred in zip(feats, preds):
            feat[ImageFeature.predict] = pred
            if cfg is not None and cfg.post_processor is not None:
                cfg.post_processor(feat, pred)
        return data

    predictImageSet = predict_image_set
