"""Seq2seq: generic recurrent encoder + bridge + decoder.

Parity: ``zoo/.../models/seq2seq/{Seq2seq,RNNEncoder,RNNDecoder,Bridge}.scala``
and ``pyzoo/zoo/models/seq2seq/seq2seq.py``. The encoder emits (sequence
output, per-layer final states); the optional Bridge maps encoder states to
decoder initial states (dense / densenonlinear / customized,
Bridge.scala:50-85); the decoder consumes [decoder_input, init_states]; an
optional generator maps decoder outputs to the final result; ``infer`` is the
reference's greedy step-by-step decode loop (Seq2seq.scala:114-160).

TPU design: the reference threads hidden state through BigDL ``Recurrent``
mutable get/setHiddenState hooks with hand-written backward plumbing
(RNNEncoder.scala:80-105). Here states are ordinary outputs of a pure
``lax.scan`` — jax.grad differentiates through encoder→bridge→decoder with no
custom backward; each layer's input projection is one hoisted MXU matmul.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ...pipeline.api.keras.engine.base import (KerasLayer, get_activation_fn,
                                               init_tensor)
from ...pipeline.api.keras.engine.graph import Variable
from ...pipeline.api.keras.models import Model
from ..common import ZooModel

_STATES_PER_LAYER = {"lstm": 2, "gru": 1, "simplernn": 1}
_GATES = {"lstm": 4, "gru": 3, "simplernn": 1}


def _cell_step(rnn_type, h_states, xt, U, hidden, act, inner):
    """One timestep. ``h_states``: tuple of per-layer state (lstm: (h, c)).

    Gate orders follow the layer library (keras-1): LSTM [i, f, c, o],
    GRU [z, r, h].
    """
    if rnn_type == "lstm":
        h_prev, c_prev = h_states
        z = xt + jnp.matmul(h_prev, U)
        i = inner(z[:, :hidden])
        f = inner(z[:, hidden:2 * hidden])
        g = act(z[:, 2 * hidden:3 * hidden])
        o = inner(z[:, 3 * hidden:])
        c = f * c_prev + i * g
        ht = o * act(c)
        return (ht, c), ht
    if rnn_type == "gru":
        (h_prev,) = h_states
        zr = xt[:, :2 * hidden] + jnp.matmul(h_prev, U[:, :2 * hidden])
        z = inner(zr[:, :hidden])
        r = inner(zr[:, hidden:])
        hh = act(xt[:, 2 * hidden:] +
                 jnp.matmul(r * h_prev, U[:, 2 * hidden:]))
        ht = z * h_prev + (1.0 - z) * hh
        return (ht,), ht
    (h_prev,) = h_states
    ht = act(xt + jnp.matmul(h_prev, U))
    return (ht,), ht


class _RNNCoder(KerasLayer):
    """Shared machinery: embedding + stacked scan over ``nlayers`` cells."""

    def __init__(self, rnn_type="lstm", nlayers=1, hidden_size=None,
                 embedding=None, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.rnn_type = str(rnn_type).lower()
        if self.rnn_type not in _STATES_PER_LAYER:
            raise ValueError(
                f"rnn_type must be simplernn | lstm | gru, got {rnn_type}")
        self.nlayers = int(nlayers)
        self.hidden_size = int(hidden_size)
        self.embedding = embedding
        self.states_per_layer = _STATES_PER_LAYER[self.rnn_type]
        self.n_states = self.nlayers * self.states_per_layer
        self.act = get_activation_fn("tanh")
        self.inner = get_activation_fn("hard_sigmoid")

    @classmethod
    def initialize(cls, rnn_type, nlayers, hidden_size, embedding=None,
                   input_shape=None):
        """Parity: RNNEncoder.initialize / RNNDecoder.initialize
        (seq2seq.py:70-79)."""
        return cls(rnn_type, nlayers, hidden_size, embedding=embedding,
                   input_shape=input_shape)

    def _build_stack(self, rng, feat_dim):
        gates = _GATES[self.rnn_type]
        h = self.hidden_size
        params = {}
        d = feat_dim
        for l in range(self.nlayers):
            r_w, r_u, rng = jax.random.split(rng, 3)
            b = jnp.zeros((gates * h,))
            if self.rnn_type == "lstm":
                b = b.at[h:2 * h].set(1.0)  # forget-gate bias
            params[f"l{l}"] = {
                "W": init_tensor(r_w, (d, gates * h)),
                "U": init_tensor(r_u, (h, gates * h), "orthogonal"),
                "b": b}
            d = h
        return params

    def _embed(self, params, x, training):
        if self.embedding is None:
            return x
        return self.embedding.call(params.get("embedding", {}), x,
                                   training=training)

    def _run_stack(self, params, x, init_states, collect_last=True):
        """x: (B, T, D). init_states: list of n_states arrays (B, H) (or
        None for zeros). Returns (seq_out, final_states list)."""
        h = self.hidden_size
        b = x.shape[0]
        spl = self.states_per_layer
        finals: List[jnp.ndarray] = []
        y = x
        for l in range(self.nlayers):
            p = params[f"l{l}"]
            xw = jnp.matmul(y, p["W"].astype(y.dtype)) + \
                p["b"].astype(y.dtype)
            U = p["U"].astype(y.dtype)
            if init_states is None:
                carry0 = tuple(jnp.zeros((b, h), y.dtype)
                               for _ in range(spl))
            else:
                carry0 = tuple(s.astype(y.dtype) for s in
                               init_states[l * spl:(l + 1) * spl])

            def cell(carry, xt, U=U):
                return _cell_step(self.rnn_type, carry, xt, U, h,
                                  self.act, self.inner)

            xs = jnp.swapaxes(xw, 0, 1)
            carry, ys = jax.lax.scan(cell, carry0, xs)
            y = jnp.swapaxes(ys, 0, 1)
            finals.extend(carry)
        return y, finals

    def step(self, params, xt, states):
        """One decode timestep: xt (B, D_in), states: n_states arrays
        (B, H). Returns (ht (B, H), new_states).

        The incremental-decode twin of ``_run_stack``: the recurrent
        state IS the RNN's cache, so carrying it forward makes each
        emitted token O(1) in sequence length — ``Seq2seq.infer`` used
        to re-run the whole decoder prefix per token (O(T^2) total).
        """
        y = xt
        if self.embedding is not None:
            y = self.embedding.call(params.get("embedding", {}),
                                    y[:, None], training=False)[:, 0]
        h = self.hidden_size
        spl = self.states_per_layer
        new_states: List[jnp.ndarray] = []
        for l in range(self.nlayers):
            p = params[f"l{l}"]
            xw = jnp.matmul(y, p["W"].astype(y.dtype)) + \
                p["b"].astype(y.dtype)
            carry = tuple(s.astype(y.dtype)
                          for s in states[l * spl:(l + 1) * spl])
            carry, y = _cell_step(self.rnn_type, carry, xw,
                                  p["U"].astype(y.dtype), h, self.act,
                                  self.inner)
            new_states.extend(carry)
        return y, new_states


class RNNEncoder(_RNNCoder):
    """Outputs: [seq_output (B,T,H)] + per-layer final states
    (lstm: h then c per layer), so ``num_outputs = 1 + nlayers *
    states_per_layer`` — the reference's T(rnnOutput, T(states))
    (RNNEncoder.scala:73-80) flattened into graph edges."""

    @property
    def num_outputs(self):
        return 1 + self.n_states

    def build(self, rng, input_shape):
        params = {}
        feat = input_shape[-1]
        if self.embedding is not None:
            r_e, rng = jax.random.split(rng)
            params["embedding"] = self.embedding.build(r_e, input_shape)
            feat = self.embedding.compute_output_shape(input_shape)[-1]
        params.update(self._build_stack(rng, int(feat)))
        return params

    def call(self, params, x, training=False, **kw):
        y = self._embed(params, x, training)
        seq, finals = self._run_stack(params, y, None)
        return (seq,) + tuple(finals)

    def compute_output_shape(self, s):
        if self.embedding is not None:
            s = self.embedding.compute_output_shape(s)
        seq_shape = (s[0], s[1], self.hidden_size)
        state_shape = (s[0], self.hidden_size)
        return [seq_shape] + [state_shape] * self.n_states


class RNNDecoder(_RNNCoder):
    """Inputs: [decoder_input, init_state_1, ..., init_state_N]; output the
    decoded sequence (B, T, H)."""

    def build(self, rng, input_shape):
        x_shape = input_shape[0]
        params = {}
        feat = x_shape[-1]
        if self.embedding is not None:
            r_e, rng = jax.random.split(rng)
            params["embedding"] = self.embedding.build(r_e, x_shape)
            feat = self.embedding.compute_output_shape(x_shape)[-1]
        params.update(self._build_stack(rng, int(feat)))
        return params

    def call(self, params, inputs, training=False, **kw):
        x, states = inputs[0], list(inputs[1:])
        y = self._embed(params, x, training)
        seq, _ = self._run_stack(params, y, states)
        return seq

    def compute_output_shape(self, s):
        x_shape = s[0]
        if self.embedding is not None:
            x_shape = self.embedding.compute_output_shape(x_shape)
        return (x_shape[0], x_shape[1], self.hidden_size)


class Bridge(KerasLayer):
    """Maps encoder final states to decoder initial states.

    Parity: Bridge.scala:50-85 — states are concatenated, passed through one
    Dense of size ``decoder_hidden_size * n_states`` ("dense": linear,
    "densenonlinear": tanh, both bias-free), then split back into n_states
    pieces. "customized" applies a caller-provided layer to the concatenation
    and splits its output evenly.
    """

    def __init__(self, bridge_type="dense", decoder_hidden_size=0,
                 bridge=None, name=None, **kwargs):
        super().__init__(name=name)
        self.bridge_type = str(bridge_type).lower()
        if self.bridge_type not in ("dense", "densenonlinear", "customized"):
            raise ValueError(
                "Only support dense | densenonlinear | customized as "
                f"bridge_type, got {bridge_type}")
        self.decoder_hidden_size = int(decoder_hidden_size)
        self.bridge = bridge
        self.n_states = None  # set by Seq2seq before graph construction

    @classmethod
    def initialize(cls, bridge_type, decoder_hidden_size):
        return cls(bridge_type, decoder_hidden_size)

    @classmethod
    def initialize_from_keras_layer(cls, bridge):
        return cls("customized", 0, bridge)

    @property
    def num_outputs(self):
        assert self.n_states is not None, \
            "Bridge must be configured by Seq2seq before use"
        return self.n_states

    def build(self, rng, input_shapes):
        if not isinstance(input_shapes[0], (list, tuple)):
            input_shapes = [input_shapes]
        total_in = sum(int(s[-1]) for s in input_shapes)
        if self.bridge_type == "customized":
            cat_shape = (input_shapes[0][0], total_in)
            return {"bridge": self.bridge.build(rng, cat_shape)}
        total_out = self.decoder_hidden_size * len(input_shapes)
        self._annotate(W=("in", "out"))
        return {"W": init_tensor(rng, (total_in, total_out))}

    def call(self, params, states, training=False, **kw):
        if not isinstance(states, (list, tuple)):
            states = [states]
        cat = jnp.concatenate(list(states), axis=-1)
        if self.bridge_type == "customized":
            out = self.bridge.call(params["bridge"], cat, training=training)
        else:
            out = jnp.matmul(cat, params["W"].astype(cat.dtype))
            if self.bridge_type == "densenonlinear":
                out = jnp.tanh(out)
        if len(states) == 1:
            return out
        return tuple(jnp.split(out, len(states), axis=-1))

    def compute_output_shape(self, input_shapes):
        if not isinstance(input_shapes[0], (list, tuple)):
            input_shapes = [input_shapes]
        n = len(input_shapes)
        if self.bridge_type == "customized":
            total_in = sum(int(s[-1]) for s in input_shapes)
            out = self.bridge.compute_output_shape(
                (input_shapes[0][0], total_in))
            per = int(out[-1]) // n
            shapes = [(s[0], per) for s in input_shapes]
        else:
            shapes = [(s[0], self.decoder_hidden_size) for s in input_shapes]
        return shapes[0] if n == 1 else shapes


class Seq2seq(ZooModel):
    """Arguments (seq2seq.py:158-183): encoder, decoder, input_shape (no
    batch dim), output_shape, optional bridge and generator layers."""

    def __init__(self, encoder, decoder, input_shape, output_shape,
                 bridge=None, generator=None):
        if input_shape is None or output_shape is None:
            raise TypeError("input_shape and output_shape cannot be None")
        self.encoder = encoder
        self.decoder = decoder
        self.input_shape_ = list(input_shape)
        self.output_shape_ = list(output_shape)
        self.bridge = bridge
        self.generator = generator
        self._record_config(input_shape_=self.input_shape_,
                            output_shape_=self.output_shape_)
        self.model = self.build_model()

    def build_model(self):
        from ...pipeline.api.keras.engine.base import Input

        encoder_input = Input(shape=tuple(self.input_shape_),
                              name="encoder_input")
        decoder_input = Input(shape=tuple(self.output_shape_),
                              name="decoder_input")
        enc_outs = self.encoder(encoder_input)
        states = list(enc_outs[1:])
        if self.bridge is not None:
            self.bridge.n_states = len(states)
            mapped = self.bridge(states)
            states = list(mapped) if isinstance(mapped, tuple) else [mapped]
        dec_out = self.decoder([decoder_input] + states)
        out = self.generator(dec_out) if self.generator is not None \
            else dec_out
        return Model([encoder_input, decoder_input], out)

    def infer(self, input, start_sign, max_seq_len=30, stop_sign=None,
              build_output=None):
        """Greedy decode (Seq2seq.scala:114-160), cached.

        * input: (T_in, feat) or (B, T_in, feat) encoder input.
        * start_sign: (feat,) tensor fed as the first decoder step.
        * stop_sign: stop early when the newest prediction matches
          (per sequence: a finished row repeats its stop token while the
          rest of the batch keeps decoding).
        * build_output: optional callable mapping the model output
          sequence (e.g. a Dense over hidden) before selecting the last
          timestep.

        Returns the decoded sequence (B, T_out, ...) including
        start_sign. Matches ``infer_reference`` exactly, but runs the
        encoder once and advances the decoder one timestep per token by
        carrying the recurrent states — O(T) total instead of the
        reference loop's O(T^2) full-prefix re-decode per token.
        """
        input = np.asarray(input, np.float32)
        if input.ndim == len(self.input_shape_):
            input = input[None]
        params, _ = self.model._params_tuple()
        enc_outs = self.encoder.call(params[self.encoder.name],
                                     jnp.asarray(input), training=False)
        states = list(enc_outs[1:])
        if self.bridge is not None:
            mapped = self.bridge.call(params[self.bridge.name], states,
                                      training=False)
            states = list(mapped) if isinstance(mapped, tuple) \
                else [mapped]

        step = getattr(self, "_decode_step", None)
        if step is None:
            dec, gen = self.decoder, self.generator

            def _step(params, xt, states):
                y, new_states = dec.step(params[dec.name], xt, states)
                out = y[:, None]
                if gen is not None:
                    out = gen.call(params[gen.name], out, training=False)
                return out, new_states

            step = self._decode_step = jax.jit(_step)

        b = input.shape[0]
        start = np.asarray(start_sign, np.float32)[None, None]
        cur = np.broadcast_to(start,
                              (b, 1) + start.shape[2:]).copy()
        outs = [cur]
        xt = jnp.asarray(cur[:, 0])
        stop = None if stop_sign is None \
            else np.asarray(stop_sign, np.float32)
        done = np.zeros((b,), bool)
        for _ in range(max_seq_len):
            out, states = step(params, xt, states)
            out_np = np.asarray(out)
            if build_output is not None:
                out_np = np.asarray(build_output(out_np))
            nxt = out_np[:, -1:]
            if done.any():
                # frozen rows repeat their stop token; their recurrent
                # states keep advancing but the outputs are pinned
                nxt = np.where(done.reshape((b,) + (1,) * (nxt.ndim - 1)),
                               outs[-1][:, -1:], nxt)
            outs.append(nxt)
            if stop is not None:
                done |= np.array([np.allclose(nxt[i, 0], stop, atol=1e-8)
                                  for i in range(b)])
                if done.all():
                    break
            xt = jnp.asarray(nxt[:, 0])
        return np.concatenate(outs, axis=1)

    def infer_reference(self, input, start_sign, max_seq_len=30,
                        stop_sign=None, build_output=None):
        """The reference's per-token full-model re-predict loop — kept
        as the parity oracle for ``infer`` (and for its exact batch-1
        early-stop semantics)."""
        input = np.asarray(input, np.float32)
        if input.ndim == len(self.input_shape_):
            input = input[None]
        start = np.asarray(start_sign, np.float32)[None, None]  # (1,1,feat)
        cur = start
        for _ in range(max_seq_len):
            pred_seq = self.model.predict([input, cur], batch_size=1)
            if build_output is not None:
                pred_seq = build_output(pred_seq)
            nxt = np.asarray(pred_seq)[:, -1:]
            cur = np.concatenate([cur, nxt], axis=1)
            if stop_sign is not None and np.allclose(
                    nxt[0, 0], np.asarray(stop_sign, np.float32), atol=1e-8):
                break
        return cur
