"""Async trial executor: keeps the worker pool saturated during a search.

The execution half of distributed AutoML (policy lives in
:mod:`analytics_zoo_tpu.automl.scheduler`).  The batch-synchronous engines
submit every trial up front and block on all refs at once; this executor
instead runs *segments* — "train trial T for B more epochs from its
checkpoint, report val loss" — as an as-completed stream over
:class:`~analytics_zoo_tpu.ray.RayContext` remote tasks:

* a slot frees up → the next runnable segment is submitted immediately
  (``RayContext.wait(num_returns=1)``), so ASHA's async promotions keep
  every worker busy with no rung barrier;
* a segment reaching its rung boundary checkpoints the forecaster params
  under ``<workdir>/trial-<id>/weights.npz`` plus a ``progress.json``
  sidecar (cumulative epochs + a fresh cache token; both atomic
  renames); a promoted trial's next segment resumes from that checkpoint
  instead of retraining from scratch (optimizer moments restart per
  segment — the params do not);
* a segment whose worker process died (``WorkerLostError``) is requeued
  **exactly once** — same trial, same budget, resumed from the last
  committed checkpoint; a second loss (or a task-raised error, or a
  non-finite val loss) marks the trial ``failed`` without aborting the
  search;
* every trial is finalized exactly once; ``stats`` carries the full
  accounting (per-state counts, requeues, max observed concurrency,
  worker pids) so chaos legs can assert exactly-once.

With no ``ray_ctx`` the executor owns a private local spawn pool (a
CPU-pinned ``RayContext`` sized to ``max_concurrent``); ``serial=True``
runs segments inline in the driver process — deterministic, for tests.
Checkpoints assume workers share the driver's filesystem (one host, or a
shared mount on a multi-host cluster).
"""

from __future__ import annotations

import logging
import math
import os
import shutil
import tempfile
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..utils import telemetry
from .scheduler import COMPLETE, PROMOTE, STOP, TrialScheduler

logger = logging.getLogger("analytics_zoo_tpu.automl")


#: worker-local model cache: (ckpt_dir, trial_id) -> (forecaster,
#: progress token at our last save).  A promoted trial that lands on
#: the worker that ran its previous segment reuses the live model —
#: skipping rebuild, recompile (jit traces are per-model-instance, so a
#: rebuilt model always recompiles) and the checkpoint load.  The cached
#: entry is only trusted while the trial's ``progress.json`` sidecar
#: still carries the random token we wrote at save time; if another
#: worker committed an intermediate segment (requeue after a kill), its
#: save rolled the token and we fall back to the authoritative
#: checkpoint.  (A stat-based check would be fooled by same-size
#: checkpoints landing within one mtime granule on coarse filesystems.)
_MODEL_CACHE: Dict[tuple, tuple] = {}
_MODEL_CACHE_CAP = 32


def _progress_path(ckpt: str) -> str:
    return os.path.join(os.path.dirname(ckpt), "progress.json")


def _read_progress(ckpt: str) -> Optional[Dict]:
    """The trial's committed progress sidecar, or None if absent/torn."""
    import json

    try:
        with open(_progress_path(ckpt)) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def _write_progress(ckpt: str, epochs: int) -> str:
    """Atomically commit {cumulative epochs, fresh token}; returns the
    token (the model-cache validity key for this checkpoint state)."""
    import json
    import uuid

    token = uuid.uuid4().hex
    path = _progress_path(ckpt)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump({"epochs": int(epochs), "token": token}, fh)
    os.replace(tmp, path)
    return token


def run_trial_segment(trial_id: int, config: Dict, budget_epochs: int,
                      data: Tuple, ckpt_dir: Optional[str],
                      start_epochs: int = 0) -> Dict:
    """Train one forecaster segment (runs inside a worker process).

    Builds the config's forecaster (or reuses the worker's still-warm
    model from the trial's previous segment), resumes params from the
    trial's checkpoint when one exists, trains up to ``budget_epochs``
    more epochs, evaluates, and commits checkpoint + progress sidecar
    (both atomic renames) before returning — so a worker killed
    mid-segment leaves the previous commit intact and the segment can
    be requeued as-is.

    ``start_epochs`` is the cumulative epoch count the driver has
    accounted for this trial.  If the sidecar already records
    ``start_epochs + budget_epochs`` — the previous attempt committed
    its checkpoint but its worker died before the result reached the
    driver — the rerun trains 0 extra epochs (evaluate only), so a
    requeued trial never accrues epochs beyond its rung and its rung
    comparison against peers stays fair.
    """
    from .forecaster import build_forecaster

    x_train, y_train, x_val, y_val = data
    t0 = time.time()
    cfg = dict(config)
    batch_size = int(cfg.pop("batch_size", 32))
    cfg.pop("epochs", None)   # budgets come from the scheduler, not cfg
    with telemetry.span("automl/trial_segment", trial=trial_id,
                        epochs=int(budget_epochs)):
        ckpt = None if ckpt_dir is None else os.path.join(
            ckpt_dir, f"trial-{trial_id}", "weights.npz")
        target = int(start_epochs) + int(budget_epochs)
        progress = None
        f = None
        resumed = False
        cached = False
        if ckpt is not None and os.path.exists(ckpt):
            progress = _read_progress(ckpt)
            entry = _MODEL_CACHE.get((ckpt_dir, trial_id))
            if (entry is not None and progress is not None
                    and entry[1] is not None
                    and entry[1] == progress.get("token")):
                f = entry[0]
                resumed = cached = True
        if f is None:
            f = build_forecaster(lookback=x_train.shape[1],
                                 feature_dim=x_train.shape[2],
                                 horizon=y_train.shape[1], **cfg)
            if ckpt is not None and os.path.exists(ckpt):
                f.load_params(ckpt)
                resumed = True
        # epochs already committed on disk; a checkpoint without a
        # sidecar (or a fresh trial) is assumed exactly at start_epochs
        done = int(start_epochs)
        if resumed and progress is not None:
            done = int(progress.get("epochs", start_epochs))
        train_epochs = min(int(budget_epochs), max(0, target - done))
        if train_epochs:
            f.fit(x_train, y_train, batch_size=batch_size,
                  epochs=train_epochs)
        metrics = f.evaluate(x_val, y_val, batch_size=batch_size)
        loss = float(metrics["loss"] if isinstance(metrics, dict)
                     else metrics)
        if ckpt is not None and train_epochs:
            f.save_params(ckpt)
            token = _write_progress(ckpt, max(done, target))
            while len(_MODEL_CACHE) >= _MODEL_CACHE_CAP:
                _MODEL_CACHE.pop(next(iter(_MODEL_CACHE)))
            _MODEL_CACHE[(ckpt_dir, trial_id)] = (f, token)
    return {"trial_id": trial_id, "val_loss": loss,
            "epochs": train_epochs, "resumed": resumed,
            "cached": cached, "seconds": time.time() - t0,
            "pid": os.getpid()}


def _finite(v) -> bool:
    try:
        return math.isfinite(float(v))
    except (TypeError, ValueError):
        return False


class _Trial:
    __slots__ = ("trial_id", "config", "state", "val_loss", "epochs",
                 "segments", "requeues", "seconds", "error", "pids",
                 "resumed_segments", "budget_done")

    def __init__(self, trial_id: int, config: Dict):
        self.trial_id = trial_id
        self.config = config
        self.state = "pending"    # pending|running|completed|stopped|failed
        self.val_loss: Optional[float] = None
        self.epochs = 0
        self.budget_done = 0      # cumulative budget of handled segments
        self.segments = 0
        self.requeues = 0
        self.seconds = 0.0
        self.error: Optional[str] = None
        self.pids: List[int] = []
        self.resumed_segments = 0

    def to_dict(self) -> Dict:
        return {"trial_id": self.trial_id, "config": self.config,
                "state": self.state, "val_loss": self.val_loss,
                "epochs": self.epochs, "segments": self.segments,
                "requeues": self.requeues,
                "resumed_segments": self.resumed_segments,
                "seconds": round(self.seconds, 3), "error": self.error,
                "pids": self.pids}


class AsyncTrialExecutor:
    """Drive a set of trial configs through a :class:`TrialScheduler`.

    Parameters
    ----------
    scheduler: the budget policy (``AshaScheduler``,
        ``RunToCompletionScheduler``, ...). Stateful; one per search.
    ray_ctx: an initialized RayContext to run segments on.  ``None`` →
        the executor owns a private CPU-pinned pool of
        ``max_concurrent`` spawn workers for the duration of ``run()``.
    max_concurrent: submission cap (and private-pool size).  With an
        external ``ray_ctx`` it defaults to the context's worker count.
    workdir: checkpoint root.  ``None`` → a private temp dir, removed
        after the search.
    trial_fn: segment function ``(trial_id, config, budget, data,
        ckpt_dir, start_epochs) -> {"val_loss": ..., ...}``; defaults
        to :func:`run_trial_segment`.  Swappable so chaos tests can run
        cheap stub segments.
    max_requeues: worker-loss requeue budget per trial (default 1 —
        "requeue exactly once").
    serial: run segments inline in the driver (deterministic tests).
    """

    def __init__(self, scheduler: TrialScheduler, ray_ctx=None,
                 max_concurrent: Optional[int] = None,
                 workdir: Optional[str] = None,
                 trial_fn: Optional[Callable] = None,
                 max_requeues: int = 1, serial: bool = False,
                 platform: str = "cpu"):
        self.scheduler = scheduler
        self.ray_ctx = ray_ctx
        if max_concurrent is None:
            max_concurrent = getattr(ray_ctx, "num_workers", None) or 2
        self.max_concurrent = max(1, int(max_concurrent))
        self.workdir = workdir
        self.trial_fn = trial_fn or run_trial_segment
        self.max_requeues = int(max_requeues)
        self.serial = bool(serial)
        self.platform = platform
        self.trials: List[_Trial] = []
        self.stats: Dict = {}

    # ------------------------------------------------------------------
    def run(self, configs: Sequence[Dict], data: Tuple) -> List[Dict]:
        self.trials = [_Trial(i, dict(c)) for i, c in enumerate(configs)]
        self.stats = {"trials": len(self.trials), "segments": 0,
                      "requeued": 0, "max_concurrent": 0,
                      "worker_pids": set(), "epochs_trained": 0,
                      "finalized": 0, "cached_segments": 0}
        owns_workdir = self.workdir is None
        workdir = self.workdir or tempfile.mkdtemp(prefix="zoo-automl-")
        runnable: deque = deque(
            (t.trial_id, self.scheduler.initial_budget())
            for t in self.trials)
        try:
            with telemetry.span("automl/search", trials=len(self.trials),
                                mode="serial" if self.serial else "pool"):
                if self.serial:
                    self._run_serial(runnable, data, workdir)
                else:
                    self._run_pool(runnable, data, workdir)
        finally:
            if owns_workdir:
                shutil.rmtree(workdir, ignore_errors=True)
        # exactly-once: every trial reached a terminal state, once
        counts = {"completed": 0, "stopped": 0, "failed": 0}
        for t in self.trials:
            if t.state not in counts:
                raise RuntimeError(
                    f"trial {t.trial_id} ended in non-terminal state "
                    f"{t.state!r} — executor accounting bug")
            counts[t.state] += 1
        if self.stats["finalized"] != len(self.trials):
            raise RuntimeError(
                f"finalized {self.stats['finalized']} of "
                f"{len(self.trials)} trials — executor accounting bug")
        self.stats.update(counts)
        self.stats["worker_pids"] = sorted(self.stats["worker_pids"])
        self.stats["early_stopped_fraction"] = (
            counts["stopped"] / max(1, len(self.trials)))
        return [t.to_dict() for t in self.trials]

    # ------------------------------------------------------------------
    def _run_serial(self, runnable, data, workdir):
        while runnable:
            trial_id, budget = runnable.popleft()
            trial = self.trials[trial_id]
            trial.state = "running"
            self.stats["segments"] += 1
            self.stats["max_concurrent"] = max(
                self.stats["max_concurrent"], 1)
            try:
                result = self.trial_fn(trial_id, trial.config, budget,
                                       data, workdir, trial.budget_done)
            except Exception as e:  # noqa: BLE001 - record, keep going
                self._finalize(trial, "failed",
                               error=f"{type(e).__name__}: {e}")
                continue
            self._handle_result(trial, budget, result, runnable)

    def _run_pool(self, runnable, data, workdir):
        ctx = self.ray_ctx
        owns_ctx = ctx is None
        if owns_ctx:
            from ..ray import RayContext
            ctx = RayContext(num_ray_nodes=self.max_concurrent,
                             ray_node_cpu_cores=1,
                             platform=self.platform).init()
        from ..ray import RemoteTaskError, WorkerLostError

        inflight: Dict[str, tuple] = {}   # task_id -> (ref, tid, budget)
        try:
            while runnable or inflight:
                while runnable and len(inflight) < self.max_concurrent:
                    trial_id, budget = runnable.popleft()
                    trial = self.trials[trial_id]
                    trial.state = "running"
                    ref = ctx.remote(self.trial_fn).remote(
                        trial_id, trial.config, budget, data, workdir,
                        trial.budget_done)
                    inflight[ref.task_id] = (ref, trial_id, budget)
                    self.stats["segments"] += 1
                self.stats["max_concurrent"] = max(
                    self.stats["max_concurrent"], len(inflight))
                ready, _ = ctx.wait([e[0] for e in inflight.values()],
                                    num_returns=1)
                for ref in ready:
                    _, trial_id, budget = inflight.pop(ref.task_id)
                    trial = self.trials[trial_id]
                    try:
                        result = ctx.get(ref)
                    except WorkerLostError as e:
                        if trial.requeues < self.max_requeues:
                            # same trial, same budget, same start_epochs:
                            # the rerun resumes from the last committed
                            # checkpoint, and the progress sidecar caps
                            # it at the rung budget — if the dead worker
                            # committed before the result got out, the
                            # rerun skips straight to evaluate
                            trial.requeues += 1
                            self.stats["requeued"] += 1
                            telemetry.counter(
                                "zoo_automl_requeued_total").inc()
                            telemetry.event("automl/segment_requeued",
                                            trial=trial_id)
                            runnable.append((trial_id, budget))
                        else:
                            self._finalize(
                                trial, "failed",
                                error=f"worker lost twice: {e}")
                    except RemoteTaskError as e:
                        self._finalize(
                            trial, "failed",
                            error=str(e).splitlines()[0][:300])
                    else:
                        self._handle_result(trial, budget, result,
                                            runnable)
        finally:
            if owns_ctx:
                ctx.stop()

    # ------------------------------------------------------------------
    def _handle_result(self, trial: _Trial, budget: int, result: Dict,
                       runnable) -> None:
        trial.segments += 1
        trial.budget_done += int(budget)
        trial.epochs += int(result.get("epochs", budget))
        trial.seconds += float(result.get("seconds", 0.0))
        if result.get("resumed"):
            trial.resumed_segments += 1
        if result.get("cached"):
            self.stats["cached_segments"] += 1
        pid = result.get("pid")
        if pid is not None:
            trial.pids.append(pid)
            self.stats["worker_pids"].add(pid)
        self.stats["epochs_trained"] += int(result.get("epochs", budget))
        val = result.get("val_loss")
        if not _finite(val):
            # a diverged trial (NaN/Inf) must neither win the search nor
            # poison the rung cutoffs — failed, excluded from best
            self._finalize(trial, "failed",
                           error=f"non-finite val_loss: {val!r}")
            return
        trial.val_loss = float(val)   # latest rung = highest budget
        decision = self.scheduler.on_report(trial.trial_id, float(val))
        telemetry.counter("zoo_automl_rung_decisions_total",
                          decision=decision.action).inc()
        telemetry.event("automl/rung_report", trial=trial.trial_id,
                        rung=decision.rung, val_loss=float(val),
                        decision=decision.action)
        if decision.action == PROMOTE:
            runnable.append((trial.trial_id, decision.budget))
        elif decision.action == STOP:
            self._finalize(trial, "stopped")
        elif decision.action == COMPLETE:
            self._finalize(trial, "completed")
        else:
            raise RuntimeError(
                f"scheduler returned unknown action {decision.action!r}")

    def _finalize(self, trial: _Trial, state: str, error: str = None):
        if trial.state in ("completed", "stopped", "failed"):
            raise RuntimeError(
                f"trial {trial.trial_id} finalized twice "
                f"({trial.state} -> {state}) — executor accounting bug")
        trial.state = state
        trial.error = error
        self.stats["finalized"] += 1
        telemetry.counter("zoo_automl_trials_total", state=state).inc()
        if error:
            logger.warning("trial %d %s: %s", trial.trial_id, state,
                           error)
