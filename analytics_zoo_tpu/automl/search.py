"""Hyperparameter search over the RayContext runtime.

The reference's AutoML (off-tree ``automl`` branch; SURVEY.md §2.8 build-plan
item 10) searches forecaster configs with Ray Tune on a RayOnSpark cluster.
TPU-native rebuild: search-space primitives + random/grid engines that
dispatch one trial per task onto :class:`analytics_zoo_tpu.ray.RayContext`
workers (separate processes, CPU-pinned jax), with the driver collecting
(config, val_loss) pairs and refitting the best config.
"""

from __future__ import annotations

import itertools
import logging
import math
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils import telemetry

logger = logging.getLogger("analytics_zoo_tpu.automl")


# ---------------------------------------------------------------------------
# search-space primitives (hp.* equivalents)
# ---------------------------------------------------------------------------

class Choice:
    def __init__(self, options: Sequence):
        self.options = list(options)

    def sample(self, rng):
        return self.options[int(rng.integers(len(self.options)))]

    def grid(self):
        return self.options


class Uniform:
    def __init__(self, low: float, high: float):
        self.low, self.high = float(low), float(high)

    def sample(self, rng):
        return float(rng.uniform(self.low, self.high))

    def grid(self):
        return [self.low, (self.low + self.high) / 2, self.high]


class RandInt:
    def __init__(self, low: int, high: int):
        self.low, self.high = int(low), int(high)

    def sample(self, rng):
        return int(rng.integers(self.low, self.high + 1))

    def grid(self):
        return list(range(self.low, self.high + 1))


def sample_config(space: Dict, rng) -> Dict:
    return {k: (v.sample(rng) if hasattr(v, "sample") else v)
            for k, v in space.items()}


#: default ceiling on grid enumeration — a wide ``RandInt`` silently
#: cross-products into thousands of full-budget trials otherwise
DEFAULT_GRID_LIMIT = 256


def grid_configs(space: Dict, limit: Optional[int] = DEFAULT_GRID_LIMIT
                 ) -> List[Dict]:
    keys, values = [], []
    for k, v in space.items():
        keys.append(k)
        values.append(v.grid() if hasattr(v, "grid") else [v])
    total = 1
    for vals in values:
        total *= len(vals)
    if limit is not None and total > limit:
        raise ValueError(
            f"grid search would enumerate {total} trials "
            f"(> max_grid_trials={limit}); narrow the space or use the "
            f"'random' or 'asha' engines, which sample a fixed "
            f"num_samples instead of cross-producting every dimension")
    return [dict(zip(keys, combo)) for combo in itertools.product(*values)]


# ---------------------------------------------------------------------------
# trial fn (runs inside a worker process)
# ---------------------------------------------------------------------------

def run_trial(config: Dict, x_train, y_train, x_val, y_val) -> Dict:
    """Train one forecaster config; returns {config, val_loss, seconds}."""
    from .forecaster import build_forecaster

    t0 = time.time()
    cfg = dict(config)
    batch_size = int(cfg.pop("batch_size", 32))
    epochs = int(cfg.pop("epochs", 1))
    f = build_forecaster(lookback=x_train.shape[1],
                         feature_dim=x_train.shape[2],
                         horizon=y_train.shape[1], **cfg)
    f.fit(x_train, y_train, batch_size=batch_size, epochs=epochs)
    metrics = f.evaluate(x_val, y_val, batch_size=batch_size)
    loss = float(metrics["loss"] if isinstance(metrics, dict) else metrics)
    return {"config": config, "val_loss": loss,
            "seconds": time.time() - t0}


# ---------------------------------------------------------------------------
# engines
# ---------------------------------------------------------------------------

def select_best(trials: Sequence[Dict]) -> Dict:
    """Best finite-loss trial; non-finite/failed trials never win.

    A diverged trial reports NaN/Inf val loss — ``min()`` over raw
    values lets NaN win the search (NaN comparisons are False both
    ways).  Trials without a finite ``val_loss`` (or already marked
    ``failed``) are excluded; if *every* trial failed the search raises
    instead of returning garbage.
    """
    eligible = []
    for t in trials:
        loss = t.get("val_loss")
        finite = loss is not None and math.isfinite(float(loss))
        if t.get("state") not in ("failed",) and finite:
            eligible.append(t)
        elif "state" not in t:
            t["state"] = "failed"
    if not eligible:
        errors = [str(t.get("error") or f"val_loss={t.get('val_loss')!r}")
                  for t in trials[:5]]
        raise RuntimeError(
            f"all {len(trials)} trials failed — no finite val_loss to "
            f"select from (first errors: {errors})")
    best = min(eligible, key=lambda t: float(t["val_loss"]))
    telemetry.gauge("zoo_automl_best_val_loss").set(
        float(best["val_loss"]))
    return best


class _EngineBase:
    def __init__(self, ray_ctx=None):
        self.ray_ctx = ray_ctx
        self.trials: List[Dict] = []
        self.stats: Dict = {}

    def _configs(self, space, num_samples, seed) -> List[Dict]:
        raise NotImplementedError

    def run(self, space: Dict, data: Tuple, num_samples: int = 4,
            epochs: int = 1, seed: int = 0) -> Dict:
        """data = (x_train, y_train, x_val, y_val). Returns the best trial."""
        x_train, y_train, x_val, y_val = data
        configs = self._configs(space, num_samples, seed)
        for c in configs:
            c.setdefault("epochs", epochs)
        if self.ray_ctx is not None and not self.ray_ctx.stopped:
            refs = [self.ray_ctx.remote(run_trial).remote(
                c, x_train, y_train, x_val, y_val) for c in configs]
            self.trials = self.ray_ctx.get(refs)
        else:
            self.trials = [run_trial(c, x_train, y_train, x_val, y_val)
                           for c in configs]
        best = select_best(self.trials)
        logger.info("search done: %d trials, best %.5f %s",
                    len(self.trials), best["val_loss"], best["config"])
        return best


class RandomSearchEngine(_EngineBase):
    def _configs(self, space, num_samples, seed):
        rng = np.random.default_rng(seed)
        return [sample_config(space, rng) for _ in range(num_samples)]


class GridSearchEngine(_EngineBase):
    def __init__(self, ray_ctx=None, max_grid_trials: int =
                 DEFAULT_GRID_LIMIT):
        super().__init__(ray_ctx)
        self.max_grid_trials = max_grid_trials

    def _configs(self, space, num_samples, seed):
        return grid_configs(space, limit=self.max_grid_trials)


class AshaSearchEngine(_EngineBase):
    """Asynchronous successive halving over the async executor.

    Samples ``num_samples`` configs like random search, but instead of
    training each to the full budget it drives them through
    :class:`~analytics_zoo_tpu.automl.scheduler.AshaScheduler` rungs at
    ``min_epochs·η^k`` epochs on an
    :class:`~analytics_zoo_tpu.automl.executor.AsyncTrialExecutor`:
    trials report at rung boundaries, losers stop early, winners resume
    from their checkpoint — same trial budget as random, a fraction of
    the trained epochs, so best-val-loss per wall-hour scales with the
    worker pool instead of the slowest bracket (docs/automl.md).

    ``epochs`` (from the recipe) is the *maximum* per-trial budget R;
    ``min_epochs`` (r) and ``reduction_factor`` (η) shape the rungs.
    """

    def __init__(self, ray_ctx=None, min_epochs: int = 1,
                 reduction_factor: int = 3,
                 max_concurrent: Optional[int] = None,
                 workdir: Optional[str] = None, serial: bool = False):
        super().__init__(ray_ctx)
        self.min_epochs = int(min_epochs)
        self.reduction_factor = int(reduction_factor)
        self.max_concurrent = max_concurrent
        self.workdir = workdir
        self.serial = serial

    def run(self, space: Dict, data: Tuple, num_samples: int = 4,
            epochs: int = 1, seed: int = 0) -> Dict:
        from .executor import AsyncTrialExecutor
        from .scheduler import AshaScheduler

        rng = np.random.default_rng(seed)
        configs = [sample_config(space, rng) for _ in range(num_samples)]
        for c in configs:
            # segment budgets come from the scheduler
            # (run_trial_segment pops this key), but the winning config
            # must still carry the full budget so AutoForecaster's final
            # refit trains recipe.epochs, not the 1-epoch fallback
            c.setdefault("epochs", epochs)
        scheduler = AshaScheduler(
            max_epochs=epochs, min_epochs=min(self.min_epochs, epochs),
            reduction_factor=self.reduction_factor)
        executor = AsyncTrialExecutor(
            scheduler, ray_ctx=self.ray_ctx,
            max_concurrent=self.max_concurrent, workdir=self.workdir,
            serial=self.serial)
        self.trials = executor.run(configs, data)
        self.stats = dict(executor.stats, rungs=scheduler.rungs())
        best = select_best(self.trials)
        logger.info(
            "asha done: %d trials (%d completed / %d stopped / %d "
            "failed), %d epochs trained, best %.5f %s",
            len(self.trials), self.stats.get("completed", 0),
            self.stats.get("stopped", 0), self.stats.get("failed", 0),
            self.stats.get("epochs_trained", 0), best["val_loss"],
            best["config"])
        return best


# ---------------------------------------------------------------------------
# facade
# ---------------------------------------------------------------------------

class AutoForecaster:
    """AutoTSTrainer-style facade: search a recipe, refit the winner.

    >>> auto = AutoForecaster(recipe=LSTMRandomRecipe(num_samples=4),
    ...                       ray_ctx=ctx)
    >>> pipeline = auto.fit(series, lookback=24, horizon=1)
    >>> preds = pipeline.predict(x)
    """

    #: engine name -> class; ``AutoForecaster(engine=...)`` validates
    #: against this map instead of silently defaulting unknowns to grid
    ENGINES = {"random": RandomSearchEngine, "grid": GridSearchEngine,
               "asha": AshaSearchEngine}

    def __init__(self, recipe, ray_ctx=None, engine: str = "random",
                 **engine_kwargs):
        self.recipe = recipe
        cls = self.ENGINES.get(engine)
        if cls is None:
            raise ValueError(
                f"unknown search engine {engine!r}; valid engines: "
                f"{sorted(self.ENGINES)}")
        self.engine = cls(ray_ctx, **engine_kwargs)
        self.best_trial: Optional[Dict] = None
        self.forecaster = None

    def fit(self, series: np.ndarray, lookback: int, horizon: int = 1,
            val_ratio: float = 0.2, seed: int = 0):
        from .feature import Scaler, rolling_window, train_val_split
        from .forecaster import build_forecaster

        self.scaler = Scaler()
        scaled = self.scaler.fit_transform(series)
        x, y = rolling_window(scaled, lookback, horizon)
        (x_tr, y_tr), (x_val, y_val) = train_val_split(x, y, val_ratio)
        self.best_trial = self.engine.run(
            self.recipe.search_space(), (x_tr, y_tr, x_val, y_val),
            num_samples=self.recipe.num_samples, epochs=self.recipe.epochs,
            seed=seed)
        # refit the winning config on the full window set (driver process);
        # fall back to the recipe's budget if the config lacks "epochs"
        # (an engine that strips it must not shrink the refit to 1 epoch)
        cfg = dict(self.best_trial["config"])
        batch_size = int(cfg.pop("batch_size", 32))
        epochs = int(cfg.pop("epochs",
                             getattr(self.recipe, "epochs", 1)))
        self.forecaster = build_forecaster(
            lookback=lookback, feature_dim=x.shape[2], horizon=horizon,
            **cfg)
        self.forecaster.fit(x, y, batch_size=batch_size, epochs=epochs)
        return self

    def predict(self, x):
        if self.forecaster is None:
            raise RuntimeError("call fit() first")
        return self.scaler.inverse_transform_y(self.forecaster.predict(x))

    def evaluate(self, x, y):
        if self.forecaster is None:
            raise RuntimeError("call fit() first")
        return self.forecaster.evaluate(x, y)
