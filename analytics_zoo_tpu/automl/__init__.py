from .executor import AsyncTrialExecutor, run_trial_segment
from .feature import rolling_window, train_val_split, Scaler
from .forecaster import LSTMForecaster, TCNForecaster, build_forecaster
from .recipe import LSTMRandomRecipe, TCNRandomRecipe, Recipe
from .scheduler import (AshaScheduler, Decision, RunToCompletionScheduler,
                        TrialScheduler)
from .search import (AshaSearchEngine, AutoForecaster, Choice,
                     GridSearchEngine, RandInt, RandomSearchEngine, Uniform,
                     grid_configs, select_best)

__all__ = ["rolling_window", "train_val_split", "Scaler", "LSTMForecaster",
           "TCNForecaster", "build_forecaster", "Recipe", "LSTMRandomRecipe",
           "TCNRandomRecipe", "AutoForecaster", "Choice", "Uniform",
           "RandInt", "RandomSearchEngine", "GridSearchEngine",
           "AshaSearchEngine", "grid_configs", "select_best",
           "AshaScheduler", "Decision", "RunToCompletionScheduler",
           "TrialScheduler", "AsyncTrialExecutor", "run_trial_segment"]
