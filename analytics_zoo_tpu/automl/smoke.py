"""Distributed-AutoML end-to-end smoke (``scripts/automl-smoke``; CI fast tier).

Proves the async-search contract with the production executor and a real
SIGKILL — the knobs the bench leg measures, asserted cheaply:

1. An 8-trial ASHA search fans across **two local spawn workers**
   (private :class:`~analytics_zoo_tpu.ray.RayContext` pool).
2. One worker is SIGKILLed the moment it claims a segment; the orphaned
   segment must be **requeued exactly once** and finish on the survivor.
3. Exactly-once accounting holds: every trial terminal, ``finalized ==
   trials``, at least one trial early-stopped, the best val loss finite.

Trial segments are deterministic stubs (loss shrinks with budget), so
the smoke exercises scheduling/execution/fault paths in seconds without
training; the bench ``automl`` leg covers real forecaster training.

Exit 0 and ``AUTOML_SMOKE_OK`` on success; 1 with the offending stat on
any violated assertion.
"""

from __future__ import annotations

import argparse
import math
import os
import signal
import sys
import tempfile
import threading
import time


def _stub_segment(trial_id, config, budget, data, ckpt_dir,
                  start_epochs=0):
    """Deterministic fake trial: announces its claim (pid file in the
    shared workdir, so the chaos thread can kill mid-segment), then
    reports a loss that improves with cumulative budget."""
    with open(os.path.join(ckpt_dir, f"claim-{os.getpid()}"), "w"):
        pass
    time.sleep(0.5)
    return {"trial_id": trial_id, "val_loss": config["v"] / (1 + budget),
            "epochs": budget, "seconds": 0.5, "pid": os.getpid()}


def run_smoke(n_trials: int = 8, kill: bool = True) -> int:
    from ..ray import RayContext
    from .executor import AsyncTrialExecutor
    from .scheduler import AshaScheduler

    ctx = RayContext(num_ray_nodes=2, ray_node_cpu_cores=1,
                     platform="cpu").init()
    workdir = tempfile.mkdtemp(prefix="zoo-automl-smoke-")
    victim = ctx._procs[0].pid
    try:
        if kill:
            def kill_on_claim():
                claim = os.path.join(workdir, f"claim-{victim}")
                deadline = time.time() + 60
                while not os.path.exists(claim) and \
                        time.time() < deadline:
                    time.sleep(0.02)
                os.kill(victim, signal.SIGKILL)
                print(f"automl-smoke: SIGKILLed worker {victim} "
                      f"mid-segment")
            threading.Thread(target=kill_on_claim, daemon=True).start()

        scheduler = AshaScheduler(max_epochs=9, min_epochs=1,
                                  reduction_factor=3)
        executor = AsyncTrialExecutor(
            scheduler, ray_ctx=ctx, max_concurrent=2,
            trial_fn=_stub_segment, workdir=workdir)
        # interleaved (non-monotone) quality order: with a descending
        # sequence, losing the single best trial to the mid-segment kill
        # requeues it behind the rest and every rung report arrives as a
        # new best — ASHA then promotes everything and the early_stopped
        # gate flakes on which worker claimed which trial first
        configs = [{"v": 0.5 + 0.37 * ((3 * i) % n_trials)}
                   for i in range(n_trials)]
        t0 = time.time()
        trials = executor.run(configs, data=None)
        wall = time.time() - t0
    finally:
        ctx.stop()
        import shutil
        shutil.rmtree(workdir, ignore_errors=True)

    stats = executor.stats
    best = min((t["val_loss"] for t in trials
                if t["val_loss"] is not None and
                math.isfinite(t["val_loss"])), default=float("nan"))
    print(f"automl-smoke: {stats['trials']} trials in {wall:.1f}s — "
          f"{stats['completed']} completed / {stats['stopped']} stopped "
          f"/ {stats['failed']} failed, {stats['requeued']} requeued, "
          f"max_concurrent={stats['max_concurrent']}, best={best:.4f}")

    checks = [
        ("finalized", stats["finalized"] == n_trials),
        ("terminal_states", all(t["state"] in
                                ("completed", "stopped", "failed")
                                for t in trials)),
        ("requeued_exactly_once", stats["requeued"] == (1 if kill else 0)),
        ("nothing_failed", stats["failed"] == 0),
        ("early_stopped", stats["stopped"] > 0),
        ("max_concurrent_2", stats["max_concurrent"] >= 2),
        ("best_finite", math.isfinite(best)),
    ]
    failed = [name for name, ok in checks if not ok]
    if failed:
        print(f"automl-smoke: FAILED {failed}; stats={stats}",
              file=sys.stderr)
        return 1
    print("AUTOML_SMOKE_OK")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="automl-smoke")
    ap.add_argument("--trials", type=int, default=8)
    ap.add_argument("--no-kill", action="store_true",
                    help="skip the worker-kill chaos leg")
    args = ap.parse_args(argv)
    return run_smoke(n_trials=args.trials, kill=not args.no_kill)


if __name__ == "__main__":
    sys.exit(main())
