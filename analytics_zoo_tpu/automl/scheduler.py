"""Trial schedulers: the promote-or-stop policy half of distributed AutoML.

The reference platform ran its forecaster search through Ray Tune, whose
trial schedulers separate *policy* (how long does a trial deserve to run)
from *execution* (where does it run).  This module rebuilds that seam:

* :class:`TrialScheduler` — the protocol.  A scheduler owns no processes
  and trains nothing; it is asked for a trial's first epoch budget and is
  told each validation result, answering with a :class:`Decision`.
* :class:`AshaScheduler` — asynchronous successive halving (ASHA; Li et
  al., MLSys 2020).  Rungs sit at cumulative budgets ``min_epochs·η^k``;
  a trial reaching a rung reports its val loss and is promoted iff it
  ranks in the top ``1/η`` of the results *recorded at that rung so far*
  — no synchronization barrier, so early reporters promote optimistically
  and the worker pool never idles waiting for a bracket to fill.
* :class:`RunToCompletionScheduler` — the degenerate policy (every trial
  gets its full budget up front, one rung, always complete) so
  random/grid-to-completion stays expressible through the same executor.

Policies here are pure and single-threaded: the executor
(:mod:`analytics_zoo_tpu.automl.executor`) serializes calls into them.
"""

from __future__ import annotations

import bisect
from typing import Dict, List, Optional

#: Decision actions.
PROMOTE = "promote"    # run the trial for ``budget`` more epochs
STOP = "stop"          # early-stop: rank at the rung did not make the cut
COMPLETE = "complete"  # trial reached the top rung — done, keep result


class Decision:
    """What to do with a trial after it reported at a rung boundary."""

    __slots__ = ("action", "budget", "rung")

    def __init__(self, action: str, budget: int = 0, rung: int = 0):
        self.action = action
        self.budget = int(budget)   # additional epochs (promote only)
        self.rung = int(rung)       # rung index the report landed on

    def __repr__(self):
        return f"Decision({self.action}, budget={self.budget}, " \
               f"rung={self.rung})"


class TrialScheduler:
    """Protocol: epoch-budget policy for one search.

    ``initial_budget()`` is the epochs a fresh trial runs before its
    first report; ``on_report(trial_id, val_loss)`` records the result
    at the trial's current rung and returns a :class:`Decision`.  A
    scheduler instance is stateful per-search and must not be reused.
    """

    def initial_budget(self) -> int:
        raise NotImplementedError

    def on_report(self, trial_id, val_loss: float) -> Decision:
        raise NotImplementedError

    def rungs(self) -> List[int]:
        """Cumulative epoch budgets per rung (diagnostics/telemetry)."""
        raise NotImplementedError


class RunToCompletionScheduler(TrialScheduler):
    """Every trial trains its full budget, then completes (random/grid)."""

    def __init__(self, max_epochs: int):
        if max_epochs < 1:
            raise ValueError(f"max_epochs must be >= 1, got {max_epochs}")
        self.max_epochs = int(max_epochs)

    def initial_budget(self) -> int:
        return self.max_epochs

    def on_report(self, trial_id, val_loss: float) -> Decision:
        return Decision(COMPLETE, 0, 0)

    def rungs(self) -> List[int]:
        return [self.max_epochs]


class AshaScheduler(TrialScheduler):
    """Asynchronous successive halving over epoch rungs.

    Rungs are the cumulative budgets ``min_epochs * η^k`` clipped to
    ``max_epochs`` (e.g. ``min=1, η=3, max=9`` → rungs ``[1, 3, 9]``).
    On a report at rung ``k`` the value is recorded into that rung's
    history and the trial is promoted iff its rank is within
    ``max(1, n/η)`` of the ``n`` results recorded there so far (lower
    val loss = better).  The ``max(1, ...)`` floor is the standard async
    relaxation: the first reporter at a rung always promotes, so the
    search never deadlocks waiting for a full bracket — the price is a
    few optimistic promotions early on, exactly ASHA's trade.

    Non-finite values are never recorded (a diverged trial must not
    poison the cutoff) and always answer STOP.
    """

    def __init__(self, max_epochs: int, min_epochs: int = 1,
                 reduction_factor: int = 3):
        if min_epochs < 1:
            raise ValueError(f"min_epochs must be >= 1, got {min_epochs}")
        if reduction_factor < 2:
            raise ValueError("reduction_factor must be >= 2, got "
                             f"{reduction_factor}")
        if max_epochs < min_epochs:
            raise ValueError(f"max_epochs ({max_epochs}) < min_epochs "
                             f"({min_epochs})")
        self.eta = int(reduction_factor)
        self.min_epochs = int(min_epochs)
        self.max_epochs = int(max_epochs)
        self._rungs: List[int] = []
        budget = self.min_epochs
        while budget < self.max_epochs:
            self._rungs.append(budget)
            budget *= self.eta
        self._rungs.append(self.max_epochs)
        # recorded (finite) results per rung, kept sorted for rank lookup
        self._results: List[List[float]] = [[] for _ in self._rungs]
        self._trial_rung: Dict[object, int] = {}

    def rungs(self) -> List[int]:
        return list(self._rungs)

    def initial_budget(self) -> int:
        return self._rungs[0]

    def cutoff(self, rung: int) -> Optional[float]:
        """Largest value that would still promote at ``rung`` right now
        (None while the rung is empty — the next reporter promotes)."""
        recorded = self._results[rung]
        if not recorded:
            return None
        keep = max(1, len(recorded) // self.eta)
        return recorded[keep - 1]

    def on_report(self, trial_id, val_loss: float) -> Decision:
        rung = self._trial_rung.get(trial_id, 0)
        val_loss = float(val_loss)
        if val_loss != val_loss or val_loss in (float("inf"),
                                                float("-inf")):
            return Decision(STOP, 0, rung)
        recorded = self._results[rung]
        bisect.insort(recorded, val_loss)
        if rung == len(self._rungs) - 1:
            return Decision(COMPLETE, 0, rung)
        # keep-top-1/η over what this rung has seen SO FAR (async: no
        # waiting for the other trials to arrive at the rung)
        keep = max(1, len(recorded) // self.eta)
        rank = bisect.bisect_left(recorded, val_loss)
        if rank < keep:
            self._trial_rung[trial_id] = rung + 1
            return Decision(PROMOTE,
                            self._rungs[rung + 1] - self._rungs[rung],
                            rung)
        return Decision(STOP, 0, rung)
