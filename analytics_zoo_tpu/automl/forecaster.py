"""Time-series forecasters searched by the AutoML engine.

Capability target per BASELINE.md ("AutoML time-series forecaster
(LSTM/TCN, Ray-on-TPU)"); the reference implementation lives on the
off-tree ``automl`` branch, so these are spec-from-docs builds on the
in-repo Keras API: an LSTM forecaster and a causal dilated-conv (TCN)
forecaster, both ``(B, lookback, F) -> (B, horizon)``.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np


class _BaseForecaster:
    def __init__(self, lookback: int, feature_dim: int = 1,
                 horizon: int = 1, lr: float = 1e-3,
                 metrics: Sequence[str] = ("mae",)):
        self.lookback = lookback
        self.feature_dim = feature_dim
        self.horizon = horizon
        self.lr = lr
        self.metrics = list(metrics)
        self.model = self._build()

    def _build(self):
        raise NotImplementedError

    def _compile(self, model):
        from ..pipeline.api.keras.optimizers import Adam

        model.compile(optimizer=Adam(lr=self.lr), loss="mse",
                      metrics=self.metrics)
        return model

    def fit(self, x, y, batch_size: int = 32, epochs: int = 1,
            validation_data=None):
        self.model.fit(np.asarray(x, np.float32),
                       np.asarray(y, np.float32),
                       batch_size=batch_size, nb_epoch=epochs)
        return self

    def evaluate(self, x, y, batch_size: int = 32):
        return self.model.evaluate(np.asarray(x, np.float32),
                                   np.asarray(y, np.float32),
                                   batch_size=batch_size)

    def predict(self, x, batch_size: int = 128):
        return self.model.predict(np.asarray(x, np.float32),
                                  batch_size=batch_size)

    # -- checkpointing (ASHA pause/resume at rung boundaries) ----------
    def save_params(self, path: str):
        """Atomically checkpoint model weights to ``path`` (npz).

        Written via a file object — ``np.savez(str)`` appends ``.npz``
        to bare paths — then ``os.replace``d so a killed worker never
        leaves a torn checkpoint behind.
        """
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as fh:
            np.savez(fh, *self.model.get_weights())
        os.replace(tmp, path)
        return path

    def load_params(self, path: str):
        """Restore weights saved by :meth:`save_params`.

        Only weights round-trip; optimizer moments restart per segment —
        a known resume tradeoff documented in docs/automl.md.
        """
        with np.load(path) as data:
            weights = [data[k] for k in sorted(
                data.files, key=lambda n: int(n.split("_")[-1]))]
        self.model.set_weights(weights)
        return self


class LSTMForecaster(_BaseForecaster):
    """Stacked-LSTM regressor (automl-branch LSTMForecaster spec)."""

    def __init__(self, lookback: int, feature_dim: int = 1, horizon: int = 1,
                 lstm_units: Sequence[int] = (32, 16), dropout: float = 0.1,
                 lr: float = 1e-3, **kw):
        self.lstm_units = [int(u) for u in (
            lstm_units if isinstance(lstm_units, (list, tuple))
            else [lstm_units])]
        self.dropout = dropout
        super().__init__(lookback, feature_dim, horizon, lr, **kw)

    def _build(self):
        from ..pipeline.api.keras.layers import LSTM, Dense, Dropout
        from ..pipeline.api.keras.models import Sequential

        model = Sequential()
        for i, units in enumerate(self.lstm_units):
            last = i == len(self.lstm_units) - 1
            kw = {"input_shape": (self.lookback, self.feature_dim)} \
                if i == 0 else {}
            model.add(LSTM(units, return_sequences=not last, **kw))
            if self.dropout:
                model.add(Dropout(self.dropout))
        model.add(Dense(self.horizon))
        return self._compile(model)


class TCNForecaster(_BaseForecaster):
    """Causal dilated-conv forecaster (TCN spec: left-padded dilated
    stacks, exponentially growing receptive field)."""

    def __init__(self, lookback: int, feature_dim: int = 1, horizon: int = 1,
                 n_filters: int = 16, kernel_size: int = 3, n_blocks: int = 2,
                 dropout: float = 0.1, lr: float = 1e-3, **kw):
        self.n_filters = int(n_filters)
        self.kernel_size = int(kernel_size)
        self.n_blocks = int(n_blocks)
        self.dropout = dropout
        super().__init__(lookback, feature_dim, horizon, lr, **kw)

    def _build(self):
        from ..pipeline.api.keras.layers import (AtrousConvolution1D, Dense,
                                                 Dropout, Flatten,
                                                 ZeroPadding1D)
        from ..pipeline.api.keras.models import Sequential

        model = Sequential()
        in_shape = {"input_shape": (self.lookback, self.feature_dim)}
        for b in range(self.n_blocks):
            dilation = 2 ** b
            pad = (self.kernel_size - 1) * dilation
            model.add(ZeroPadding1D(padding=(pad, 0), **in_shape))
            in_shape = {}
            model.add(AtrousConvolution1D(self.n_filters, self.kernel_size,
                                          atrous_rate=dilation,
                                          activation="relu"))
            if self.dropout:
                model.add(Dropout(self.dropout))
        model.add(Flatten())
        model.add(Dense(self.horizon))
        return self._compile(model)


FORECASTERS = {"lstm": LSTMForecaster, "tcn": TCNForecaster}


def build_forecaster(model: str = "lstm", **config) -> _BaseForecaster:
    try:
        cls = FORECASTERS[model.lower()]
    except KeyError:
        raise ValueError(f"unknown forecaster {model!r}; "
                         f"choose from {sorted(FORECASTERS)}") from None
    import inspect

    allowed = set(inspect.signature(cls.__init__).parameters)
    return cls(**{k: v for k, v in config.items() if k in allowed})
