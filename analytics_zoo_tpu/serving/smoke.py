"""Serving-pipeline smoke: run the pipelined loop for ~2s on CPU and
fail on any dropped record.

CI/tooling entry (``scripts/serving-pipeline-smoke``): a producer thread
enqueues tensor records in mixed-size bursts against a live pipelined
:class:`ClusterServing` over the in-process transport; at the end every
record must have produced a result with the right value.  Exit 0 on
success, 1 on any missing/mismatched result, printing one JSON line of
pipeline stats either way.

Usage::

    python -m analytics_zoo_tpu.serving.smoke [--seconds 2] [--batch 8]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def build_tiny_model(shape=(3, 8, 8), units: int = 4, scale=None):
    """Flatten+Dense InferenceModel for smoke/bench traffic.  With
    ``scale`` the kernel is a constant, so outputs identify which model
    (or version) served a record — what the registry smoke asserts on."""
    import numpy as np

    from ..pipeline.api.keras.layers import Dense, Flatten
    from ..pipeline.api.keras.models import Sequential
    from ..pipeline.inference import InferenceModel

    m = Sequential()
    m.add(Flatten(input_shape=shape))
    m.add(Dense(units, activation=None if scale is not None
                else "softmax"))
    m.compile("sgd", "sparse_categorical_crossentropy")
    if scale is not None:
        # constant kernel (the 2-D leaf), zero bias — leaf order comes
        # from the param tree, so match by shape instead of position
        m.set_weights([np.full(w.shape, float(scale) if w.ndim == 2
                               else 0.0, np.float32)
                       for w in m.get_weights()])
    inf = InferenceModel(supported_concurrent_num=1)
    inf.load_keras_net(m)
    return inf


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="serving-pipeline-smoke")
    ap.add_argument("--seconds", type=float, default=2.0,
                    help="how long to keep producing traffic")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--decode-workers", type=int, default=2)
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from .client import InputQueue, OutputQueue
    from .cluster_serving import ClusterServing, ClusterServingHelper
    from .queue_backend import InProcessStreamQueue

    shape = (3, 8, 8)
    inf = build_tiny_model(shape)

    helper = ClusterServingHelper(config={
        "data": {"image_shape": "3, 8, 8"},
        "params": {"batch_size": args.batch,
                   "decode_workers": args.decode_workers,
                   "top_n": 0}})
    backend = InProcessStreamQueue()
    serving = ClusterServing(model=inf, helper=helper, backend=backend)
    serving.warmup()
    serving.start()

    in_q = InputQueue(backend=backend)
    out_q = OutputQueue(backend=backend)
    rng = np.random.default_rng(0)
    uris = []
    deadline = time.time() + args.seconds
    i = 0
    try:
        while time.time() < deadline:
            burst = int(rng.integers(1, args.batch + 1))
            for _ in range(burst):
                uri = f"smoke-{i}"
                in_q.enqueue(uri, input=np.full(shape, i % 97, np.float32))
                uris.append(uri)
                i += 1
            time.sleep(0.002)
        got = out_q.wait_all(uris, timeout=30.0)
    finally:
        serving.stop()

    stats = serving.pipeline_stats()
    missing = [u for u in uris if u not in got]
    stats["submitted"] = len(uris)
    stats["received"] = len(got)
    stats["missing"] = len(missing)
    print(json.dumps(stats))
    if missing or stats["dropped"]:
        print(f"SMOKE FAILED: {len(missing)} missing, "
              f"{stats['dropped']} dropped "
              f"(first missing: {missing[:5]})", file=sys.stderr)
        return 1
    print(f"SMOKE OK: {len(uris)} records served, 0 dropped, "
          f"e2e p99 {stats['stages'].get('e2e', {}).get('p99', 0):.1f}ms",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
