"""Deadline-aware admission control + adaptive re-batching for serving.

The reference's Cluster Serving queues everything and lets the tail
land where it may; at saturating offered load that makes p99 a function
of queue depth, i.e. of luck.  This module bounds the tail by policy
instead:

- every wire record may carry ``deadline_ms`` (relative to its client
  ``enqueue_ts_ms`` stamp).  At intake the serving loop asks
  :meth:`AdmissionController.admit` whether the record can still meet
  its deadline given the measured per-record service time and the
  current backlog; a record that cannot is **shed immediately** with a
  typed rejection payload (clients see
  :class:`~analytics_zoo_tpu.serving.client.ServingRejected`) instead
  of rotting in the queue and dragging the tail out;
- records whose deadline expires while queued are shed again at
  dispatch time (``shed_expired``) so the accelerator never spends a
  batch on an answer nobody is waiting for;
- :class:`AdaptiveBatcher` gives the compute stage a *linger budget*:
  under load it may wait a bounded extra moment to round a partial
  batch up to the next padding-bucket boundary (continuous
  re-batching), but never longer than the oldest queued record's
  deadline slack allows.

Service-time estimates are :class:`~analytics_zoo_tpu.utils.profiling.
Ewma` so the controller adapts as traffic or the model mix shifts.
All decisions are O(1) per record — this sits on the intake hot path.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Optional, Sequence, Tuple

from ..utils.profiling import Ewma

#: typed rejection codes carried in the shed result payload ("code")
SHED_DEADLINE = "shed_deadline"   # unmeetable at admission time
SHED_EXPIRED = "shed_expired"     # expired while queued, shed at dispatch


def now_ms() -> float:
    """Epoch milliseconds — the wire-timestamp clock (clients and
    workers share a host or NTP; perf_counter is not comparable across
    processes)."""
    return time.time() * 1e3


class AdmissionController:
    """Shed-or-admit decisions from measured service time + backlog.

    ``safety_ms`` is the scheduling slop added to every estimate (queue
    polling, GIL, host jitter); a record is admitted only when
    ``backlog * per_record_ms + batch_ms + safety_ms`` fits inside its
    remaining deadline slack.  Until the first batch has been observed
    both estimates are unknown and only the safety margin is applied —
    the controller never sheds on a guess it has no data for.
    """

    def __init__(self, safety_ms: float = 2.0, alpha: float = 0.25):
        self.safety_ms = float(safety_ms)
        self._record_ms = Ewma(alpha)   # per-record service time
        self._batch_ms = Ewma(alpha)    # per-dispatch wall time
        self._token_ms = Ewma(alpha)    # per-token decode step time
        self._chunk_ms = Ewma(alpha)    # per-prefill-chunk wall time
        self._lock = threading.Lock()
        self.shed_deadline = 0
        self.shed_expired = 0

    # -- estimate maintenance (fed by the writer stage) ----------------
    def observe_batch(self, n: int, seconds: float):
        """One dispatched batch of ``n`` records took ``seconds``."""
        ms = float(seconds) * 1e3
        self._batch_ms.update(ms)
        self._record_ms.update(ms / max(int(n), 1))

    def observe_tokens(self, n_tokens: int, seconds: float):
        """One continuous-batching decode step emitted ``n_tokens``
        (one per in-flight sequence) in ``seconds`` — maintains the
        per-token service estimate the generate admission path uses."""
        if n_tokens > 0:
            self._token_ms.update(float(seconds) * 1e3)

    def observe_prefill_chunk(self, seconds: float):
        """One chunked-prefill step (a fixed-size prompt slice fed
        between decode steps) took ``seconds`` — maintains the per-chunk
        estimate that lets ``admit_generate`` budget a long prompt as N
        interleaved chunk-steps instead of one monolithic stall."""
        self._chunk_ms.update(float(seconds) * 1e3)

    @property
    def record_ms(self) -> float:
        return self._record_ms.value or 0.0

    @property
    def batch_ms(self) -> float:
        return self._batch_ms.value or 0.0

    @property
    def token_ms(self) -> float:
        """EWMA wall time of one decode step (every in-flight sequence
        advances one token per step, so this is also per-sequence)."""
        return self._token_ms.value or 0.0

    @property
    def chunk_ms(self) -> float:
        """EWMA wall time of one prefill chunk; falls back to the batch
        estimate before the first chunk has been observed (a monolithic
        prefill is the degenerate one-chunk case)."""
        return self._chunk_ms.value or self.batch_ms

    # -- decisions ------------------------------------------------------
    def estimate_wait_ms(self, backlog: int) -> float:
        """Expected time for a record arriving now to finish: drain the
        backlog ahead of it plus its own batch."""
        return max(int(backlog), 0) * self.record_ms + self.batch_ms

    def admit(self, slack_ms: Optional[float],
              backlog: int) -> Tuple[bool, Optional[str]]:
        """(admitted, shed_code).  ``slack_ms`` is the record's remaining
        deadline budget (``None`` = no deadline, always admitted)."""
        if slack_ms is None:
            return True, None
        if self.estimate_wait_ms(backlog) + self.safety_ms > slack_ms:
            with self._lock:
                self.shed_deadline += 1
            return False, SHED_DEADLINE
        return True, None

    def admit_generate(self, slack_ms: Optional[float], max_new_tokens: int,
                       queue_depth: int = 0, prefill_chunks: int = 1,
                       tokens_per_step: float = 1.0
                       ) -> Tuple[bool, Optional[str]]:
        """Admission for a generate request: the EWMA deadline shed
        extended with the per-token service estimate. The request is
        admitted only when prefill plus its decode steps plus the wait
        for a free cache slot (``queue_depth`` requests ahead, each
        worth one more token-stream in front of us) fits its slack.

        ``prefill_chunks`` budgets a chunked prompt as N *interleaved*
        chunk-steps — each chunk shares a token boundary with one gang
        decode step, so the request's own prefill timeline is
        ``N * (chunk_ms + token_ms)``, not one monolithic stall.
        ``tokens_per_step`` (> 1 under speculative decoding: accepted
        drafts + 1 per verify step) divides the decode-step count — the
        shed must reflect the real token timeline, or speculation's
        speedup would be invisible to deadline admission.  With no
        observations yet only the batch/safety terms apply — never shed
        on a guess with no data behind it.
        """
        if slack_ms is None:
            return True, None
        chunks = max(int(prefill_chunks), 1)
        if chunks > 1:
            prefill_est = chunks * (self.chunk_ms + self.token_ms)
        else:
            prefill_est = self.batch_ms
        steps = math.ceil(max(int(max_new_tokens), 1) /
                          max(float(tokens_per_step), 1.0))
        est = (prefill_est + self.safety_ms + steps * self.token_ms +
               max(int(queue_depth), 0) * self.token_ms)
        if est > slack_ms:
            with self._lock:
                self.shed_deadline += 1
            return False, SHED_DEADLINE
        return True, None

    def stream_expired(self, deadline_at_ms: Optional[float],
                       at_ms: Optional[float] = None) -> bool:
        """Mid-generation deadline check, one call per emitted token:
        True when even one more decode step lands past the deadline.
        The scheduler evicts the sequence and commits a typed
        ``shed_deadline`` payload carrying the partial tokens."""
        if deadline_at_ms is None:
            return False
        at = now_ms() if at_ms is None else at_ms
        if at + self.token_ms + self.safety_ms > deadline_at_ms:
            with self._lock:
                self.shed_deadline += 1
            return True
        return False

    def expired(self, deadline_at_ms: Optional[float],
                at_ms: Optional[float] = None) -> bool:
        """True when a queued record can no longer produce a useful
        answer: its deadline lands before even an immediate dispatch
        would complete."""
        if deadline_at_ms is None:
            return False
        at = now_ms() if at_ms is None else at_ms
        if at + self.batch_ms + self.safety_ms > deadline_at_ms:
            with self._lock:
                self.shed_expired += 1
            return True
        return False

    def stats(self) -> dict:
        with self._lock:
            return {"shed_deadline": self.shed_deadline,
                    "shed_expired": self.shed_expired,
                    "est_record_ms": round(self.record_ms, 3),
                    "est_batch_ms": round(self.batch_ms, 3),
                    "est_token_ms": round(self.token_ms, 3),
                    "est_chunk_ms": round(self.chunk_ms, 3),
                    "safety_ms": self.safety_ms}


class BacklogAutoscaler:
    """Backlog-driven worker-count policy for the serving fleet.

    Pure decision logic (no process management — ServingFleet owns
    that): the supervisor feeds it the shared stream's backlog plus the
    workers' EWMA service estimates and the current worker count; it
    answers with the desired count and a reason string for the
    autoscale trace (docs/serving-network.md#autoscaling).

    - **scale up** when the predicted wait for a record arriving now —
      backlog drained across the current workers plus one batch —
      exceeds ``scale_up_fraction`` of ``target_ms`` (the deadline-slack
      budget scaling defends).  The jump is sized to bring the wait
      back under the threshold in one step rather than one worker per
      poll.
    - **scale down** one worker at a time after ``idle_s`` of
      sustained-empty backlog (a momentary gap between bursts must not
      flap the fleet).
    - ``cooldown_s`` separates consecutive actions so a decision is
      judged on post-change evidence, not on the backlog it inherited.

    Until the first batch has been observed ``record_ms`` is 0 and the
    predicted wait is just ``batch_ms`` — the policy never grows the
    fleet on a guess it has no data for.
    """

    def __init__(self, min_workers: int, max_workers: int,
                 target_ms: float = 250.0,
                 scale_up_fraction: float = 0.5,
                 idle_s: float = 3.0, cooldown_s: float = 2.0):
        if min_workers < 1 or max_workers < min_workers:
            raise ValueError(
                f"need 1 <= min_workers <= max_workers, got "
                f"{min_workers}..{max_workers}")
        self.min_workers = int(min_workers)
        self.max_workers = int(max_workers)
        self.target_ms = float(target_ms)
        self.scale_up_fraction = float(scale_up_fraction)
        self.idle_s = float(idle_s)
        self.cooldown_s = float(cooldown_s)
        self._idle_since: Optional[float] = None
        self._last_change: float = -1e12

    def predicted_wait_ms(self, backlog: int, record_ms: float,
                          batch_ms: float, workers: int) -> float:
        """Expected finish time for a record arriving now, with the
        backlog drained in parallel across ``workers``."""
        return (max(int(backlog), 0) * max(record_ms, 0.0)
                / max(int(workers), 1) + max(batch_ms, 0.0))

    def desired(self, backlog: int, record_ms: float, batch_ms: float,
                workers: int, now: Optional[float] = None
                ) -> Tuple[int, Optional[str]]:
        """(desired_workers, reason) — reason is None when no change."""
        now = time.time() if now is None else now
        workers = max(int(workers), 1)
        wait = self.predicted_wait_ms(backlog, record_ms, batch_ms,
                                      workers)
        threshold = self.scale_up_fraction * self.target_ms
        if backlog > 0:
            self._idle_since = None
        elif self._idle_since is None:
            self._idle_since = now
        if now - self._last_change < self.cooldown_s:
            return workers, None
        if wait > threshold and workers < self.max_workers:
            # size the jump: workers needed so the drain term fits the
            # slack left after one batch (>= +1, <= max)
            slack = max(threshold - batch_ms, 1.0)
            need = math.ceil(backlog * record_ms / slack) \
                if record_ms > 0 else workers + 1
            target = min(self.max_workers, max(workers + 1, need))
            self._last_change = now
            self._idle_since = None
            return target, (f"predicted wait {wait:.0f}ms > "
                            f"{threshold:.0f}ms at backlog {backlog}")
        if (workers > self.min_workers and self._idle_since is not None
                and now - self._idle_since >= self.idle_s):
            self._last_change = now
            return workers - 1, (f"idle {now - self._idle_since:.1f}s "
                                 f">= {self.idle_s:.1f}s")
        return workers, None


class AdaptiveBatcher:
    """Linger budget for the compute stage's batch assembly.

    The greedy assembler takes whatever is already decoded; with a
    linger budget it may additionally block a bounded moment for more
    records so partial batches round up to the next padding-bucket
    boundary — amortizing MXU time under load without ever spending a
    queued record's deadline slack.  ``linger_ms = 0`` (the default)
    disables lingering and preserves the latency-first behavior.
    """

    def __init__(self, buckets: Sequence[int],
                 controller: Optional[AdmissionController] = None,
                 linger_ms: float = 0.0):
        self.buckets = sorted(buckets)
        self.controller = controller
        self.linger_ms = max(float(linger_ms), 0.0)

    def next_boundary(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def linger_budget_s(self, n_have: int,
                        oldest_deadline_at_ms: Optional[float],
                        at_ms: Optional[float] = None) -> float:
        """Seconds the assembler may block waiting for record number
        ``n_have + 1``; 0.0 means dispatch now."""
        if self.linger_ms <= 0.0 or n_have >= self.buckets[-1]:
            return 0.0
        if n_have in self.buckets:
            # already exactly on a bucket boundary: lingering would only
            # trade latency for a *larger* signature — dispatch
            return 0.0
        budget = self.linger_ms
        if oldest_deadline_at_ms is not None:
            at = now_ms() if at_ms is None else at_ms
            cost = (self.controller.batch_ms + self.controller.safety_ms
                    if self.controller is not None else 0.0)
            budget = min(budget, oldest_deadline_at_ms - at - cost)
        return max(budget, 0.0) / 1e3
