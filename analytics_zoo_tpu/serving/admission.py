"""Deadline-aware admission control + adaptive re-batching for serving.

The reference's Cluster Serving queues everything and lets the tail
land where it may; at saturating offered load that makes p99 a function
of queue depth, i.e. of luck.  This module bounds the tail by policy
instead:

- every wire record may carry ``deadline_ms`` (relative to its client
  ``enqueue_ts_ms`` stamp).  At intake the serving loop asks
  :meth:`AdmissionController.admit` whether the record can still meet
  its deadline given the measured per-record service time and the
  current backlog; a record that cannot is **shed immediately** with a
  typed rejection payload (clients see
  :class:`~analytics_zoo_tpu.serving.client.ServingRejected`) instead
  of rotting in the queue and dragging the tail out;
- records whose deadline expires while queued are shed again at
  dispatch time (``shed_expired``) so the accelerator never spends a
  batch on an answer nobody is waiting for;
- :class:`AdaptiveBatcher` gives the compute stage a *linger budget*:
  under load it may wait a bounded extra moment to round a partial
  batch up to the next padding-bucket boundary (continuous
  re-batching), but never longer than the oldest queued record's
  deadline slack allows.

Service-time estimates are :class:`~analytics_zoo_tpu.utils.profiling.
Ewma` so the controller adapts as traffic or the model mix shifts.
All decisions are O(1) per record — this sits on the intake hot path.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from ..utils.profiling import Ewma

#: typed rejection codes carried in the shed result payload ("code")
SHED_DEADLINE = "shed_deadline"   # unmeetable at admission time
SHED_EXPIRED = "shed_expired"     # expired while queued, shed at dispatch
SHED_CAPACITY = "shed_capacity"   # shed by tenant policy under pressure


def now_ms() -> float:
    """Epoch milliseconds — the wire-timestamp clock (clients and
    workers share a host or NTP; perf_counter is not comparable across
    processes)."""
    return time.time() * 1e3


class AdmissionController:
    """Shed-or-admit decisions from measured service time + backlog.

    ``safety_ms`` is the scheduling slop added to every estimate (queue
    polling, GIL, host jitter); a record is admitted only when
    ``backlog * per_record_ms + batch_ms + safety_ms`` fits inside its
    remaining deadline slack.  Until the first batch has been observed
    both estimates are unknown and only the safety margin is applied —
    the controller never sheds on a guess it has no data for.
    """

    def __init__(self, safety_ms: float = 2.0, alpha: float = 0.25):
        self.safety_ms = float(safety_ms)
        self._record_ms = Ewma(alpha)   # per-record service time
        self._batch_ms = Ewma(alpha)    # per-dispatch wall time
        self._token_ms = Ewma(alpha)    # per-token decode step time
        self._chunk_ms = Ewma(alpha)    # per-prefill-chunk wall time
        self._lock = threading.Lock()
        self.shed_deadline = 0
        self.shed_expired = 0

    # -- estimate maintenance (fed by the writer stage) ----------------
    def observe_batch(self, n: int, seconds: float):
        """One dispatched batch of ``n`` records took ``seconds``."""
        ms = float(seconds) * 1e3
        self._batch_ms.update(ms)
        self._record_ms.update(ms / max(int(n), 1))

    def observe_tokens(self, n_tokens: int, seconds: float):
        """One continuous-batching decode step emitted ``n_tokens``
        (one per in-flight sequence) in ``seconds`` — maintains the
        per-token service estimate the generate admission path uses."""
        if n_tokens > 0:
            self._token_ms.update(float(seconds) * 1e3)

    def observe_prefill_chunk(self, seconds: float):
        """One chunked-prefill step (a fixed-size prompt slice fed
        between decode steps) took ``seconds`` — maintains the per-chunk
        estimate that lets ``admit_generate`` budget a long prompt as N
        interleaved chunk-steps instead of one monolithic stall."""
        self._chunk_ms.update(float(seconds) * 1e3)

    @property
    def record_ms(self) -> float:
        return self._record_ms.value or 0.0

    @property
    def batch_ms(self) -> float:
        return self._batch_ms.value or 0.0

    @property
    def token_ms(self) -> float:
        """EWMA wall time of one decode step (every in-flight sequence
        advances one token per step, so this is also per-sequence)."""
        return self._token_ms.value or 0.0

    @property
    def chunk_ms(self) -> float:
        """EWMA wall time of one prefill chunk; falls back to the batch
        estimate before the first chunk has been observed (a monolithic
        prefill is the degenerate one-chunk case)."""
        return self._chunk_ms.value or self.batch_ms

    # -- decisions ------------------------------------------------------
    def estimate_wait_ms(self, backlog: int) -> float:
        """Expected time for a record arriving now to finish: drain the
        backlog ahead of it plus its own batch."""
        return max(int(backlog), 0) * self.record_ms + self.batch_ms

    def admit(self, slack_ms: Optional[float],
              backlog: int) -> Tuple[bool, Optional[str]]:
        """(admitted, shed_code).  ``slack_ms`` is the record's remaining
        deadline budget (``None`` = no deadline, always admitted)."""
        if slack_ms is None:
            return True, None
        if self.estimate_wait_ms(backlog) + self.safety_ms > slack_ms:
            with self._lock:
                self.shed_deadline += 1
            return False, SHED_DEADLINE
        return True, None

    def admit_generate(self, slack_ms: Optional[float], max_new_tokens: int,
                       queue_depth: int = 0, prefill_chunks: int = 1,
                       tokens_per_step: float = 1.0
                       ) -> Tuple[bool, Optional[str]]:
        """Admission for a generate request: the EWMA deadline shed
        extended with the per-token service estimate. The request is
        admitted only when prefill plus its decode steps plus the wait
        for a free cache slot (``queue_depth`` requests ahead, each
        worth one more token-stream in front of us) fits its slack.

        ``prefill_chunks`` budgets a chunked prompt as N *interleaved*
        chunk-steps — each chunk shares a token boundary with one gang
        decode step, so the request's own prefill timeline is
        ``N * (chunk_ms + token_ms)``, not one monolithic stall.
        ``tokens_per_step`` (> 1 under speculative decoding: accepted
        drafts + 1 per verify step) divides the decode-step count — the
        shed must reflect the real token timeline, or speculation's
        speedup would be invisible to deadline admission.  With no
        observations yet only the batch/safety terms apply — never shed
        on a guess with no data behind it.
        """
        if slack_ms is None:
            return True, None
        chunks = max(int(prefill_chunks), 1)
        if chunks > 1:
            prefill_est = chunks * (self.chunk_ms + self.token_ms)
        else:
            prefill_est = self.batch_ms
        steps = math.ceil(max(int(max_new_tokens), 1) /
                          max(float(tokens_per_step), 1.0))
        est = (prefill_est + self.safety_ms + steps * self.token_ms +
               max(int(queue_depth), 0) * self.token_ms)
        if est > slack_ms:
            with self._lock:
                self.shed_deadline += 1
            return False, SHED_DEADLINE
        return True, None

    def stream_expired(self, deadline_at_ms: Optional[float],
                       at_ms: Optional[float] = None) -> bool:
        """Mid-generation deadline check, one call per emitted token:
        True when even one more decode step lands past the deadline.
        The scheduler evicts the sequence and commits a typed
        ``shed_deadline`` payload carrying the partial tokens."""
        if deadline_at_ms is None:
            return False
        at = now_ms() if at_ms is None else at_ms
        if at + self.token_ms + self.safety_ms > deadline_at_ms:
            with self._lock:
                self.shed_deadline += 1
            return True
        return False

    def expired(self, deadline_at_ms: Optional[float],
                at_ms: Optional[float] = None) -> bool:
        """True when a queued record can no longer produce a useful
        answer: its deadline lands before even an immediate dispatch
        would complete."""
        if deadline_at_ms is None:
            return False
        at = now_ms() if at_ms is None else at_ms
        if at + self.batch_ms + self.safety_ms > deadline_at_ms:
            with self._lock:
                self.shed_expired += 1
            return True
        return False

    def stats(self) -> dict:
        with self._lock:
            return {"shed_deadline": self.shed_deadline,
                    "shed_expired": self.shed_expired,
                    "est_record_ms": round(self.record_ms, 3),
                    "est_batch_ms": round(self.batch_ms, 3),
                    "est_token_ms": round(self.token_ms, 3),
                    "est_chunk_ms": round(self.chunk_ms, 3),
                    "safety_ms": self.safety_ms}


#: implicit tenant for traffic no SLO class binds (weight 1, priority 0,
#: never pressure-shed — it declared no wait bound)
DEFAULT_TENANT = "_default"


class TenantScheduler:
    """Weighted-fair intake + priority sheds across SLO classes.

    The single-tenant intake path admits records in stream order, so one
    tenant's burst monopolizes the pipeline and burns every other
    tenant's error budget.  This scheduler puts a per-tenant queue
    between stream intake and the decode stage
    (docs/multi-tenancy.md#scheduling):

    - **classify**: route each record to the most-specific SLO class for
      its (model, version) — exact match > model-only > catch-all —
      falling back to the implicit ``_default`` tenant;
    - **weighted-fair drain**: deficit round-robin — each pass a class
      earns ``weight * quantum`` credit and drains whole records while
      credit lasts, so a weight-3 class gets 3 of every 4 slots while
      both have backlog, yet an idle class's share flows to the others
      (work-conserving; an empty class's deficit resets so it cannot
      hoard credit for a later burst);
    - **priority sheds**: under predicted-wait pressure the scheduler
      sheds the *oldest* queued record of the least-important violating
      class (highest ``priority`` number; lower = more important) until
      every remaining class's predicted wait fits its ``shed_wait_ms``
      bound — so a low-priority burst absorbs the typed
      ``shed_capacity`` rejections while the high-priority tenant keeps
      its latency objective.

    Classes are any objects with ``name``/``weight``/``priority``/
    ``model``/``version``/``shed_wait_ms`` attributes —
    :class:`~analytics_zoo_tpu.utils.slo.SloClass` in production.
    """

    def __init__(self, classes: Sequence = (), quantum: float = 1.0):
        self.classes = list(classes)
        self.quantum = float(quantum)
        self.class_of: Dict[str, object] = {c.name: c
                                            for c in self.classes}
        self._order = [c.name for c in self.classes]
        if DEFAULT_TENANT not in self.class_of:
            self._order.append(DEFAULT_TENANT)
        self._queues: Dict[str, deque] = {n: deque() for n in self._order}
        self._deficit: Dict[str, float] = {n: 0.0 for n in self._order}
        self._lock = threading.Lock()
        self.offered: Dict[str, int] = {n: 0 for n in self._order}
        self.drained: Dict[str, int] = {n: 0 for n in self._order}
        self.shed: Dict[str, int] = {n: 0 for n in self._order}

    # -- class attributes with _default fallbacks ----------------------
    def _weight(self, name: str) -> float:
        cls = self.class_of.get(name)
        return float(getattr(cls, "weight", 1.0)) if cls else 1.0

    def _priority(self, name: str) -> int:
        cls = self.class_of.get(name)
        return int(getattr(cls, "priority", 0)) if cls else 0

    def _shed_wait_ms(self, name: str) -> Optional[float]:
        cls = self.class_of.get(name)
        return getattr(cls, "shed_wait_ms", None) if cls else None

    # -- routing --------------------------------------------------------
    def classify(self, model: Optional[str],
                 version: Optional[str]) -> str:
        """Tenant name for a record's (model, version): exact match >
        model-only > catch-all > implicit ``_default``."""
        best, best_rank = DEFAULT_TENANT, -1
        for cls in self.classes:
            if cls.model is None:
                rank = 0
            elif cls.model == model:
                rank = 2 if cls.version is not None else 1
                if cls.version is not None and cls.version != version:
                    continue
            else:
                continue
            if rank > best_rank:
                best, best_rank = cls.name, rank
        return best

    # -- intake ---------------------------------------------------------
    def offer(self, tenant: str, item) -> None:
        """Queue one intake item (whatever the serving loop carries —
        (meta, record) tuples) under its tenant."""
        with self._lock:
            if tenant not in self._queues:
                tenant = DEFAULT_TENANT
            self._queues[tenant].append(item)
            self.offered[tenant] += 1

    def drain(self, max_items: int) -> List:
        """Up to ``max_items`` items in weighted-fair (DRR) order."""
        out: List = []
        with self._lock:
            while (len(out) < max_items
                   and any(self._queues[n] for n in self._order)):
                for name in self._order:
                    q = self._queues[name]
                    if not q:
                        self._deficit[name] = 0.0
                        continue
                    self._deficit[name] += self._weight(name) * self.quantum
                    while (q and self._deficit[name] >= 1.0
                           and len(out) < max_items):
                        out.append(q.popleft())
                        self._deficit[name] -= 1.0
                        self.drained[name] += 1
                    if not q:
                        self._deficit[name] = 0.0
        return out

    # -- pressure sheds -------------------------------------------------
    def shed_under_pressure(self, controller: AdmissionController,
                            extra_backlog: int = 0) -> List[Tuple[str, object]]:
        """Shed queued items until every class's predicted wait fits its
        ``shed_wait_ms`` bound.  Returns [(tenant, item), ...] oldest
        first; the caller commits the typed ``shed_capacity`` payloads.

        ``extra_backlog`` is the pipeline's already-admitted depth (the
        records queued ahead of every tenant queue).  Victim order: the
        highest priority *number* (least important) among violating
        classes, largest backlog as tie-break — so a low class's burst
        is shed away before a high class loses anything."""
        out: List[Tuple[str, object]] = []
        with self._lock:
            while True:
                backlog = (max(int(extra_backlog), 0)
                           + sum(len(q) for q in self._queues.values()))
                wait = (controller.estimate_wait_ms(backlog)
                        + controller.safety_ms)
                victims = [
                    n for n in self._order
                    if self._queues[n]
                    and self._shed_wait_ms(n) is not None
                    and wait > self._shed_wait_ms(n)]
                if not victims:
                    return out
                name = max(victims, key=lambda n: (self._priority(n),
                                                   len(self._queues[n])))
                out.append((name, self._queues[name].popleft()))
                self.shed[name] += 1

    # -- observability --------------------------------------------------
    def queued_total(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._queues.values())

    def stats(self) -> Dict[str, dict]:
        with self._lock:
            return {n: {"queued": len(self._queues[n]),
                        "offered": self.offered[n],
                        "drained": self.drained[n],
                        "shed_capacity": self.shed[n],
                        "weight": self._weight(n),
                        "priority": self._priority(n),
                        "shed_wait_ms": self._shed_wait_ms(n)}
                    for n in self._order
                    if self.offered[n] or n != DEFAULT_TENANT}


class BacklogAutoscaler:
    """Backlog-driven worker-count policy for the serving fleet.

    Pure decision logic (no process management — ServingFleet owns
    that): the supervisor feeds it the shared stream's backlog plus the
    workers' EWMA service estimates and the current worker count; it
    answers with the desired count and a reason string for the
    autoscale trace (docs/serving-network.md#autoscaling).

    - **scale up** when the predicted wait for a record arriving now —
      backlog drained across the current workers plus one batch —
      exceeds ``scale_up_fraction`` of ``target_ms`` (the deadline-slack
      budget scaling defends).  The jump is sized to bring the wait
      back under the threshold in one step rather than one worker per
      poll.
    - **scale down** one worker at a time after ``idle_s`` of
      sustained-empty backlog (a momentary gap between bursts must not
      flap the fleet).
    - ``cooldown_s`` separates consecutive actions so a decision is
      judged on post-change evidence, not on the backlog it inherited.

    Until the first batch has been observed ``record_ms`` is 0 and the
    predicted wait is just ``batch_ms`` — the policy never grows the
    fleet on a guess it has no data for.
    """

    def __init__(self, min_workers: int, max_workers: int,
                 target_ms: float = 250.0,
                 scale_up_fraction: float = 0.5,
                 idle_s: float = 3.0, cooldown_s: float = 2.0):
        if min_workers < 1 or max_workers < min_workers:
            raise ValueError(
                f"need 1 <= min_workers <= max_workers, got "
                f"{min_workers}..{max_workers}")
        self.min_workers = int(min_workers)
        self.max_workers = int(max_workers)
        self.target_ms = float(target_ms)
        self.scale_up_fraction = float(scale_up_fraction)
        self.idle_s = float(idle_s)
        self.cooldown_s = float(cooldown_s)
        self._idle_since: Optional[float] = None
        self._last_change: float = -1e12

    def predicted_wait_ms(self, backlog: int, record_ms: float,
                          batch_ms: float, workers: int, *,
                          gen_steps: float = 0.0,
                          token_ms: float = 0.0) -> float:
        """Expected finish time for a record arriving now, with the
        backlog drained in parallel across ``workers``.  ``gen_steps``
        weighs the generate backlog in queued *decode steps* times the
        EWMA per-token cost — one queued 512-token generation is 512
        steps of work, not one record."""
        return (max(int(backlog), 0) * max(record_ms, 0.0)
                / max(int(workers), 1)
                + max(gen_steps, 0.0) * max(token_ms, 0.0)
                / max(int(workers), 1)
                + max(batch_ms, 0.0))

    def desired(self, backlog: int, record_ms: float, batch_ms: float,
                workers: int, now: Optional[float] = None, *,
                gen_steps: float = 0.0, token_ms: float = 0.0
                ) -> Tuple[int, Optional[str]]:
        """(desired_workers, reason) — reason is None when no change."""
        now = time.time() if now is None else now
        workers = max(int(workers), 1)
        wait = self.predicted_wait_ms(backlog, record_ms, batch_ms,
                                      workers, gen_steps=gen_steps,
                                      token_ms=token_ms)
        threshold = self.scale_up_fraction * self.target_ms
        if backlog > 0 or gen_steps > 0:
            self._idle_since = None
        elif self._idle_since is None:
            self._idle_since = now
        if now - self._last_change < self.cooldown_s:
            return workers, None
        if wait > threshold and workers < self.max_workers:
            # size the jump: workers needed so the drain term (predict
            # records + generate decode steps) fits the slack left
            # after one batch (>= +1, <= max)
            slack = max(threshold - batch_ms, 1.0)
            work_ms = (max(int(backlog), 0) * max(record_ms, 0.0)
                       + max(gen_steps, 0.0) * max(token_ms, 0.0))
            need = math.ceil(work_ms / slack) \
                if work_ms > 0 else workers + 1
            target = min(self.max_workers, max(workers + 1, need))
            self._last_change = now
            self._idle_since = None
            detail = f" + {gen_steps:.0f} decode steps" \
                if gen_steps > 0 else ""
            return target, (f"predicted wait {wait:.0f}ms > "
                            f"{threshold:.0f}ms at backlog "
                            f"{backlog}{detail}")
        if (workers > self.min_workers and self._idle_since is not None
                and now - self._idle_since >= self.idle_s):
            self._last_change = now
            return workers - 1, (f"idle {now - self._idle_since:.1f}s "
                                 f">= {self.idle_s:.1f}s")
        return workers, None


class AdaptiveBatcher:
    """Linger budget for the compute stage's batch assembly.

    The greedy assembler takes whatever is already decoded; with a
    linger budget it may additionally block a bounded moment for more
    records so partial batches round up to the next padding-bucket
    boundary — amortizing MXU time under load without ever spending a
    queued record's deadline slack.  ``linger_ms = 0`` (the default)
    disables lingering and preserves the latency-first behavior.
    """

    def __init__(self, buckets: Sequence[int],
                 controller: Optional[AdmissionController] = None,
                 linger_ms: float = 0.0):
        self.buckets = sorted(buckets)
        self.controller = controller
        self.linger_ms = max(float(linger_ms), 0.0)

    def next_boundary(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def linger_budget_s(self, n_have: int,
                        oldest_deadline_at_ms: Optional[float],
                        at_ms: Optional[float] = None) -> float:
        """Seconds the assembler may block waiting for record number
        ``n_have + 1``; 0.0 means dispatch now."""
        if self.linger_ms <= 0.0 or n_have >= self.buckets[-1]:
            return 0.0
        if n_have in self.buckets:
            # already exactly on a bucket boundary: lingering would only
            # trade latency for a *larger* signature — dispatch
            return 0.0
        budget = self.linger_ms
        if oldest_deadline_at_ms is not None:
            at = now_ms() if at_ms is None else at_ms
            cost = (self.controller.batch_ms + self.controller.safety_ms
                    if self.controller is not None else 0.0)
            budget = min(budget, oldest_deadline_at_ms - at - cost)
        return max(budget, 0.0) / 1e3
