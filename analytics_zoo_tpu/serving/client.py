"""Serving clients: InputQueue / OutputQueue.

Parity: ``pyzoo/zoo/serving/client.py`` — ``InputQueue.enqueue_image``
(:83, base64-encoded jpg into the stream), ``OutputQueue.dequeue``/``query``
(:131,142).  The transport is pluggable (§queue_backend) instead of
hard-coded Redis.
"""

from __future__ import annotations

import base64
import json
import time
from typing import Dict, Iterable, Optional

import numpy as np

from .queue_backend import StreamQueue, get_queue_backend


class ServingError(Exception):
    """A dead-lettered record: the server committed an error payload for
    this uri instead of a prediction (unknown model, failed decode,
    failed batch — docs/model-registry.md#dead-letters)."""

    def __init__(self, uri: Optional[str], message: str,
                 model: Optional[str] = None,
                 version: Optional[int] = None):
        super().__init__(f"{uri}: {message}" if uri else message)
        self.uri = uri
        self.message = message
        self.model = model
        self.version = version


class API:
    """Shared client base (client.py:25)."""

    def __init__(self, backend: Optional[StreamQueue] = None,
                 address: Optional[str] = None):
        self.db = backend if backend is not None else \
            get_queue_backend(address)


class InputQueue(API):
    @staticmethod
    def _route_fields(rec: dict, model: Optional[str],
                      version: Optional[int]) -> dict:
        # optional on the wire: absent fields route to the server's
        # default model, so pre-registry clients keep working unchanged
        if model is not None:
            rec["model"] = model
        if version is not None:
            rec["version"] = int(version)
        return rec

    def enqueue_image(self, uri: str, img, model: Optional[str] = None,
                      version: Optional[int] = None) -> str:
        """Put one image on the stream; ``img`` is an ndarray (HWC BGR
        uint8) or pre-encoded jpg/png bytes (client.py:83-122).
        ``model``/``version`` target a registry-served model."""
        if isinstance(img, np.ndarray):
            import cv2

            ok, buf = cv2.imencode(".jpg", img.astype(np.uint8))
            if not ok:
                raise ValueError("jpg encode failed")
            data = buf.tobytes()
        else:
            data = bytes(img)
        rec = {"uri": uri, "image": self.base64_encode_image(data)}
        return self.db.enqueue(self._route_fields(rec, model, version))

    def enqueue(self, uri: str, model: Optional[str] = None,
                version: Optional[int] = None, **tensors) -> str:
        """General tensor input: each kwarg becomes a (shape, data) entry."""
        rec = {"uri": uri, "tensors": {
            k: {"shape": list(np.asarray(v).shape),
                "data": np.asarray(v, np.float32).tobytes()}
            for k, v in tensors.items()}}
        return self.db.enqueue(self._route_fields(rec, model, version))

    @staticmethod
    def base64_encode_image(data: bytes) -> str:
        return base64.b64encode(data).decode("utf-8")


class OutputQueue(API):
    def dequeue(self):
        """Fetch-and-clear all results: {uri: ndarray} (client.py:131)."""
        return {uri: self._decode(v, uri)
                for uri, v in self.db.all_results(pop=True).items()}

    def query(self, uri: str):
        """Result for one uri or None (client.py:142)."""
        v = self.db.get_result(uri, pop=False)
        return self._decode(v, uri) if v is not None else None

    def wait_all(self, uris: Iterable[str], timeout: float = 30.0,
                 poll: float = 0.01, max_poll: float = 0.5,
                 raise_on_error: bool = False) -> Dict[str, np.ndarray]:
        """Poll until every uri has a result (popping as they land) or
        the deadline passes; returns whatever arrived.  The interval
        backs off exponentially from ``poll`` to ``max_poll`` while
        nothing lands and snaps back to ``poll`` on progress, so a hot
        stream is polled tightly and an idle one cheaply.

        Dead-lettered uris come back as :class:`ServingError` values
        (structured error instead of a silent timeout); with
        ``raise_on_error`` the first one raises."""
        want = set(uris)
        got: Dict[str, np.ndarray] = {}
        deadline = time.time() + timeout
        interval = poll
        while want and time.time() < deadline:
            progressed = False
            for uri, v in self.db.all_results(pop=True).items():
                got[uri] = self._decode(v, uri)
                want.discard(uri)
                progressed = True
            if raise_on_error:
                for v in got.values():
                    if isinstance(v, ServingError):
                        raise v
            if want:
                if progressed:
                    interval = poll
                else:
                    interval = min(interval * 2, max_poll)
                time.sleep(interval)
        return got

    @staticmethod
    def _decode(value: bytes, uri: Optional[str] = None):
        obj = json.loads(value.decode("utf-8"))
        if isinstance(obj, dict) and "error" in obj:
            return ServingError(uri, obj["error"], obj.get("model"),
                                obj.get("version"))
        return np.asarray(obj["value"], np.float32)
