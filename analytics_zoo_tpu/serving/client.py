"""Serving clients: InputQueue / OutputQueue.

Parity: ``pyzoo/zoo/serving/client.py`` — ``InputQueue.enqueue_image``
(:83, base64-encoded jpg into the stream), ``OutputQueue.dequeue``/``query``
(:131,142).  The transport is pluggable (§queue_backend) instead of
hard-coded Redis.
"""

from __future__ import annotations

import base64
import json
import time
from typing import Dict, Iterable, Optional

import numpy as np

from .queue_backend import StreamQueue, get_queue_backend


class API:
    """Shared client base (client.py:25)."""

    def __init__(self, backend: Optional[StreamQueue] = None,
                 address: Optional[str] = None):
        self.db = backend if backend is not None else \
            get_queue_backend(address)


class InputQueue(API):
    def enqueue_image(self, uri: str, img) -> str:
        """Put one image on the stream; ``img`` is an ndarray (HWC BGR
        uint8) or pre-encoded jpg/png bytes (client.py:83-122)."""
        if isinstance(img, np.ndarray):
            import cv2

            ok, buf = cv2.imencode(".jpg", img.astype(np.uint8))
            if not ok:
                raise ValueError("jpg encode failed")
            data = buf.tobytes()
        else:
            data = bytes(img)
        return self.db.enqueue({"uri": uri,
                                "image": self.base64_encode_image(data)})

    def enqueue(self, uri: str, **tensors) -> str:
        """General tensor input: each kwarg becomes a (shape, data) entry."""
        rec = {"uri": uri, "tensors": {
            k: {"shape": list(np.asarray(v).shape),
                "data": np.asarray(v, np.float32).tobytes()}
            for k, v in tensors.items()}}
        return self.db.enqueue(rec)

    @staticmethod
    def base64_encode_image(data: bytes) -> str:
        return base64.b64encode(data).decode("utf-8")


class OutputQueue(API):
    def dequeue(self):
        """Fetch-and-clear all results: {uri: ndarray} (client.py:131)."""
        return {uri: self._decode(v)
                for uri, v in self.db.all_results(pop=True).items()}

    def query(self, uri: str):
        """Result for one uri or None (client.py:142)."""
        v = self.db.get_result(uri, pop=False)
        return self._decode(v) if v is not None else None

    def wait_all(self, uris: Iterable[str], timeout: float = 30.0,
                 poll: float = 0.01) -> Dict[str, np.ndarray]:
        """Poll until every uri has a result (popping as they land) or
        the deadline passes; returns whatever arrived.  The bench leg,
        smoke entry, and pipeline tests all need exactly this loop."""
        want = set(uris)
        got: Dict[str, np.ndarray] = {}
        deadline = time.time() + timeout
        while want and time.time() < deadline:
            for uri, v in self.db.all_results(pop=True).items():
                got[uri] = self._decode(v)
                want.discard(uri)
            if want:
                time.sleep(poll)
        return got

    @staticmethod
    def _decode(value: bytes):
        obj = json.loads(value.decode("utf-8"))
        return np.asarray(obj["value"], np.float32)
