"""Serving clients: InputQueue / OutputQueue.

Parity: ``pyzoo/zoo/serving/client.py`` — ``InputQueue.enqueue_image``
(:83, base64-encoded jpg into the stream), ``OutputQueue.dequeue``/``query``
(:131,142).  The transport is pluggable (§queue_backend) instead of
hard-coded Redis.

Latency decomposition + deadlines (docs/serving-fleet.md): every enqueue
stamps ``enqueue_ts_ms`` (epoch ms) and may carry ``deadline_ms``; the
server threads its dequeue/compute timestamps into the result payload,
so a decoded result is a :class:`ServingResult` whose ``timing`` dict
splits ``transport_ms`` (wire + result-poll time) from ``device_ms``
(accelerator time) per row.  Requests the server cannot finish inside
their deadline come back as typed :class:`ServingRejected` values
instead of silent timeouts, and :meth:`OutputQueue.wait_all` raises
:class:`ServingTimeout` rather than backing off past a client deadline.
"""

from __future__ import annotations

import base64
import json
import os
import time
from typing import Dict, Iterable, Optional

import numpy as np

from ..utils import telemetry
from .queue_backend import StreamQueue, get_queue_backend


class ServingError(Exception):
    """A dead-lettered record: the server committed an error payload for
    this uri instead of a prediction (unknown model, failed decode,
    failed batch — docs/model-registry.md#dead-letters)."""

    def __init__(self, uri: Optional[str], message: str,
                 model: Optional[str] = None,
                 version: Optional[int] = None):
        super().__init__(f"{uri}: {message}" if uri else message)
        self.uri = uri
        self.message = message
        self.model = model
        self.version = version


class ServingRejected(ServingError):
    """Typed load-shed rejection: admission control determined the
    record could not meet its ``deadline_ms`` (``code`` is
    ``shed_deadline`` at intake, ``shed_expired`` when the deadline
    passed while queued — docs/serving-fleet.md#admission).  For a
    generate request shed *mid-stream* (deadline passed while decoding)
    ``tokens`` carries the partial token array the budget allowed —
    docs/serving-generate.md#deadlines."""

    def __init__(self, uri: Optional[str], message: str,
                 code: str = "shed_deadline",
                 model: Optional[str] = None,
                 version: Optional[int] = None,
                 tokens=None):
        super().__init__(uri, message, model, version)
        self.code = code
        self.tokens = (np.asarray(tokens, np.int64)
                       if tokens is not None else None)


class ServingTimeout(ServingError):
    """Client-side deadline expiry in :meth:`OutputQueue.wait_all`:
    results for ``missing`` uris had not landed when the deadline
    passed.  ``partial`` holds everything that did arrive."""

    def __init__(self, missing, partial: Optional[dict] = None,
                 deadline_ms: Optional[float] = None):
        self.missing = sorted(missing)
        self.partial = partial or {}
        self.deadline_ms = deadline_ms
        super().__init__(
            None,
            f"{len(self.missing)} of "
            f"{len(self.missing) + len(self.partial)} results missing "
            f"after deadline"
            + (f" of {deadline_ms:.0f}ms" if deadline_ms else "")
            + f": {self.missing[:5]}"
            + ("..." if len(self.missing) > 5 else ""))


class ServingResult(np.ndarray):
    """A prediction plus its latency decomposition: behaves exactly like
    the float32 ndarray it always was, with a ``timing`` dict attached
    (``device_ms``, ``transport_ms``, ``queue_ms``, ``rtt_ms``, raw
    timestamps) when the server reported one."""

    timing: Optional[dict]

    def __array_finalize__(self, obj):
        self.timing = getattr(obj, "timing", None)

    @classmethod
    def wrap(cls, value, timing: Optional[dict]) -> "ServingResult":
        out = np.asarray(value, np.float32).view(cls)
        out.timing = timing
        return out


class GenerationResult(np.ndarray):
    """A generated token stream: the int64 token array, plus ``finish``
    (why the sequence ended: ``stop_id`` / ``max_new_tokens``) and the
    per-sequence ``timing`` dict (``ttft_ms``, ``decode_ms``,
    ``tokens_per_s``, ``rtt_ms`` — docs/serving-generate.md)."""

    timing: Optional[dict]
    finish: Optional[str]

    def __array_finalize__(self, obj):
        self.timing = getattr(obj, "timing", None)
        self.finish = getattr(obj, "finish", None)

    @classmethod
    def wrap(cls, tokens, finish: Optional[str],
             timing: Optional[dict]) -> "GenerationResult":
        out = np.asarray(tokens, np.int64).view(cls)
        out.finish = finish
        out.timing = timing
        return out


class API:
    """Shared client base (client.py:25)."""

    def __init__(self, backend: Optional[StreamQueue] = None,
                 address: Optional[str] = None):
        self.db = backend if backend is not None else \
            get_queue_backend(address)


class InputQueue(API):
    #: trace id stamped on the most recent enqueue (the handle for
    #: `zoo-serving trace <id>` / `zoo-trace show <id>`)
    last_trace_id: Optional[str] = None

    def __init__(self, backend: Optional[StreamQueue] = None,
                 address: Optional[str] = None,
                 route_workdir: Optional[str] = None):
        """``route_workdir`` opts generate enqueues into length- and
        cache-aware fleet placement: point it at the ServingFleet
        workdir (where workers write heartbeats) and a file-rooted
        transport, and each generate record lands on the cheapest
        worker's substream instead of the shared any-claim stream —
        degrading back to any-claim whenever reports are stale
        (docs/serving-generate.md#fleet-routing)."""
        super().__init__(backend=backend, address=address)
        self._routed = None
        if route_workdir is not None:
            from .routing import RoutedGenerateQueue, file_root

            src = address or os.environ.get("ZOO_SERVING_TRANSPORT")
            if file_root(src) is None and hasattr(self.db, "stream_dir"):
                # backend injected directly or built from a bare path:
                # recover the file root from its stream directory
                src = "file:" + os.path.dirname(self.db.stream_dir)
            if file_root(src) is not None:
                self._routed = RoutedGenerateQueue(
                    route_workdir, src=src, base=self.db)

    @property
    def routing_stats(self) -> Optional[dict]:
        return self._routed.stats() if self._routed is not None else None

    def _route_fields(self, rec: dict, model: Optional[str],
                      version: Optional[int],
                      deadline_ms: Optional[float] = None,
                      trace_id: Optional[str] = None) -> dict:
        # optional on the wire: absent fields route to the server's
        # default model, so pre-registry clients keep working unchanged
        if model is not None:
            rec["model"] = model
        if version is not None:
            rec["version"] = int(version)
        if deadline_ms is not None:
            rec["deadline_ms"] = float(deadline_ms)
        rec["enqueue_ts_ms"] = time.time() * 1e3
        # Dapper-style trace context: every wire record carries a
        # client-stamped trace id + the client's span name as parent;
        # each downstream hop (queue delivery, admission, pipeline
        # stages, device dispatch, write) tags its spans with the same
        # id, so one request merges into one causal tree across
        # processes (docs/observability.md#tracing)
        rec["trace_id"] = trace_id or telemetry.new_trace_id()
        rec["parent_span"] = "client/enqueue"
        self.last_trace_id = rec["trace_id"]
        return rec

    def _traced_enqueue(self, rec: dict) -> str:
        """Enqueue inside a client span tagged with the record's trace
        id, opening the flow arrow the server's intake span closes."""
        with telemetry.span("client/enqueue", trace_id=rec["trace_id"],
                            uri=rec.get("uri")):
            telemetry.flow("serving/request", rec["trace_id"], "s")
            return self.db.enqueue(rec)

    def enqueue_image(self, uri: str, img, model: Optional[str] = None,
                      version: Optional[int] = None,
                      deadline_ms: Optional[float] = None) -> str:
        """Put one image on the stream; ``img`` is an ndarray (HWC BGR
        uint8) or pre-encoded jpg/png bytes (client.py:83-122).
        ``model``/``version`` target a registry-served model;
        ``deadline_ms`` opts into deadline-aware admission control."""
        if isinstance(img, np.ndarray):
            import cv2

            ok, buf = cv2.imencode(".jpg", img.astype(np.uint8))
            if not ok:
                raise ValueError("jpg encode failed")
            data = buf.tobytes()
        else:
            data = bytes(img)
        rec = {"uri": uri, "image": self.base64_encode_image(data)}
        return self._traced_enqueue(
            self._route_fields(rec, model, version, deadline_ms))

    def enqueue(self, uri: str, model: Optional[str] = None,
                version: Optional[int] = None,
                deadline_ms: Optional[float] = None, **tensors) -> str:
        """General tensor input: each kwarg becomes a (shape, data) entry."""
        rec = {"uri": uri, "tensors": {
            k: {"shape": list(np.asarray(v).shape),
                "data": np.asarray(v, np.float32).tobytes()}
            for k, v in tensors.items()}}
        return self._traced_enqueue(
            self._route_fields(rec, model, version, deadline_ms))

    def enqueue_generate(self, uri: str, prompt,
                         max_new_tokens: Optional[int] = None,
                         stop_id: Optional[int] = None,
                         temperature: Optional[float] = None,
                         model: Optional[str] = None,
                         version: Optional[int] = None,
                         deadline_ms: Optional[float] = None) -> str:
        """Submit a generate request: ``prompt`` is a 1-D sequence of
        int token ids; the result (an int64 :class:`GenerationResult`
        of newly generated tokens) lands under ``uri`` the moment the
        sequence finishes — sequences in the same continuous batch
        complete independently (docs/serving-generate.md).  Omitted
        sampling fields fall back to the server's configured defaults."""
        gen: dict = {"prompt": [int(t) for t in np.asarray(prompt).ravel()]}
        if max_new_tokens is not None:
            gen["max_new_tokens"] = int(max_new_tokens)
        if stop_id is not None:
            gen["stop_id"] = int(stop_id)
        if temperature is not None:
            gen["temperature"] = float(temperature)
        rec = {"uri": uri, "generate": gen}
        rec = self._route_fields(rec, model, version, deadline_ms)
        if self._routed is not None:
            with telemetry.span("client/enqueue",
                                trace_id=rec["trace_id"], uri=uri):
                telemetry.flow("serving/request", rec["trace_id"], "s")
                rid, _decision = self._routed.enqueue_routed(rec)
            return rid
        return self._traced_enqueue(rec)

    @staticmethod
    def base64_encode_image(data: bytes) -> str:
        return base64.b64encode(data).decode("utf-8")


class OutputQueue(API):
    def dequeue(self):
        """Fetch-and-clear all results: {uri: ndarray} (client.py:131)."""
        return {uri: self._decode(v, uri)
                for uri, v in self.db.all_results(pop=True).items()}

    def query(self, uri: str):
        """Result for one uri or None (client.py:142)."""
        v = self.db.get_result(uri, pop=False)
        return self._decode(v, uri) if v is not None else None

    def wait_all(self, uris: Iterable[str], timeout: float = 30.0,
                 poll: float = 0.01, max_poll: float = 0.5,
                 raise_on_error: bool = False,
                 deadline_ms: Optional[float] = None
                 ) -> Dict[str, np.ndarray]:
        """Poll until every uri has a result (popping as they land) or
        the deadline passes; returns whatever arrived.  The interval
        backs off exponentially from ``poll`` to ``max_poll`` while
        nothing lands and snaps back to ``poll`` on progress — but never
        sleeps past the deadline, so the budget is honored, not merely
        approximated.

        ``deadline_ms`` is the typed-deadline form: it bounds the wait
        (overriding ``timeout``) and raises :class:`ServingTimeout`
        listing the missing uris when it expires, instead of silently
        returning a partial dict.

        Dead-lettered uris come back as :class:`ServingError` values and
        load-shed uris as :class:`ServingRejected` (structured errors
        instead of a silent timeout); with ``raise_on_error`` the first
        one raises.

        On a transport advertising ``supports_long_poll`` (the socket
        broker) the wait is server-side — ``wait_any`` blocks until a
        wanted result lands, popping only *those* uris, so there is no
        spin-polling and no stealing of other clients' results; every
        other transport keeps the exponential-backoff poll above."""
        want = set(uris)
        got: Dict[str, np.ndarray] = {}
        budget_s = deadline_ms / 1e3 if deadline_ms is not None else timeout
        deadline = time.time() + budget_s
        interval = poll
        long_poll = bool(getattr(self.db, "supports_long_poll", False))
        while want and time.time() < deadline:
            progressed = False
            if long_poll:
                remaining = deadline - time.time()
                if remaining <= 0:
                    break
                landed = self.db.wait_any(sorted(want),
                                          timeout=min(remaining, 5.0),
                                          pop=True)
            else:
                landed = self.db.all_results(pop=True)
            for uri, v in landed.items():
                got[uri] = self._decode(v, uri)
                want.discard(uri)
                progressed = True
            if raise_on_error:
                for v in got.values():
                    if isinstance(v, ServingError):
                        raise v
            if want and not long_poll:
                if progressed:
                    interval = poll
                else:
                    interval = min(interval * 2, max_poll)
                # honor the deadline: never back off past it
                remaining = deadline - time.time()
                if remaining <= 0:
                    break
                time.sleep(min(interval, remaining))
        if want and deadline_ms is not None:
            raise ServingTimeout(want, partial=got, deadline_ms=deadline_ms)
        return got

    @staticmethod
    def _decode(value: bytes, uri: Optional[str] = None):
        obj = json.loads(value.decode("utf-8"))
        if isinstance(obj, dict) and "error" in obj:
            code = obj.get("code")
            if code in ("shed_deadline", "shed_expired",
                        "shed_capacity", "cancelled"):
                return ServingRejected(uri, obj["error"], code,
                                       obj.get("model"),
                                       obj.get("version"),
                                       tokens=obj.get("tokens"))
            return ServingError(uri, obj["error"], obj.get("model"),
                                obj.get("version"))
        timing = obj.get("timing")
        if timing:
            # complete the round trip client-side: total wall from the
            # enqueue stamp, minus time inside the server = wire +
            # result-poll transport
            recv_ms = time.time() * 1e3
            enq = timing.get("enqueue_ts_ms")
            if enq is not None:
                timing["rtt_ms"] = round(recv_ms - enq, 3)
                server_ms = timing.get("server_ms")
                if server_ms is not None:
                    timing["transport_ms"] = round(
                        max(timing["rtt_ms"] - server_ms, 0.0), 3)
            if timing.get("trace_id"):
                telemetry.event("client/result", uri=uri,
                                trace_id=timing["trace_id"],
                                rtt_ms=timing.get("rtt_ms"))
        if "tokens" in obj and "value" not in obj:
            return GenerationResult.wrap(obj["tokens"],
                                         obj.get("finish"), timing)
        return ServingResult.wrap(obj["value"], timing)
