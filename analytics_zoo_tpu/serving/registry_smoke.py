"""Model-registry smoke: hot-swap a version under live multi-model
traffic and fail on any lost record.

CI/tooling entry (``scripts/registry-smoke``): two models ("alpha",
"beta") are deployed into an in-memory :class:`ModelRegistry` behind a
live :class:`RoutedClusterServing`; a producer alternates records
between them while the main thread deploys **alpha v2** mid-traffic
(hot-swap: warm off the serve path, atomic pointer swap, drain v1).
Every enqueued record must come back with a real prediction — any
missing uri, dead-lettered record, or dropped count fails the run.
Constant-kernel models make the serving version observable from the
output value, so the swap is asserted end-to-end: alpha results must
show both v1 and v2 markers, and nothing else.

Usage::

    python -m analytics_zoo_tpu.serving.registry_smoke [--seconds 2]
                                                       [--batch 8]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="registry-smoke")
    ap.add_argument("--seconds", type=float, default=2.0,
                    help="how long to keep producing traffic")
    ap.add_argument("--batch", type=int, default=8)
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from .client import InputQueue, OutputQueue, ServingError
    from .cluster_serving import ClusterServingHelper
    from .queue_backend import InProcessStreamQueue
    from .registry import ModelRegistry
    from .router import RoutedClusterServing
    from .smoke import build_tiny_model

    shape = (3, 8, 8)
    flat = shape[0] * shape[1] * shape[2]
    # constant kernels: a record of all-ones yields flat*scale in every
    # output slot, identifying (model, version) from the value alone
    scales = {"alpha:v1": 1.0, "alpha:v2": 2.0, "beta:v1": 3.0}

    # top_n larger than the output width -> raw values on the wire
    # (top-n would replace them with [argmax, value] pairs)
    helper = ClusterServingHelper(config={
        "data": {"image_shape": "3, 8, 8"},
        "params": {"batch_size": args.batch, "top_n": 100}})
    backend = InProcessStreamQueue()
    registry = ModelRegistry(default_model="alpha")
    serving = RoutedClusterServing(registry, helper=helper,
                                   backend=backend)
    serving.deploy("alpha", model=build_tiny_model(
        shape, scale=scales["alpha:v1"]))
    serving.deploy("beta", model=build_tiny_model(
        shape, scale=scales["beta:v1"]))
    serving.warmup()
    serving.start()

    in_q = InputQueue(backend=backend)
    out_q = OutputQueue(backend=backend)
    uris = {"alpha": [], "beta": []}
    stop = threading.Event()

    def _produce():
        i = 0
        x = np.ones(shape, np.float32)
        while not stop.is_set():
            model = "alpha" if i % 2 == 0 else "beta"
            uri = f"smoke-{model}-{i}"
            in_q.enqueue(uri, model=model, input=x)
            uris[model].append(uri)
            i += 1
            time.sleep(0.002)

    producer = threading.Thread(target=_produce, daemon=True)
    producer.start()
    rc = 0
    try:
        # let v1 serve some traffic, then hot-swap alpha mid-stream
        time.sleep(args.seconds / 2)
        serving.deploy("alpha", model=build_tiny_model(
            shape, scale=scales["alpha:v2"]))
        time.sleep(args.seconds / 2)
        stop.set()
        producer.join()
        all_uris = uris["alpha"] + uris["beta"]
        got = out_q.wait_all(all_uris, timeout=30.0)
    finally:
        stop.set()
        serving.stop()

    stats = serving.pipeline_stats()
    missing = [u for u in uris["alpha"] + uris["beta"] if u not in got]
    errors = [u for u, v in got.items() if isinstance(v, ServingError)]

    def marker(v):
        return round(float(np.asarray(v).ravel()[0]) / flat, 3)

    alpha_markers = {marker(got[u]) for u in uris["alpha"] if u in got
                     and not isinstance(got[u], ServingError)}
    beta_markers = {marker(got[u]) for u in uris["beta"] if u in got
                    and not isinstance(got[u], ServingError)}
    stats.update(submitted=len(uris["alpha"]) + len(uris["beta"]),
                 received=len(got), missing=len(missing),
                 errors=len(errors),
                 alpha_markers=sorted(alpha_markers),
                 beta_markers=sorted(beta_markers))
    print(json.dumps(stats))
    if missing or errors or stats["dropped"] or stats["dead_letters"]:
        print(f"REGISTRY SMOKE FAILED: {len(missing)} missing, "
              f"{len(errors)} errored, {stats['dropped']} dropped, "
              f"{stats['dead_letters']} dead-lettered", file=sys.stderr)
        rc = 1
    elif not alpha_markers <= {1.0, 2.0} or 2.0 not in alpha_markers:
        print(f"REGISTRY SMOKE FAILED: alpha markers {alpha_markers} "
              f"(want subset of {{1.0, 2.0}} including post-swap 2.0)",
              file=sys.stderr)
        rc = 1
    elif beta_markers != {3.0}:
        print(f"REGISTRY SMOKE FAILED: beta markers {beta_markers} "
              f"(want exactly {{3.0}})", file=sys.stderr)
        rc = 1
    else:
        print(f"REGISTRY SMOKE OK: {stats['submitted']} records across "
              f"2 models, alpha hot-swapped v1->v2 with 0 lost "
              f"(markers {sorted(alpha_markers)})", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
