"""SocketStreamQueue: the network transport for Cluster Serving.

The reference's front door is a Redis stream (``image_stream`` XADD /
XREAD, ClusterServing.scala:105-116).  This module is the stdlib
equivalent: a small TCP broker (:class:`StreamQueueBroker`, built on
``socketserver``) speaking length-prefixed msgpack frames, and a client
(:class:`SocketStreamQueue`) implementing the full
:class:`~analytics_zoo_tpu.serving.queue_backend.StreamQueue` contract
— so N fleet workers on N hosts share one stream without per-record
file I/O (docs/serving-network.md).

Wire protocol
-------------
Every frame is ``4-byte big-endian length + msgpack map``; every
request map carries ``op`` and gets exactly one response map
(``{"ok": True, ...}`` or ``{"ok": False, "error": ...}``) on the same
connection.  Connections are persistent; clients keep one per thread so
a blocking long-poll never serializes behind another op.

Delivery contract (claim ledger instead of atomic rename)
---------------------------------------------------------
``read_batch`` is a **single-assignment claim**: the broker moves the
delivered records from the stream into a per-consumer claim table, so
two fleet workers can never double-serve a record.  A claim is released
by an ``ack`` — which :meth:`SocketStreamQueue.put_results` piggybacks
on the result commit, so the happy path costs no extra round trip.
Unacked claims are **redelivered** (requeued at the stream head, FIFO
preserved) when:

- the consumer's read connection drops (worker SIGKILL / host loss) —
  detected immediately at EOF, or
- a claim outlives ``claim_timeout_s`` (worker wedged while its
  connection lingers) — swept lazily on the next ``read_batch``.

Redelivery after a *successful-but-unacked* commit is harmless: the
results map is idempotent per uri, and each consumer's delivery ledger
(queue_backend.DeliveryLedger) drops duplicate rids client-side.

Result long-poll
----------------
``wait_results`` blocks server-side until any wanted uri has a result
(or the timeout lapses), so clients stop spin-polling ``all_results``
— :meth:`OutputQueue.wait_all` uses it when the transport advertises
``supports_long_poll``.

Timing decomposition survives the hop: the client stamps
``dequeue_ts_ms`` + the ``queue/deliver`` trace event at delivery
(StreamQueue._stamp_dequeue), in the worker process where the trace
spans live.

Run a standalone broker with::

    python -m analytics_zoo_tpu.serving.socket_queue --port 6380
"""

from __future__ import annotations

import argparse
import itertools
import logging
import socket
import socketserver
import threading
import time
import uuid
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import msgpack

from .queue_backend import DeliveryLedger, StreamQueue

logger = logging.getLogger("analytics_zoo_tpu.serving.socket_queue")

#: frame size guard — a length prefix beyond this is a protocol error,
#: not an allocation request (a stray HTTP client must not OOM the broker)
MAX_FRAME = 64 * 1024 * 1024

#: producer-token dedup window (enqueue retried over a new connection
#: after a send error must not double-insert)
TOKEN_WINDOW = 65536


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return bytes(buf)


def read_frame(sock: socket.socket) -> dict:
    n = int.from_bytes(_recv_exact(sock, 4), "big")
    if n > MAX_FRAME:
        raise ConnectionError(f"frame of {n} bytes exceeds MAX_FRAME")
    return msgpack.unpackb(_recv_exact(sock, n), raw=False)


def write_frame(sock: socket.socket, obj: dict):
    payload = msgpack.packb(obj, use_bin_type=True)
    sock.sendall(len(payload).to_bytes(4, "big") + payload)


class _Handler(socketserver.BaseRequestHandler):
    """One thread per connection; strictly request→response."""

    def setup(self):
        self.request.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.broker: "StreamQueueBroker" = self.server.broker
        self.conn_id = id(self)
        with self.broker._cv:
            self.broker._connections += 1

    def handle(self):
        while True:
            try:
                req = read_frame(self.request)
            except (ConnectionError, OSError):
                return
            try:
                resp = self.broker.dispatch(req, self.conn_id)
                resp.setdefault("ok", True)
            except Exception as e:  # noqa: BLE001 - report, keep serving
                resp = {"ok": False, "error": f"{type(e).__name__}: {e}"}
            try:
                write_frame(self.request, resp)
            except (ConnectionError, OSError):
                return

    def finish(self):
        # EOF on a consumer's read connection == worker death: requeue
        # its unacked claims so another worker serves them
        self.broker.release_connection(self.conn_id)
        with self.broker._cv:
            self.broker._connections -= 1


class _TCPServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


class StreamQueueBroker:
    """In-process TCP broker holding the stream, claims, and results.

    ``port=0`` binds an ephemeral port (see :attr:`port` /
    :attr:`address` after construction).  :meth:`start` serves on a
    daemon thread; :meth:`run_forever` serves in the foreground.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 name: str = "image_stream", claim_timeout_s: float = 60.0,
                 op_cost_ms: float = 0.0):
        self.name = name
        self.claim_timeout_s = float(claim_timeout_s)
        # stubbed serialized-core cost: sleep this long INSIDE the stream
        # lock on each data-plane op, so scale-out benches on a 1-core
        # host can model N brokers on N cores (sleeping releases the GIL,
        # so two brokers' ops overlap the way two cores would, while one
        # broker's ops stay serialized on its lock).  0 = off; see
        # BENCH_NOTES.md for the stubbed-cost methodology.
        self.op_cost_ms = float(op_cost_ms)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)          # stream
        self._results_cv = threading.Condition(self._lock)  # results
        self._stream: "OrderedDict[str, dict]" = OrderedDict()
        # consumer -> rid -> (record, claim_ts); OrderedDict so a
        # requeue preserves the consumer's delivery order
        self._claims: Dict[str, "OrderedDict[str, Tuple[dict, float]]"] = {}
        self._consumer_conn: Dict[str, int] = {}
        self._results: Dict[str, bytes] = {}
        self._tokens: "OrderedDict[str, str]" = OrderedDict()
        self._seq = itertools.count()
        self._broker_id = uuid.uuid4().hex[:8]
        # counters (all under _lock)
        self._connections = 0
        self.enqueued = 0
        self.delivered = 0
        self.redelivered = 0
        self.acked = 0
        self.trimmed = 0
        self._server = _TCPServer((host, int(port)), _Handler)
        self._server.broker = self
        self.host, self.port = self._server.server_address[:2]
        self._thread: Optional[threading.Thread] = None
        self._shutdown_once = threading.Lock()
        self._shut_down = False

    @property
    def address(self) -> str:
        return f"socket://{self.host}:{self.port}"

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "StreamQueueBroker":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        kwargs={"poll_interval": 0.1},
                                        daemon=True, name="queue-broker")
        self._thread.start()
        logger.info("stream broker serving on %s", self.address)
        return self

    def run_forever(self):  # pragma: no cover - foreground CLI path
        logger.info("stream broker serving on %s", self.address)
        self._server.serve_forever(poll_interval=0.1)

    def shutdown(self):
        # Idempotent and thread-safe: the CLI's SIGTERM handler shuts
        # down from a helper thread while the foreground finally-block
        # does the same (server.shutdown() must never run on the thread
        # inside serve_forever, or it deadlocks waiting for the ack).
        with self._shutdown_once:
            if self._shut_down:
                return
            self._shut_down = True
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()

    # -- claim bookkeeping (caller holds _lock) -------------------------
    def _requeue_locked(self, consumer: str, why: str):
        claims = self._claims.pop(consumer, None)
        if not claims:
            return
        # claimed rids predate everything still queued (they were popped
        # from the head), so re-inserting them at the front — newest of
        # the batch first — restores global FIFO order exactly
        for rid, (rec, _ts) in reversed(list(claims.items())):
            self._stream[rid] = rec
            self._stream.move_to_end(rid, last=False)
        self.redelivered += len(claims)
        logger.info("requeued %d unacked claim(s) of consumer %s (%s)",
                    len(claims), consumer, why)
        self._cv.notify_all()

    def _sweep_expired_locked(self, now: float):
        for consumer, claims in list(self._claims.items()):
            expired = [rid for rid, (_r, ts) in claims.items()
                       if now - ts > self.claim_timeout_s]
            if not expired:
                continue
            for rid in reversed(expired):
                rec, _ts = claims.pop(rid)
                self._stream[rid] = rec
                self._stream.move_to_end(rid, last=False)
            self.redelivered += len(expired)
            logger.info("requeued %d claim(s) of consumer %s past "
                        "claim_timeout", len(expired), consumer)
            if not claims:
                del self._claims[consumer]
            self._cv.notify_all()

    def release_connection(self, conn_id: int):
        """Connection closed: redeliver unacked claims of every consumer
        whose *lease* (most recent read_batch) rode this connection."""
        with self._lock:
            for consumer, cid in list(self._consumer_conn.items()):
                if cid != conn_id:
                    continue
                del self._consumer_conn[consumer]
                self._requeue_locked(consumer, "connection closed")

    # -- ops ------------------------------------------------------------
    def dispatch(self, req: dict, conn_id: int) -> dict:
        op = req.get("op")
        fn = getattr(self, f"_op_{op}", None)
        if fn is None:
            raise ValueError(f"unknown op {op!r}")
        return fn(req, conn_id)

    def _op_enqueue(self, req, conn_id):
        records = req.get("records") or []
        toks = req.get("toks") or [None] * len(records)
        rids = []
        with self._cv:
            if self.op_cost_ms:
                time.sleep(self.op_cost_ms / 1e3)
            for rec, tok in zip(records, toks):
                if tok is not None and tok in self._tokens:
                    rids.append(self._tokens[tok])   # retried send: dedup
                    continue
                rid = (f"{time.time_ns():020d}-{self._broker_id}"
                       f"-{next(self._seq):08d}")
                self._stream[rid] = rec
                self.enqueued += 1
                rids.append(rid)
                if tok is not None:
                    self._tokens[tok] = rid
                    while len(self._tokens) > TOKEN_WINDOW:
                        self._tokens.popitem(last=False)
            self._cv.notify_all()
        return {"rids": rids}

    def _op_read_batch(self, req, conn_id):
        consumer = req["consumer"]
        max_items = int(req.get("max", 1))
        deadline = time.time() + float(req.get("timeout_ms", 1000)) / 1e3
        with self._cv:
            if self.op_cost_ms:
                time.sleep(self.op_cost_ms / 1e3)
            # this connection is now the consumer's lease: its death
            # triggers redelivery of whatever this read hands out
            self._consumer_conn[consumer] = conn_id
            self._sweep_expired_locked(time.time())
            while not self._stream:
                remaining = deadline - time.time()
                if remaining <= 0:
                    return {"items": []}
                self._cv.wait(timeout=min(remaining, 0.5))
            now = time.time()
            claims = self._claims.setdefault(consumer, OrderedDict())
            items = []
            while self._stream and len(items) < max_items:
                rid, rec = self._stream.popitem(last=False)
                claims[rid] = (rec, now)
                items.append([rid, rec])
            self.delivered += len(items)
            return {"items": items}

    def _op_ack(self, req, conn_id):
        consumer = req["consumer"]
        n = 0
        with self._lock:
            claims = self._claims.get(consumer)
            if claims:
                for rid in req.get("rids") or []:
                    if claims.pop(rid, None) is not None:
                        n += 1
                if not claims:
                    self._claims.pop(consumer, None)
            self.acked += n
        return {"acked": n}

    def _op_put_results(self, req, conn_id):
        results = req.get("results") or {}
        with self._results_cv:
            self._results.update(results)
            self._results_cv.notify_all()
        # piggybacked claim release — the happy path needs no extra ack
        if req.get("consumer") and req.get("rids"):
            self._op_ack(req, conn_id)
        return {"n": len(results)}

    def _op_get_result(self, req, conn_id):
        uri = req["uri"]
        with self._lock:
            v = (self._results.pop(uri, None) if req.get("pop", True)
                 else self._results.get(uri))
        return {"value": v}

    def _op_all_results(self, req, conn_id):
        with self._lock:
            out = dict(self._results)
            if req.get("pop", True):
                self._results.clear()
        return {"results": out}

    def _op_wait_results(self, req, conn_id):
        """Result long-poll: block until any wanted uri has a result."""
        want = set(req.get("uris") or [])
        pop = req.get("pop", True)
        deadline = time.time() + float(req.get("timeout_ms", 1000)) / 1e3
        with self._results_cv:
            while True:
                found = want & self._results.keys()
                if found:
                    out = {}
                    for uri in found:
                        out[uri] = (self._results.pop(uri) if pop
                                    else self._results[uri])
                    return {"results": out}
                remaining = deadline - time.time()
                if remaining <= 0:
                    return {"results": {}}
                self._results_cv.wait(timeout=min(remaining, 0.5))

    def _op_stream_len(self, req, conn_id):
        with self._lock:
            return {"n": len(self._stream)}

    def _op_trim(self, req, conn_id):
        keep = int(req.get("keep_last", 0))
        n = 0
        with self._lock:
            while len(self._stream) > keep:
                self._stream.popitem(last=False)
                n += 1
            self.trimmed += n
        return {"trimmed": n}

    def _op_stats(self, req, conn_id):
        with self._lock:
            return {"stats": self._stats_locked()}

    def _stats_locked(self) -> dict:
        return {
            "address": self.address,
            "connections": self._connections,
            "consumers": len(self._consumer_conn),
            "stream_len": len(self._stream),
            "claims_outstanding": sum(len(c)
                                      for c in self._claims.values()),
            "results_pending": len(self._results),
            "enqueued": self.enqueued,
            "delivered": self.delivered,
            "redelivered": self.redelivered,
            "acked": self.acked,
            "trimmed": self.trimmed,
        }

    def stats(self) -> dict:
        with self._lock:
            return self._stats_locked()


def parse_socket_spec(spec: str) -> Tuple[str, int]:
    """``socket://host:port`` -> (host, port)."""
    rest = spec[len("socket://"):] if spec.startswith("socket://") else spec
    host, _, port = rest.rpartition(":")
    if not host or not port:
        raise ValueError(f"bad socket spec {spec!r} "
                         "(want socket://host:port)")
    return host, int(port)


class SocketStreamQueue(StreamQueue):
    """Client side of the broker protocol — a drop-in StreamQueue.

    One TCP connection per calling thread (``threading.local``), so the
    serving loop's intake thread can sit in a ``read_batch`` long-poll
    while the writer thread commits results concurrently.  A send/recv
    error closes the connection and retries once on a fresh one —
    enqueues carry a dedup token so the retry can't double-insert, and
    the broker requeues any claims the dead connection held.
    """

    #: OutputQueue.wait_all switches from exponential-backoff polling to
    #: wait_any() when the transport sets this
    supports_long_poll = True

    def __init__(self, host: str, port: int, name: str = "image_stream",
                 connect_timeout: float = 10.0):
        self.host, self.port = host, int(port)
        self.name = name
        self.connect_timeout = float(connect_timeout)
        self.consumer = uuid.uuid4().hex[:12]
        self._local = threading.local()
        self._lock = threading.Lock()
        self._socks: List[socket.socket] = []
        # uri -> rids claimed by this consumer and not yet committed;
        # put_results() turns the matching entries into piggybacked acks
        self._unacked: Dict[str, List[str]] = {}
        self._ledger = DeliveryLedger()

    # -- connection management ------------------------------------------
    def _conn(self) -> socket.socket:
        sock = getattr(self._local, "sock", None)
        if sock is None:
            sock = socket.create_connection((self.host, self.port),
                                            timeout=self.connect_timeout)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._local.sock = sock
            with self._lock:
                self._socks.append(sock)
        return sock

    def _drop_conn(self):
        sock = getattr(self._local, "sock", None)
        if sock is not None:
            self._local.sock = None
            with self._lock:
                if sock in self._socks:
                    self._socks.remove(sock)
            try:
                sock.close()
            except OSError:
                pass

    def close(self):
        with self._lock:
            socks, self._socks = self._socks[:], []
        for sock in socks:
            try:
                sock.close()
            except OSError:
                pass

    def _request(self, req: dict, timeout_s: float = 30.0) -> dict:
        for attempt in (0, 1):
            sock = self._conn()
            try:
                sock.settimeout(timeout_s)
                write_frame(sock, req)
                resp = read_frame(sock)
                break
            except (ConnectionError, OSError) as e:
                self._drop_conn()
                if attempt:
                    raise ConnectionError(
                        f"broker at {self.host}:{self.port} unreachable: "
                        f"{e}") from e
        if not resp.get("ok"):
            raise RuntimeError(f"broker error: {resp.get('error')}")
        return resp

    # -- StreamQueue contract -------------------------------------------
    def enqueue(self, record: dict, token: Optional[str] = None) -> str:
        # a caller-supplied token lets a fabric retry the SAME logical
        # send against this broker without double-inserting (shard
        # failover reuses one token across attempts)
        return self._request({"op": "enqueue", "records": [record],
                              "toks": [token or uuid.uuid4().hex]}
                             )["rids"][0]

    def read_batch(self, max_items: int, timeout: float = 1.0
                   ) -> List[Tuple[str, dict]]:
        resp = self._request(
            {"op": "read_batch", "consumer": self.consumer,
             "max": int(max_items), "timeout_ms": float(timeout) * 1e3},
            timeout_s=float(timeout) + 30.0)
        out: List[Tuple[str, dict]] = []
        for rid, rec in resp.get("items") or []:
            if not self._ledger.note(rid):
                # duplicate redelivery (claim-timeout raced an in-flight
                # batch): ack so the broker stops re-offering it
                self._request({"op": "ack", "consumer": self.consumer,
                               "rids": [rid]})
                continue
            uri = rec.get("uri") if isinstance(rec, dict) else None
            if uri is not None:
                with self._lock:
                    self._unacked.setdefault(uri, []).append(rid)
            out.append((rid, rec))
        return self._stamp_dequeue(out)

    def _take_acks(self, uris) -> List[str]:
        rids: List[str] = []
        with self._lock:
            for uri in uris:
                rids.extend(self._unacked.pop(uri, ()))
        return rids

    def put_result(self, uri: str, value: bytes):
        self.put_results({uri: value})

    def put_results(self, results: Dict[str, bytes]):
        req = {"op": "put_results",
               "results": {u: bytes(v) for u, v in results.items()}}
        rids = self._take_acks(results.keys())
        if rids:
            req["consumer"] = self.consumer
            req["rids"] = rids
        self._request(req)

    def get_result(self, uri: str, pop: bool = True) -> Optional[bytes]:
        return self._request({"op": "get_result", "uri": uri,
                              "pop": pop})["value"]

    def all_results(self, pop: bool = True) -> Dict[str, bytes]:
        return self._request({"op": "all_results",
                              "pop": pop})["results"]

    def wait_any(self, uris, timeout: float = 1.0,
                 pop: bool = True) -> Dict[str, bytes]:
        """Long-poll: block until ANY of ``uris`` has a result (returns
        the found subset, possibly empty on timeout)."""
        return self._request(
            {"op": "wait_results", "uris": list(uris),
             "timeout_ms": float(timeout) * 1e3, "pop": pop},
            timeout_s=float(timeout) + 30.0)["results"]

    def stream_len(self) -> int:
        return self._request({"op": "stream_len"})["n"]

    def trim(self, keep_last: int):
        self._request({"op": "trim", "keep_last": int(keep_last)})

    # -- observability ---------------------------------------------------
    def stats(self) -> dict:
        """Broker-side transport stats (zoo-serving status renders these:
        connections, claims outstanding, redeliveries)."""
        return self._request({"op": "stats"})["stats"]

    def consumer_stats(self) -> dict:
        """Delivery-integrity counters for THIS consumer (same shape as
        FileStreamQueue.consumer_stats)."""
        return self._ledger.stats()


def main(argv=None) -> int:  # pragma: no cover - CLI entry
    ap = argparse.ArgumentParser(
        prog="zoo-stream-broker",
        description="Standalone stream broker for socket:// serving")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=6380)
    ap.add_argument("--claim-timeout-s", type=float, default=60.0)
    ap.add_argument("--op-cost-ms", type=float, default=0.0,
                    help="stubbed serialized-core cost per data-plane op "
                         "(scale-out benches on few-core hosts)")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s broker %(message)s")
    broker = StreamQueueBroker(host=args.host, port=args.port,
                               claim_timeout_s=args.claim_timeout_s,
                               op_cost_ms=args.op_cost_ms)
    try:
        broker.run_forever()
    except KeyboardInterrupt:
        pass
    finally:
        broker.shutdown()
    return 0


if __name__ == "__main__":  # pragma: no cover
    import sys

    sys.exit(main())
