"""Network-serving end-to-end smoke (``scripts/net-smoke``; CI fast tier).

Brings up the socket transport's full production shape — an in-process
:class:`StreamQueueBroker`, an autoscaling :class:`ServingFleet` of
socket-connected workers, and real clients — and asserts the network
contract (docs/serving-network.md):

- **exactly-once over the wire**: every enqueued uri gets exactly one
  result carrying *its own* record's value, with the broker's claim
  ledger (not file renames) partitioning work across the fleet;
- **redelivery on worker death**: a worker SIGKILLed mid-stream drops
  its broker connection; the broker requeues that consumer's unacked
  claims (``redelivered > 0``) and the survivors finish the burst with
  no record lost or double-answered;
- **backlog autoscaling**: the burst grows the fleet to
  ``max_workers`` (scale_up events in the autoscale trace), the idle
  window after it shrinks back to ``min_workers`` (scale_down events),
  and scaling never sheds or loses a record.

Exit 0 on success, 1 on any violated assertion (printing the fan-in
worker log for diagnosis).
"""

from __future__ import annotations

import argparse
import io
import os
import shutil
import signal
import sys
import tempfile
import threading
import time

CONFIG_TMPL = """\
model:
  stub_ms_per_batch: {stub_ms}

data:
  src: socket://127.0.0.1:{port}
  image_shape: 3, 4, 4

params:
  batch_size: 4
  top_n: 0
  workers: 2
  min_workers: 1
  max_workers: 3
  autoscale_target_ms: {target_ms}
  autoscale_interval: 0.2
  autoscale_cooldown_s: 0.5
  scale_down_idle_s: {idle_s}
  health_interval: 0.25
  health_timeout: {health_timeout}
"""


def run_smoke(records: int = 160, stub_ms: float = 30.0,
              target_ms: float = 100.0, idle_s: float = 1.5,
              health_timeout: float = 5.0, stream=None) -> int:
    import numpy as np

    from .client import InputQueue, OutputQueue
    from .fleet import ServingFleet, read_autoscale_trace, read_health
    from .socket_queue import SocketStreamQueue, StreamQueueBroker

    out = stream if stream is not None else sys.stdout
    workdir = tempfile.mkdtemp(prefix="zoo_net_smoke_")
    broker = StreamQueueBroker().start()
    cfg = os.path.join(workdir, "config.yaml")
    with open(cfg, "w") as f:
        f.write(CONFIG_TMPL.format(stub_ms=stub_ms, port=broker.port,
                                   target_ms=target_ms, idle_s=idle_s,
                                   health_timeout=health_timeout))
    shape = (3, 4, 4)
    cap = io.StringIO()

    def fail(msg):
        out.write(cap.getvalue())
        out.write(f"NET_SMOKE_FAIL: {msg}\n")
        return 1

    fleet = ServingFleet(cfg, workdir, stream=cap,
                         env={"JAX_PLATFORMS": "cpu"})
    sup = threading.Thread(target=fleet.supervise, daemon=True)
    try:
        fleet.start()
        sup.start()
        if not fleet.wait_healthy(timeout=90.0):
            return fail("workers never became healthy")

        # -- phase 1: burst through the broker; backlog must grow the
        # fleet to max_workers while it drains ------------------------
        mk = lambda: SocketStreamQueue("127.0.0.1", broker.port)  # noqa: E731
        in_q = InputQueue(backend=mk())
        out_q = OutputQueue(backend=mk())
        uris = [f"u-{i}" for i in range(records)]
        for i, uri in enumerate(uris):
            in_q.enqueue(uri, input=np.full(shape, i, np.float32))

        # -- phase 2: SIGKILL a socket-connected worker mid-stream; the
        # broker must requeue its unacked claims ----------------------
        victim = 1
        h0 = read_health(workdir, victim)
        if not h0:
            return fail("no health file for victim worker")
        deadline = time.time() + 30.0
        while broker.stats()["delivered"] < records // 4:
            if time.time() > deadline:
                return fail("burst never started draining")
            time.sleep(0.02)
        os.kill(int(h0["pid"]), signal.SIGKILL)

        got = out_q.wait_all(uris, timeout=120.0)
        if len(got) != records:
            return fail(f"only {len(got)}/{records} results after kill")
        for i, uri in enumerate(uris):
            v = got[uri]
            if isinstance(v, Exception):
                return fail(f"{uri} errored: {v}")
            if abs(float(np.asarray(v).ravel()[0]) - i) > 1e-4:
                return fail(f"{uri} value {float(np.asarray(v).ravel()[0])}"
                            f" != {i} (cross-wired)")
        st = broker.stats()
        if st["redelivered"] < 1:
            return fail(f"SIGKILL of a connected worker produced no "
                        f"redelivery (stats {st})")
        grew = max((e["active"] for e in fleet.autoscale_events
                    if e["action"] == "scale_up"), default=fleet.workers)
        if grew < fleet.max_workers:
            return fail(f"burst never grew the fleet to max "
                        f"({grew} < {fleet.max_workers}); "
                        f"events={fleet.autoscale_events}")

        # -- phase 3: idle window shrinks the fleet back to min -------
        deadline = time.time() + 60.0
        while len(fleet._active) > fleet.min_workers:
            if time.time() > deadline:
                return fail(f"idle fleet never shrank to min "
                            f"({sorted(fleet._active)}); "
                            f"events={fleet.autoscale_events}")
            time.sleep(0.1)
        trace = read_autoscale_trace(workdir)
        actions = [e["action"] for e in trace]
        if "scale_up" not in actions or "scale_down" not in actions:
            return fail(f"autoscale trace missing up/down: {actions}")
        # a shrunken fleet must still answer (drain-before-kill left
        # nothing stranded, min worker still claims from the broker)
        in_q.enqueue("after-scale", input=np.full(shape, 7.0, np.float32))
        got2 = out_q.wait_all(["after-scale"], timeout=60.0)
        v = got2.get("after-scale")
        if v is None or isinstance(v, Exception) or \
                abs(float(np.asarray(v).ravel()[0]) - 7.0) > 1e-4:
            return fail(f"post-scale-down request failed: {v!r}")
        st = broker.stats()
        if st["claims_outstanding"] != 0:
            return fail(f"claims leaked: {st}")

        out.write(f"NET_SMOKE_OK records={records} "
                  f"redelivered={st['redelivered']} "
                  f"scaled_up_to={grew} "
                  f"scaled_down_to={len(fleet._active)} "
                  f"autoscale_events={len(trace)}\n")
        return 0
    finally:
        fleet.stop()
        sup.join(timeout=30.0)
        fleet.shutdown()
        broker.shutdown()
        shutil.rmtree(workdir, ignore_errors=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="net-smoke")
    ap.add_argument("--records", type=int, default=160)
    ap.add_argument("--stub-ms", type=float, default=30.0)
    ap.add_argument("--idle-s", type=float, default=1.5)
    ap.add_argument("--health-timeout", type=float, default=5.0)
    args = ap.parse_args(argv)
    return run_smoke(records=args.records, stub_ms=args.stub_ms,
                     idle_s=args.idle_s,
                     health_timeout=args.health_timeout)


if __name__ == "__main__":
    sys.exit(main())
