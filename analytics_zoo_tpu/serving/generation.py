"""Continuous-batching generative serving: decode engines + scheduler.

The serving layer's autoregressive workload front. Classification
serving dispatches a batch and is done; generation holds a sequence in
flight for tens-to-thousands of decode steps. Batching at *request*
granularity (static batching) means the whole gang waits for its
slowest member — short answers pay for long ones. Iteration-level
scheduling (Orca, OSDI '22) rebatches at every token boundary instead:

- the in-flight batch is a set of **cache slots** over preallocated
  power-of-two KV slabs (``ops/kv_cache.py``);
- a finished sequence (stop token / max_new_tokens / deadline) is
  **evicted at the very step it finishes** and its result committed
  immediately;
- the freed slot is **refilled from the admission queue
  mid-generation** — joiners prefill into the running gang without
  stalling it;
- admission reuses the padding-bucket + linger machinery, with the
  EWMA deadline shed extended by a per-token service estimate
  (:meth:`AdmissionController.admit_generate`), and a mid-stream shed
  (:meth:`AdmissionController.stream_expired`) that evicts a sequence
  whose deadline passes while decoding, committing a typed
  ``shed_deadline`` payload that carries the partial tokens.

Two engines implement the gang interface: ``TransformerDecodeEngine``
(the real KV-cache decode path through ``TransformerLayer``) and
``StubDecodeEngine`` (a deterministic CPU stand-in whose decode step
costs a flat ``ms_per_step`` regardless of gang width — the
MXU-amortization property that makes continuous batching pay; the
bench ``generation`` leg and the fast-tier smoke run on it).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..ops.kv_cache import cache_length_buckets, pick_cache_bucket
from ..utils import telemetry
from ..utils.telemetry import span
from .admission import (SHED_DEADLINE, AdaptiveBatcher, AdmissionController,
                        now_ms)

logger = logging.getLogger(__name__)

#: eviction reasons — the "reason" label on zoo_generate_evict_total and
#: the "finish" field of committed results
FINISH_STOP = "stop_id"
FINISH_MAX_TOKENS = "max_new_tokens"
FINISH_DEADLINE = "shed_deadline"
FINISH_CANCELLED = "cancelled"

#: typed shed code for prompts no cache bucket can hold
SHED_CAPACITY = "shed_capacity"


@dataclass
class GenRequest:
    """One generate request as it leaves the wire decoder."""

    uri: str
    prompt: np.ndarray                  # 1-D int token ids
    max_new_tokens: int = 32
    stop_id: Optional[int] = None
    temperature: float = 0.0            # 0 = greedy
    deadline_at_ms: Optional[float] = None
    enqueue_ts_ms: Optional[float] = None
    t_in: float = field(default_factory=time.perf_counter)
    trace_id: Optional[str] = None      # client-stamped trace context

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt).astype(np.int64).ravel()
        self.max_new_tokens = max(int(self.max_new_tokens), 1)


@dataclass
class _Slot:
    """Scheduler-side tracker for one in-flight sequence."""

    req: GenRequest
    tokens: List[int] = field(default_factory=list)
    last: int = 0
    t_join: float = 0.0
    t_first_token: Optional[float] = None
    t_tokens: List[float] = field(default_factory=list)
    finish: Optional[str] = None


# ---------------------------------------------------------------------------
# engines
# ---------------------------------------------------------------------------

class StubDecodeEngine:
    """Deterministic gang-decode stand-in (the generate analogue of
    ``EchoStubModel``).

    Token stream for a prompt ``p``: token i (1-based) is ``p[0] + i``,
    except that when the prompt has a second element ``p[1] > 0`` the
    stream emits ``stop_id`` at position ``p[1]`` — letting tests
    script stop-token eviction per request. ``step()`` sleeps a flat
    ``ms_per_step`` for the *whole gang* (device-like cost: one MXU
    pass per token boundary, amortized over every active slot) and
    ``join()`` sleeps ``ms_per_prefill`` once.
    """

    def __init__(self, ms_per_step: float = 1.0,
                 ms_per_prefill: float = 0.0, stop_id: int = 0,
                 capacity_buckets: Optional[Sequence[int]] = None):
        self.ms_per_step = float(ms_per_step)
        self.ms_per_prefill = float(ms_per_prefill)
        self.stop_id = int(stop_id)
        self.buckets = list(capacity_buckets or cache_length_buckets(1024))

    def alloc(self, nslots: int, capacity: int):
        # per-slot [base, emitted, stop_at]; None = free
        return [None] * nslots

    def grow(self, state, capacity: int):
        return state

    def join(self, state, slot: int, req: GenRequest):
        if self.ms_per_prefill > 0:
            time.sleep(self.ms_per_prefill / 1e3)
        p = req.prompt
        base = int(p[0]) if p.size else 0
        stop_at = int(p[1]) if p.size > 1 and int(p[1]) > 0 else None
        state[slot] = [base, 1, stop_at]
        first = self.stop_id if stop_at == 1 else base + 1
        return state, first

    def step(self, state, feeds: Dict[int, int],
             temps: Dict[int, float]):
        """Advance every fed slot one token; flat gang-wide cost."""
        if self.ms_per_step > 0:
            time.sleep(self.ms_per_step / 1e3)
        out = {}
        for slot in feeds:
            base, emitted, stop_at = state[slot]
            emitted += 1
            state[slot][1] = emitted
            out[slot] = self.stop_id if stop_at == emitted else base + emitted
        return state, out

    def evict(self, state, slot: int):
        state[slot] = None
        return state


class TransformerDecodeEngine:
    """Gang decode over a causal ``TransformerLayer`` via its KV-cache
    API (``prefill`` / ``decode_step`` on ops/kv_cache.py slabs).

    A join prefills the prompt on a batch-1 state of the gang's
    capacity and splices the resulting slabs into the joiner's slot —
    the running gang never recomputes. Freed slots sit at length 0:
    their rows are masked out of every step, and whatever the dead slot
    keeps emitting is discarded by the scheduler.
    """

    def __init__(self, layer, params, max_len: Optional[int] = None,
                 rng=None):
        import jax
        import jax.numpy as jnp

        self.layer = layer
        self.params = params
        self.buckets = cache_length_buckets(
            max_len or layer.seq_len, min_bucket=min(128, layer.seq_len))
        self._jnp = jnp
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)
        self._step_fn = jax.jit(lambda p, s, t: layer.decode_step(p, s, t))

    def alloc(self, nslots: int, capacity: int):
        return self.layer.init_decode_state(nslots, capacity)

    def grow(self, state, capacity: int):
        jnp = self._jnp
        if capacity <= state.capacity:
            return state
        pad = [(0, 0), (0, capacity - state.capacity), (0, 0), (0, 0)]
        return state._replace(
            k_cache=tuple(jnp.pad(k, pad) for k in state.k_cache),
            v_cache=tuple(jnp.pad(v, pad) for v in state.v_cache))

    def _pick(self, logits, temperature: float) -> int:
        import jax

        if temperature and temperature > 0.0:
            self._rng, sub = jax.random.split(self._rng)
            return int(jax.random.categorical(
                sub, logits.astype(self._jnp.float32) / temperature))
        return int(self._jnp.argmax(logits))

    def join(self, state, slot: int, req: GenRequest):
        from ..ops.kv_cache import place_slot

        jnp = self._jnp
        st1 = self.layer.init_decode_state(1, state.capacity,
                                           dtype=state.k_cache[0].dtype)
        logits, st1 = self.layer.prefill(
            self.params, jnp.asarray(req.prompt, jnp.int32)[None],
            jnp.array([req.prompt.size], jnp.int32), st1)
        state = state._replace(
            k_cache=tuple(place_slot(k, slot, s1[0])
                          for k, s1 in zip(state.k_cache, st1.k_cache)),
            v_cache=tuple(place_slot(v, slot, s1[0])
                          for v, s1 in zip(state.v_cache, st1.v_cache)),
            lengths=state.lengths.at[slot].set(int(req.prompt.size)))
        return state, self._pick(logits[0], req.temperature)

    def step(self, state, feeds: Dict[int, int],
             temps: Dict[int, float]):
        jnp = self._jnp
        tokens = np.zeros((state.batch,), np.int32)
        for slot, tok in feeds.items():
            tokens[slot] = tok
        logits, state = self._step_fn(self.params, state,
                                      jnp.asarray(tokens))
        out = {slot: self._pick(logits[slot], temps.get(slot, 0.0))
               for slot in feeds}
        return state, out

    def evict(self, state, slot: int):
        from ..ops.kv_cache import evict_slot

        return state._replace(lengths=evict_slot(state.lengths, slot))


# ---------------------------------------------------------------------------
# scheduler
# ---------------------------------------------------------------------------

class ContinuousBatchScheduler:
    """Iteration-level scheduler over a gang-decode engine.

    Loop body (one token boundary): **evict** finished sequences and
    commit their results immediately → **refill** the freed cache
    slots from the admission queue (``admit_generate`` sheds requests
    whose deadline cannot survive the queue depth; joiners prefill
    into the running gang) → **step** the gang one token
    (``observe_tokens`` feeds the per-token EWMA back to admission).

    ``continuous=False`` degrades to static batching — the gang only
    refills once *every* slot has drained — which is the baseline leg
    of the bench comparison, not a recommended mode.

    Results leave through ``commit(uri, payload)`` exactly once per
    submitted request: a finished sequence commits ``{"tokens",
    "finish", "timing"}``; a shed one commits ``{"error", "code",
    "tokens"}`` where ``tokens`` carries whatever partial stream the
    deadline allowed.
    """

    def __init__(self, engine, commit: Callable[[str, dict], None],
                 max_slots: int = 8, continuous: bool = True,
                 admission: Optional[AdmissionController] = None,
                 batcher: Optional[AdaptiveBatcher] = None,
                 idle_poll_s: float = 0.02):
        self.engine = engine
        self._commit_cb = commit
        self.max_slots = max(int(max_slots), 1)
        self.continuous = bool(continuous)
        self.admission = admission
        self.batcher = batcher
        self.idle_poll_s = float(idle_poll_s)

        self._queue: "queue.Queue[GenRequest]" = queue.Queue()
        self._slots: List[Optional[_Slot]] = [None] * self.max_slots
        self._state = None
        self._capacity = 0
        self._committed = set()
        self._lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._drain = True
        self._thread: Optional[threading.Thread] = None
        self.counts = {"submitted": 0, "committed": 0, "tokens": 0,
                       "joins": 0, "evictions": 0, "shed": 0,
                       "duplicate_commits": 0}

    # -- public surface -------------------------------------------------
    def submit(self, req: GenRequest):
        with self._lock:
            self.counts["submitted"] += 1
        self._queue.put(req)

    def start(self):
        if self._thread is not None:
            raise RuntimeError("scheduler already started")
        self._thread = threading.Thread(target=self.run,
                                        name="zoo-generate-scheduler",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, drain: bool = True, timeout: Optional[float] = None):
        self._drain = bool(drain)
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def stats(self) -> dict:
        with self._lock:
            out = dict(self.counts)
        out["queue_depth"] = self._queue.qsize()
        out["active_slots"] = sum(s is not None for s in self._slots)
        out["capacity"] = self._capacity
        return out

    # -- commit (exactly once) ------------------------------------------
    def _commit(self, uri: str, payload: dict):
        with self._lock:
            if uri in self._committed:
                self.counts["duplicate_commits"] += 1
                logger.error("duplicate commit suppressed for %r", uri)
                return
            self._committed.add(uri)
            self.counts["committed"] += 1
        self._commit_cb(uri, payload)

    def _shed(self, req: GenRequest, code: str, msg: str,
              tokens: Optional[List[int]] = None):
        with self._lock:
            self.counts["shed"] += 1
        telemetry.counter("zoo_generate_shed_total", code=code).inc()
        self._commit(req.uri, {"error": msg, "code": code,
                               "tokens": list(tokens or [])})

    # -- slot lifecycle --------------------------------------------------
    def _slack_ms(self, req: GenRequest) -> Optional[float]:
        if req.deadline_at_ms is None:
            return None
        return req.deadline_at_ms - now_ms()

    def _admit(self, req: GenRequest) -> bool:
        """Admission-time shed; True when the request may join."""
        if self.admission is not None:
            ok, code = self.admission.admit_generate(
                self._slack_ms(req), req.max_new_tokens,
                queue_depth=self._queue.qsize())
            if not ok:
                self._shed(req, code, "deadline unmeetable at admission")
                return False
        try:
            need = pick_cache_bucket(
                int(req.prompt.size) + req.max_new_tokens,
                self.engine.buckets)
        except ValueError:
            self._shed(req, SHED_CAPACITY,
                       "prompt + max_new_tokens exceeds the largest "
                       "cache bucket")
            return False
        if self._state is None:
            self._capacity = need
            self._state = self.engine.alloc(self.max_slots, need)
        elif need > self._capacity:
            self._state = self.engine.grow(self._state, need)
            self._capacity = need
        return True

    def _join(self, slot: int, req: GenRequest):
        with span("generate/prefill", uri=req.uri, slot=slot,
                  prompt_len=int(req.prompt.size),
                  trace_id=req.trace_id):
            if req.trace_id:
                telemetry.flow("serving/request", req.trace_id, "f")
            self._state, first = self.engine.join(self._state, slot, req)
        s = _Slot(req=req, t_join=time.perf_counter())
        self._slots[slot] = s
        with self._lock:
            self.counts["joins"] += 1
        telemetry.counter("zoo_generate_join_total").inc()
        telemetry.event("generate_join", uri=req.uri, slot=slot,
                        trace_id=req.trace_id)
        self._note_token(slot, int(first))

    def _note_token(self, slot: int, tok: int):
        """Record one emitted token; set the slot's finish reason when
        this token ends the sequence (checked in priority order: stop
        token, token budget, deadline)."""
        s = self._slots[slot]
        t_now = time.perf_counter()
        if s.t_first_token is None:
            s.t_first_token = t_now
            telemetry.summary("zoo_generate_ttft_ms").record(
                (t_now - s.req.t_in) * 1e3)
        if telemetry.enabled():
            s.t_tokens.append(t_now)
        s.tokens.append(tok)
        s.last = tok
        with self._lock:
            self.counts["tokens"] += 1
        if s.req.stop_id is not None and tok == s.req.stop_id:
            s.finish = FINISH_STOP
        elif len(s.tokens) >= s.req.max_new_tokens:
            s.finish = FINISH_MAX_TOKENS
        elif self.admission is not None and self.admission.stream_expired(
                s.req.deadline_at_ms):
            s.finish = FINISH_DEADLINE

    def _evict(self, slot: int):
        s = self._slots[slot]
        self._state = self.engine.evict(self._state, slot)
        self._slots[slot] = None
        with self._lock:
            self.counts["evictions"] += 1
        telemetry.counter("zoo_generate_evict_total",
                          reason=s.finish).inc()
        telemetry.event("generate_evict", uri=s.req.uri, slot=slot,
                        reason=s.finish, n_tokens=len(s.tokens),
                        trace_id=s.req.trace_id)
        if s.finish == FINISH_DEADLINE:
            self._shed(s.req, SHED_DEADLINE,
                       "deadline exceeded mid-generation",
                       tokens=s.tokens)
            return
        t_done = time.perf_counter()
        decode_s = max(t_done - s.t_join, 1e-9)
        tokens_per_s = len(s.tokens) / decode_s
        telemetry.summary("zoo_generate_tokens_per_s").record(tokens_per_s)
        timing = {
            "ttft_ms": round((s.t_first_token - s.req.t_in) * 1e3, 3),
            "decode_ms": round(decode_s * 1e3, 3),
            "n_tokens": len(s.tokens),
            "tokens_per_s": round(tokens_per_s, 3),
        }
        if s.req.trace_id:
            timing["trace_id"] = s.req.trace_id
        if s.t_tokens:
            # per-token boundaries relative to join — the waterfall's
            # token ruler (`zoo-serving trace <id>`); recorded only
            # while telemetry is enabled to keep the hot path flat
            timing["token_ms"] = [round((t - s.t_join) * 1e3, 3)
                                  for t in s.t_tokens]
        if s.req.enqueue_ts_ms is not None:
            # lets the client complete the rtt/transport decomposition
            timing["enqueue_ts_ms"] = s.req.enqueue_ts_ms
            timing["server_ms"] = timing["ttft_ms"] + timing["decode_ms"]
            timing["done_ts_ms"] = now_ms()
        self._commit(s.req.uri, {"tokens": list(s.tokens),
                                 "finish": s.finish, "timing": timing})

    # -- loop stages -----------------------------------------------------
    def _evict_finished(self):
        for i, s in enumerate(self._slots):
            if s is not None and s.finish is not None:
                self._evict(i)

    def _oldest_active_deadline(self) -> Optional[float]:
        ds = [s.req.deadline_at_ms for s in self._slots
              if s is not None and s.req.deadline_at_ms is not None]
        return min(ds) if ds else None

    def _refill(self):
        """Fill free slots from the queue.  Static mode refills only
        when the gang is fully drained; continuous mode refills at
        every token boundary.  At empty-gang assembly the adaptive
        batcher may linger a bounded moment to round the gang up to
        the next padding-bucket boundary."""
        active = sum(s is not None for s in self._slots)
        if not self.continuous and active > 0:
            return
        gang_was_empty = active == 0
        free = [i for i, s in enumerate(self._slots) if s is None]
        while free:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                n_have = self.max_slots - len(free)
                if not (gang_was_empty and n_have > 0
                        and self.batcher is not None):
                    break
                budget = self.batcher.linger_budget_s(
                    n_have, self._oldest_active_deadline())
                if budget <= 0:
                    break
                try:
                    req = self._queue.get(timeout=budget)
                except queue.Empty:
                    break
            if not self._admit(req):
                continue
            slot = free.pop(0)
            self._join(slot, req)

    def _step(self):
        feeds = {i: s.last for i, s in enumerate(self._slots)
                 if s is not None and s.finish is None}
        if not feeds:
            return
        temps = {i: self._slots[i].req.temperature for i in feeds}
        t0 = time.perf_counter()
        self._state, out = self.engine.step(self._state, feeds, temps)
        dt = time.perf_counter() - t0
        if self.admission is not None:
            self.admission.observe_tokens(len(feeds), dt)
        telemetry.counter("zoo_generate_tokens_total").inc(len(feeds))
        telemetry.summary("zoo_generate_step_ms").record(dt * 1e3)
        for slot, tok in out.items():
            self._note_token(slot, int(tok))
        self._publish_occupancy()

    def _publish_occupancy(self):
        active = [s for s in self._slots if s is not None]
        telemetry.gauge("zoo_generate_active_slots").set(len(active))
        if self._capacity > 0:
            used = sum(int(s.req.prompt.size) + len(s.tokens)
                       for s in active)
            telemetry.gauge("zoo_generate_cache_occupancy").set(
                used / (self.max_slots * self._capacity))

    # -- main loop -------------------------------------------------------
    def run(self):
        """Process until :meth:`stop`.  ``stop(drain=True)`` lets the
        queue and gang empty first; ``drain=False`` cancels in-flight
        sequences (committed with ``code="cancelled"``)."""
        while True:
            self._evict_finished()
            self._refill()
            active = sum(s is not None for s in self._slots)
            if self._stop_evt.is_set():
                if not self._drain:
                    break
                if active == 0 and self._queue.empty():
                    break
            if active == 0:
                # idle: block briefly for the next request
                try:
                    req = self._queue.get(timeout=self.idle_poll_s)
                except queue.Empty:
                    continue
                self._queue.put(req)   # re-enter through _refill
                continue
            self._step()
        if not self._drain:
            for i, s in enumerate(self._slots):
                if s is not None:
                    s.finish = FINISH_CANCELLED
                    self._state = self.engine.evict(self._state, i)
                    self._slots[i] = None
                    self._shed(s.req, FINISH_CANCELLED,
                               "generation cancelled at shutdown",
                               tokens=s.tokens)
            while True:
                try:
                    req = self._queue.get_nowait()
                except queue.Empty:
                    break
                self._shed(req, FINISH_CANCELLED,
                           "generation cancelled at shutdown")
