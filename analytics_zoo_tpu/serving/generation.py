"""Continuous-batching generative serving: decode engines + scheduler.

The serving layer's autoregressive workload front. Classification
serving dispatches a batch and is done; generation holds a sequence in
flight for tens-to-thousands of decode steps. Batching at *request*
granularity (static batching) means the whole gang waits for its
slowest member — short answers pay for long ones. Iteration-level
scheduling (Orca, OSDI '22) rebatches at every token boundary instead:

- the in-flight batch is a set of **cache slots** over preallocated
  power-of-two KV slabs (``ops/kv_cache.py``);
- a finished sequence (stop token / max_new_tokens / deadline) is
  **evicted at the very step it finishes** and its result committed
  immediately;
- the freed slot is **refilled from the admission queue
  mid-generation** — joiners prefill into the running gang without
  stalling it;
- admission reuses the padding-bucket + linger machinery, with the
  EWMA deadline shed extended by a per-token service estimate
  (:meth:`AdmissionController.admit_generate`), and a mid-stream shed
  (:meth:`AdmissionController.stream_expired`) that evicts a sequence
  whose deadline passes while decoding, committing a typed
  ``shed_deadline`` payload that carries the partial tokens.

Two engines implement the gang interface: ``TransformerDecodeEngine``
(the real KV-cache decode path through ``TransformerLayer``) and
``StubDecodeEngine`` (a deterministic CPU stand-in whose decode step
costs a flat ``ms_per_step`` regardless of gang width — the
MXU-amortization property that makes continuous batching pay; the
bench ``generation`` leg and the fast-tier smoke run on it).

On top of the base gang interface the engines expose a **generative
fast path**, each piece optional and independently degradable:

- **batched joins** (``join_batch``): concurrent arrivals prefill as
  one padded dispatch instead of N sequential batch-1 prefills;
- **chunked prefill** (``prefill_chunk``): a long prompt splits into
  fixed-width chunks interleaved with the running gang's decode steps,
  bounding the inter-token stall a long joiner inflicts on everyone
  else (the scheduler advances one chunk per token boundary);
- **speculative decoding** (``SpeculativeDecodeEngine``): a cheap
  draft proposes ``k`` tokens per round and the target verifies them
  in one rectangular ``step_chunk``; greedy output is token-for-token
  identical to plain decode (Leviathan et al., 2023);
- **shared-prefix cache** (``PrefixCache``): a content-hash hit
  splices previously computed KV rows into the joiner's slot —
  ``prefill_calls`` does not move;
- **int8 KV slabs** (``kv_dtype="int8"`` on the transformer engine):
  ``ops/kv_cache.Int8KVSlab`` storage at 0.375x the f32 bytes.
"""

from __future__ import annotations

import hashlib
import logging
import math
import queue
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..ops.kv_cache import cache_length_buckets, pick_cache_bucket
from ..utils import telemetry
from ..utils.telemetry import span
from .admission import (SHED_DEADLINE, AdaptiveBatcher, AdmissionController,
                        now_ms)

logger = logging.getLogger(__name__)

#: eviction reasons — the "reason" label on zoo_generate_evict_total and
#: the "finish" field of committed results
FINISH_STOP = "stop_id"
FINISH_MAX_TOKENS = "max_new_tokens"
FINISH_DEADLINE = "shed_deadline"
FINISH_CANCELLED = "cancelled"

#: typed shed code for prompts no cache bucket can hold
SHED_CAPACITY = "shed_capacity"


@dataclass
class GenRequest:
    """One generate request as it leaves the wire decoder."""

    uri: str
    prompt: np.ndarray                  # 1-D int token ids
    max_new_tokens: int = 32
    stop_id: Optional[int] = None
    temperature: float = 0.0            # 0 = greedy
    deadline_at_ms: Optional[float] = None
    enqueue_ts_ms: Optional[float] = None
    t_in: float = field(default_factory=time.perf_counter)
    trace_id: Optional[str] = None      # client-stamped trace context

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt).astype(np.int64).ravel()
        self.max_new_tokens = max(int(self.max_new_tokens), 1)


@dataclass
class _Slot:
    """Scheduler-side tracker for one in-flight sequence."""

    req: GenRequest
    tokens: List[int] = field(default_factory=list)
    last: int = 0
    t_join: float = 0.0
    t_first_token: Optional[float] = None
    t_tokens: List[float] = field(default_factory=list)
    finish: Optional[str] = None
    prefill_next: Optional[int] = None  # next chunk start; None = done


# ---------------------------------------------------------------------------
# shared-prefix cache
# ---------------------------------------------------------------------------

def prompt_key(prompt: np.ndarray) -> str:
    """Content hash of a prompt token sequence (the cache key)."""
    p = np.ascontiguousarray(np.asarray(prompt, np.int64).ravel())
    return hashlib.sha1(p.tobytes()).hexdigest()


class PrefixCache:
    """LRU map from prompt content-hash to a prefilled-KV payload.

    A hit lets a joiner splice previously computed rows straight into
    its slot (``place_slot``) instead of re-running prefill — the
    dominant cost for agent/template workloads where many requests
    share a long system prompt. Payloads are engine-specific (the
    transformer engine stores per-layer K/V rows, possibly already
    int8-quantized, plus the last-token logits row; the stub stores its
    scripted stream state); the cache only tracks recency and bytes.

    ``lookup`` is the *only* place hit/miss counters move — engines
    call it exactly once per join attempt, so the telemetry counters
    are a true hit ratio. Not thread-safe beyond the scheduler-loop
    single-writer pattern it lives in.
    """

    def __init__(self, max_bytes: int = 256 << 20):
        self.max_bytes = int(max_bytes)
        self._entries: "OrderedDict[str, Tuple[object, int]]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def nbytes(self) -> int:
        return self._bytes

    def lookup(self, prompt: np.ndarray):
        """Return the cached payload or None; counts the hit/miss."""
        key = prompt_key(prompt)
        entry = self._entries.get(key)
        if entry is None:
            self.misses += 1
            telemetry.counter("zoo_generate_prefix_cache_misses_total").inc()
            return None
        self._entries.move_to_end(key)
        self.hits += 1
        telemetry.counter("zoo_generate_prefix_cache_hits_total").inc()
        return entry[0]

    def insert(self, prompt: np.ndarray, payload, nbytes: int):
        key = prompt_key(prompt)
        if key in self._entries:
            _, old = self._entries.pop(key)
            self._bytes -= old
        nbytes = int(nbytes)
        if nbytes > self.max_bytes:
            return
        self._entries[key] = (payload, nbytes)
        self._bytes += nbytes
        while self._bytes > self.max_bytes and len(self._entries) > 1:
            _, (_, evicted) = self._entries.popitem(last=False)
            self._bytes -= evicted
        telemetry.gauge("zoo_generate_prefix_cache_bytes").set(self._bytes)

    def contains(self, prompt: np.ndarray) -> bool:
        """Membership probe that does NOT move the hit/miss counters or
        recency — routing affinity accounting must not pollute the true
        hit ratio that ``lookup`` maintains."""
        return prompt_key(prompt) in self._entries

    def key_digest(self, limit: int = 32, width: int = 12) -> List[str]:
        """Newest-first bounded digest of resident keys, truncated to
        ``width`` hex chars — small enough to ride a fleet heartbeat,
        wide enough that a router prefix-match is a real cache hit."""
        keys = list(reversed(self._entries))[: max(int(limit), 0)]
        return [k[: int(width)] for k in keys]

    def stats(self) -> dict:
        return {"hits": self.hits, "misses": self.misses,
                "entries": len(self._entries), "bytes": self._bytes}


# ---------------------------------------------------------------------------
# engines
# ---------------------------------------------------------------------------

class StubDecodeEngine:
    """Deterministic gang-decode stand-in (the generate analogue of
    ``EchoStubModel``).

    Token stream for a prompt ``p``: token i (1-based) is ``p[0] + i``,
    except that when the prompt has a second element ``p[1] > 0`` the
    stream emits ``stop_id`` at position ``p[1]`` — letting tests
    script stop-token eviction per request. ``step()`` sleeps a flat
    ``ms_per_step`` for the *whole gang* (device-like cost: one MXU
    pass per token boundary, amortized over every active slot) and
    ``join()`` sleeps ``ms_per_prefill + ms_per_prefill_token * Lp``
    once.

    Fast-path knobs mirror the device engine's cost shape:
    ``join_batch`` costs one prefill of the *longest* member (padded
    batch on the MXU); ``prefill_chunk`` costs only its own tokens;
    ``step_chunk`` costs one flat gang pass regardless of width. A
    ``draft_skew > 0`` makes every ``draft_skew``-th stream token come
    out wrong — an imperfect-draft injector for speculation tests.
    """

    def __init__(self, ms_per_step: float = 1.0,
                 ms_per_prefill: float = 0.0, stop_id: int = 0,
                 capacity_buckets: Optional[Sequence[int]] = None,
                 ms_per_prefill_token: float = 0.0,
                 draft_skew: int = 0,
                 prefix_cache: Optional[PrefixCache] = None):
        self.ms_per_step = float(ms_per_step)
        self.ms_per_prefill = float(ms_per_prefill)
        self.ms_per_prefill_token = float(ms_per_prefill_token)
        self.stop_id = int(stop_id)
        self.draft_skew = int(draft_skew)
        self.prefix_cache = prefix_cache
        self.buckets = list(capacity_buckets or cache_length_buckets(1024))
        self.prefill_calls = 0

    def alloc(self, nslots: int, capacity: int):
        # per-slot [base, emitted, stop_at]; None = free
        return [None] * nslots

    def grow(self, state, capacity: int):
        return state

    # -- stream helpers ---------------------------------------------------
    @staticmethod
    def _entry(req: GenRequest):
        p = req.prompt
        base = int(p[0]) if p.size else 0
        stop_at = int(p[1]) if p.size > 1 and int(p[1]) > 0 else None
        return [base, 1, stop_at]

    def _stream(self, entry, pos: int) -> int:
        base, _, stop_at = entry
        if stop_at == pos:
            return self.stop_id
        tok = base + pos
        if self.draft_skew > 0 and pos % self.draft_skew == 0:
            tok += 1                     # scripted draft mistake
        return tok

    def _prefill_sleep(self, n_tokens: int, base: bool = True):
        ms = (self.ms_per_prefill if base else 0.0) \
            + self.ms_per_prefill_token * n_tokens
        if ms > 0:
            time.sleep(ms / 1e3)

    # -- joins ------------------------------------------------------------
    def join(self, state, slot: int, req: GenRequest):
        self._prefill_sleep(int(req.prompt.size))
        self.prefill_calls += 1
        state[slot] = self._entry(req)
        if self.prefix_cache is not None:
            self.prefix_cache.insert(req.prompt, tuple(state[slot]),
                                     int(req.prompt.size) * 8)
        return state, self._stream(state[slot], 1)

    def join_batch(self, state, joins: Sequence[Tuple[int, GenRequest]]):
        """One fused prefill dispatch: padded-batch cost is the longest
        member's, not the sum — the batched-join win."""
        longest = max(int(r.prompt.size) for _, r in joins)
        self._prefill_sleep(longest)
        self.prefill_calls += 1
        out = {}
        for slot, req in joins:
            state[slot] = self._entry(req)
            if self.prefix_cache is not None:
                self.prefix_cache.insert(req.prompt, tuple(state[slot]),
                                         int(req.prompt.size) * 8)
            out[slot] = self._stream(state[slot], 1)
        return state, out

    def try_cached_join(self, state, slot: int, req: GenRequest):
        """Prefix-cache hit path: no sleep, no ``prefill_calls``."""
        if self.prefix_cache is None:
            return None
        payload = self.prefix_cache.lookup(req.prompt)
        if payload is None:
            return None
        state[slot] = [payload[0], 1, payload[2]]
        return state, self._stream(state[slot], 1)

    def prefill_chunk(self, state, slot: int, req: GenRequest,
                      start: int, end: int, is_last: bool):
        """Advance one prompt chunk; emits the first token only when
        the last chunk lands."""
        self._prefill_sleep(end - start, base=(start == 0))
        self.prefill_calls += 1          # one dispatch per chunk
        if not is_last:
            return state, None
        state[slot] = self._entry(req)
        if self.prefix_cache is not None:
            self.prefix_cache.insert(req.prompt, tuple(state[slot]),
                                     int(req.prompt.size) * 8)
        return state, self._stream(state[slot], 1)

    # -- decode -----------------------------------------------------------
    def step(self, state, feeds: Dict[int, int],
             temps: Dict[int, float]):
        """Advance every fed slot one token; flat gang-wide cost."""
        if self.ms_per_step > 0:
            time.sleep(self.ms_per_step / 1e3)
        out = {}
        for slot in feeds:
            entry = state[slot]
            entry[1] += 1
            out[slot] = self._stream(entry, entry[1])
        return state, out

    def step_chunk(self, state, feeds: Dict[int, List[int]],
                   temps: Dict[int, float]):
        """Rectangular gang step: C fed tokens per slot, C predictions
        back (row i predicts the token after prefix+feeds[:i+1]), one
        flat gang-wide cost. ``draft_skew`` never applies here — the
        verifier is the ground-truth stream."""
        if self.ms_per_step > 0:
            time.sleep(self.ms_per_step / 1e3)
        out = {}
        for slot, toks in feeds.items():
            entry = state[slot]
            base, emitted, stop_at = entry
            preds = []
            for i in range(len(toks)):
                pos = emitted + 1 + i
                preds.append(self.stop_id if stop_at == pos
                             else base + pos)
            entry[1] = emitted + len(toks)
            out[slot] = preds
        return state, out

    def rollback(self, state, drops: Dict[int, int]):
        """Drop the trailing ``drops[slot]`` committed rows (the
        rejected speculative suffix)."""
        for slot, n in drops.items():
            if n > 0 and state[slot] is not None:
                state[slot][1] -= int(n)
        return state

    def evict(self, state, slot: int):
        state[slot] = None
        return state

    def stats(self) -> dict:
        out = {"prefill_calls": self.prefill_calls}
        if self.prefix_cache is not None:
            out["prefix_cache"] = self.prefix_cache.stats()
        return out


class TransformerDecodeEngine:
    """Gang decode over a causal ``TransformerLayer`` via its KV-cache
    API (``prefill`` / ``decode_step`` on ops/kv_cache.py slabs).

    A join prefills the prompt on a batch-1 state of the gang's
    capacity and splices the resulting slabs into the joiner's slot —
    the running gang never recomputes. Freed slots sit at length 0:
    their rows are masked out of every step, and whatever the dead slot
    keeps emitting is discarded by the scheduler.

    ``kv_dtype="int8"`` allocates ``Int8KVSlab`` caches (0.375x f32
    bytes per slot); all fast-path verbs are slab-polymorphic. A
    ``prefix_cache`` stores per-layer slot rows + the last-token logits
    row at join time; a hit splices them back via ``place_slot`` with
    no prefill dispatch (watch ``prefill_calls`` stand still).
    """

    def __init__(self, layer, params, max_len: Optional[int] = None,
                 rng=None, kv_dtype=None,
                 prefix_cache: Optional[PrefixCache] = None):
        import jax
        import jax.numpy as jnp

        self.layer = layer
        self.params = params
        self.buckets = cache_length_buckets(
            max_len or layer.seq_len, min_bucket=min(128, layer.seq_len))
        self._jnp = jnp
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)
        self.kv_dtype = "int8" if kv_dtype in ("int8", jnp.int8) \
            else (kv_dtype or jnp.float32)
        self.prefix_cache = prefix_cache
        self.prefill_calls = 0
        self._step_fn = jax.jit(lambda p, s, t: layer.decode_step(p, s, t))
        self._chunk_fn = jax.jit(
            lambda p, s, t, nv: layer.decode_chunk(p, s, t, n_valid=nv))
        # slot -> (batch-1 state, chunk width) for in-flight chunked joins
        self._pending: Dict[int, tuple] = {}

    def alloc(self, nslots: int, capacity: int):
        return self.layer.init_decode_state(nslots, capacity,
                                            dtype=self.kv_dtype)

    def grow(self, state, capacity: int):
        from ..ops.kv_cache import grow_slab

        if capacity <= state.capacity:
            return state
        return state._replace(
            k_cache=tuple(grow_slab(k, capacity) for k in state.k_cache),
            v_cache=tuple(grow_slab(v, capacity) for v in state.v_cache))

    def _pick(self, logits, temperature: float) -> int:
        import jax

        if temperature and temperature > 0.0:
            self._rng, sub = jax.random.split(self._rng)
            return int(jax.random.categorical(
                sub, logits.astype(self._jnp.float32) / temperature))
        return int(self._jnp.argmax(logits))

    # -- join helpers -----------------------------------------------------
    def _slot_rows(self, slab, b: int, lp: int):
        """Extract one sequence's first ``lp`` K/V rows from a batch
        slab — the prefix-cache payload / splice unit."""
        from ..ops.kv_cache import Int8KVSlab

        if isinstance(slab, Int8KVSlab):
            return Int8KVSlab(slab.q[b, :lp], slab.scale[b, :lp])
        return slab[b, :lp]

    def _splice(self, state, slot: int, k_rows, v_rows, lp: int):
        from ..ops.kv_cache import place_slot

        return state._replace(
            k_cache=tuple(place_slot(k, slot, r)
                          for k, r in zip(state.k_cache, k_rows)),
            v_cache=tuple(place_slot(v, slot, r)
                          for v, r in zip(state.v_cache, v_rows)),
            lengths=state.lengths.at[slot].set(lp))

    def _cache_insert(self, req: GenRequest, st1, last_logits, b: int = 0):
        if self.prefix_cache is None:
            return
        import jax

        lp = int(req.prompt.size)
        k_rows = tuple(self._slot_rows(k, b, lp) for k in st1.k_cache)
        v_rows = tuple(self._slot_rows(v, b, lp) for v in st1.v_cache)
        payload = (k_rows, v_rows, last_logits)
        nbytes = sum(x.nbytes for x in jax.tree.leaves(payload))
        self.prefix_cache.insert(req.prompt, payload, nbytes)

    def join(self, state, slot: int, req: GenRequest):
        jnp = self._jnp
        st1 = self.layer.init_decode_state(1, state.capacity,
                                           dtype=self.kv_dtype)
        logits, st1 = self.layer.prefill(
            self.params, jnp.asarray(req.prompt, jnp.int32)[None],
            jnp.array([req.prompt.size], jnp.int32), st1)
        self.prefill_calls += 1
        lp = int(req.prompt.size)
        state = self._splice(
            state, slot,
            tuple(self._slot_rows(k, 0, lp) for k in st1.k_cache),
            tuple(self._slot_rows(v, 0, lp) for v in st1.v_cache), lp)
        self._cache_insert(req, st1, logits[0])
        return state, self._pick(logits[0], req.temperature)

    def join_batch(self, state, joins: Sequence[Tuple[int, GenRequest]]):
        """Prefill every joiner in ONE padded dispatch, then splice each
        sequence's rows into its gang slot. One compile per distinct
        join-batch width (bounded by ``max_slots``)."""
        jnp = self._jnp
        n = len(joins)
        longest = max(int(r.prompt.size) for _, r in joins)
        toks = np.zeros((n, longest), np.int32)
        lens = np.zeros((n,), np.int32)
        for j, (_, req) in enumerate(joins):
            toks[j, :req.prompt.size] = req.prompt
            lens[j] = req.prompt.size
        stn = self.layer.init_decode_state(n, state.capacity,
                                           dtype=self.kv_dtype)
        logits, stn = self.layer.prefill(
            self.params, jnp.asarray(toks), jnp.asarray(lens), stn)
        self.prefill_calls += 1
        out = {}
        for j, (slot, req) in enumerate(joins):
            lp = int(req.prompt.size)
            state = self._splice(
                state, slot,
                tuple(self._slot_rows(k, j, lp) for k in stn.k_cache),
                tuple(self._slot_rows(v, j, lp) for v in stn.v_cache), lp)
            self._cache_insert(req, stn, logits[j], b=j)
            out[slot] = self._pick(logits[j], req.temperature)
        return state, out

    def try_cached_join(self, state, slot: int, req: GenRequest):
        """Splice cached rows; None on miss. No prefill dispatch."""
        if self.prefix_cache is None:
            return None
        hit = self.prefix_cache.lookup(req.prompt)
        if hit is None:
            return None
        k_rows, v_rows, last_logits = hit
        state = self._splice(state, slot, k_rows, v_rows,
                             int(req.prompt.size))
        return state, self._pick(last_logits, req.temperature)

    def prefill_chunk(self, state, slot: int, req: GenRequest,
                      start: int, end: int, is_last: bool):
        """Advance one fixed-width prompt chunk on a batch-1 side state
        (the running gang is untouched until the final splice). The
        last (possibly ragged) chunk pads to the established width and
        masks via ``n_valid``, keeping one jit signature per width."""
        jnp = self._jnp
        if start == 0:
            st1 = self.layer.init_decode_state(1, state.capacity,
                                               dtype=self.kv_dtype)
            self._pending[slot] = (st1, end - start)
        st1, width = self._pending[slot]
        n_valid = end - start
        buf = np.zeros((1, width), np.int32)
        buf[0, :n_valid] = req.prompt[start:end]
        logits, st1 = self._chunk_fn(
            self.params, st1, jnp.asarray(buf),
            jnp.full((1,), n_valid, jnp.int32))
        self.prefill_calls += 1
        self._pending[slot] = (st1, width)
        if not is_last:
            return state, None
        del self._pending[slot]
        lp = int(req.prompt.size)
        state = self._splice(
            state, slot,
            tuple(self._slot_rows(k, 0, lp) for k in st1.k_cache),
            tuple(self._slot_rows(v, 0, lp) for v in st1.v_cache), lp)
        last_logits = logits[0, n_valid - 1]
        self._cache_insert(req, st1, last_logits)
        return state, self._pick(last_logits, req.temperature)

    # -- decode -----------------------------------------------------------
    def step(self, state, feeds: Dict[int, int],
             temps: Dict[int, float]):
        jnp = self._jnp
        tokens = np.zeros((state.batch,), np.int32)
        for slot, tok in feeds.items():
            tokens[slot] = tok
        logits, state = self._step_fn(self.params, state,
                                      jnp.asarray(tokens))
        out = {slot: self._pick(logits[slot], temps.get(slot, 0.0))
               for slot in feeds}
        return state, out

    def step_chunk(self, state, feeds: Dict[int, List[int]],
                   temps: Dict[int, float]):
        """Rectangular gang step (speculative verification): C fed
        tokens per slot through one ``decode_chunk``, C per-row
        predictions back. Row 0 honours the slot's temperature (it is
        the one guaranteed-emitted token); rows 1.. are the greedy
        verification lane."""
        jnp = self._jnp
        width = len(next(iter(feeds.values())))
        tokens = np.zeros((state.batch, width), np.int32)
        for slot, toks in feeds.items():
            tokens[slot] = toks
        logits, state = self._chunk_fn(self.params, state,
                                       jnp.asarray(tokens), None)
        out = {}
        for slot in feeds:
            rows = logits[slot]
            greedy = np.asarray(jnp.argmax(rows, axis=-1)).tolist()
            temp = temps.get(slot, 0.0)
            if temp and temp > 0.0:
                greedy[0] = self._pick(rows[0], temp)
            out[slot] = [int(t) for t in greedy]
        return state, out

    def rollback(self, state, drops: Dict[int, int]):
        """Length surgery: un-commit the trailing ``drops[slot]`` rows
        (the rejected speculative suffix). The rows stay in the slab
        above the watermark — masked out, overwritten by the next
        write."""
        jnp = self._jnp
        d = np.zeros((state.batch,), np.int32)
        for slot, n in drops.items():
            d[slot] = n
        return state._replace(lengths=state.lengths - jnp.asarray(d))

    def evict(self, state, slot: int):
        from ..ops.kv_cache import evict_slot

        self._pending.pop(slot, None)
        return state._replace(lengths=evict_slot(state.lengths, slot))

    def stats(self) -> dict:
        out = {"prefill_calls": self.prefill_calls}
        if self.prefix_cache is not None:
            out["prefix_cache"] = self.prefix_cache.stats()
        return out


class SpeculativeDecodeEngine:
    """Draft-and-verify gang decode behind the same engine interface
    (Leviathan et al., 2023).

    Each round, per fed slot: the cheap **draft** runs ``k + 1``
    width-1 steps (``k`` proposals plus one throwaway step that writes
    the ``k``-th proposal's KV row, so the draft cache never lags the
    target on full acceptance); the **target** verifies ``[fed, d_1 ..
    d_k]`` in ONE rectangular ``step_chunk``. The longest agreeing
    prefix ``a`` yields ``a + 1`` emitted tokens (``a`` accepted drafts
    plus the target's own next token — the classic bonus), and both
    engines ``rollback`` the rejected ``k - a`` suffix rows by length
    surgery. Greedy output is token-for-token identical to plain
    decode; sampled slots (temperature > 0) force ``a = 0`` and emit
    the target's row-0 sample, which is exactly a plain sampled step.

    ``step`` therefore returns per-slot **lists** of 1..k+1 tokens;
    the scheduler normalises. ``expected_tokens_per_step`` feeds the
    admission estimate.
    """

    def __init__(self, target, draft, k: int = 3):
        self.target = target
        self.draft = draft
        self.k = max(int(k), 1)
        self.buckets = list(target.buckets)
        self._accepted = 0
        self._proposed = 0
        if getattr(target, "prefill_chunk", None) is None or \
                getattr(draft, "prefill_chunk", None) is None:
            self.prefill_chunk = None    # degrade: scheduler won't chunk
        self.prefix_cache = None         # lookups need both caches; skip

    # -- lifecycle (paired states) ----------------------------------------
    def alloc(self, nslots: int, capacity: int):
        return (self.target.alloc(nslots, capacity),
                self.draft.alloc(nslots, capacity))

    def grow(self, state, capacity: int):
        return (self.target.grow(state[0], capacity),
                self.draft.grow(state[1], capacity))

    def join(self, state, slot: int, req: GenRequest):
        t_state, first = self.target.join(state[0], slot, req)
        d_state, _ = self.draft.join(state[1], slot, req)
        return (t_state, d_state), first

    def join_batch(self, state, joins: Sequence[Tuple[int, GenRequest]]):
        t_state, out = self.target.join_batch(state[0], joins)
        d_state, _ = self.draft.join_batch(state[1], joins)
        return (t_state, d_state), out

    def prefill_chunk(self, state, slot: int, req: GenRequest,
                      start: int, end: int, is_last: bool):
        t_state, first = self.target.prefill_chunk(
            state[0], slot, req, start, end, is_last)
        d_state, _ = self.draft.prefill_chunk(
            state[1], slot, req, start, end, is_last)
        return (t_state, d_state), first

    def evict(self, state, slot: int):
        return (self.target.evict(state[0], slot),
                self.draft.evict(state[1], slot))

    # -- decode -----------------------------------------------------------
    def step(self, state, feeds: Dict[int, int],
             temps: Dict[int, float]):
        t_state, d_state = state
        k = self.k
        props: Dict[int, List[int]] = {slot: [] for slot in feeds}
        cur = {slot: int(tok) for slot, tok in feeds.items()}
        for i in range(k + 1):
            d_state, d_out = self.draft.step(d_state, cur, {})
            for slot in feeds:
                tok = int(d_out[slot])
                if i < k:
                    props[slot].append(tok)
                cur[slot] = tok
        chunks = {slot: [int(feeds[slot])] + props[slot] for slot in feeds}
        t_state, preds = self.target.step_chunk(t_state, chunks, temps)
        out: Dict[int, List[int]] = {}
        drops: Dict[int, int] = {}
        for slot in feeds:
            pred = [int(t) for t in preds[slot]]
            a = 0
            if not temps.get(slot):           # sampling can't verify
                while a < k and props[slot][a] == pred[a]:
                    a += 1
            out[slot] = props[slot][:a] + [pred[a]]
            drops[slot] = k - a               # both wrote k+1, keep a+1
            self._accepted += a
            self._proposed += k
        t_state = self.target.rollback(t_state, drops)
        d_state = self.draft.rollback(d_state, drops)
        telemetry.gauge("zoo_generate_draft_acceptance_rate").set(
            self.acceptance_rate)
        return (t_state, d_state), out

    @property
    def acceptance_rate(self) -> float:
        return self._accepted / self._proposed if self._proposed else 0.0

    @property
    def expected_tokens_per_step(self) -> float:
        """EWMA-free admission signal: accepted drafts per round plus
        the always-emitted bonus token."""
        if not self._proposed:
            return 1.0
        return 1.0 + self.k * self.acceptance_rate

    def stats(self) -> dict:
        out = {"k": self.k, "draft_accepted": self._accepted,
               "draft_proposed": self._proposed,
               "acceptance_rate": round(self.acceptance_rate, 4),
               "tokens_per_step": round(self.expected_tokens_per_step, 4)}
        t_stats = getattr(self.target, "stats", None)
        if callable(t_stats):
            out["target"] = t_stats()
        return out


# ---------------------------------------------------------------------------
# scheduler
# ---------------------------------------------------------------------------

class ContinuousBatchScheduler:
    """Iteration-level scheduler over a gang-decode engine.

    Loop body (one token boundary): **evict** finished sequences and
    commit their results immediately → **refill** the freed cache
    slots from the admission queue (``admit_generate`` sheds requests
    whose deadline cannot survive the queue depth; joiners prefill
    into the running gang) → **advance chunked prefills** one chunk
    each → **step** the gang one token (``observe_tokens`` feeds the
    per-token EWMA back to admission).

    Refill takes the fast path where the engine offers one: a
    prefix-cache hit joins with no prefill at all; a prompt longer
    than ``prefill_chunk`` tokens joins *incrementally* — one chunk
    per token boundary, decode steps interleaved between chunks, so a
    long joiner can no longer stall the gang for its whole prompt;
    remaining same-boundary joiners fuse into a single batched prefill
    dispatch. Engines missing a verb degrade to the sequential path.

    An engine whose ``step`` returns per-slot token *lists* (the
    speculative engine) is handled natively — every emitted token gets
    its own ``_note_token`` so stop/budget/deadline checks stay
    per-token exact.

    ``continuous=False`` degrades to static batching — the gang only
    refills once *every* slot has drained — which is the baseline leg
    of the bench comparison, not a recommended mode.

    Results leave through ``commit(uri, payload)`` exactly once per
    submitted request: a finished sequence commits ``{"tokens",
    "finish", "timing"}``; a shed one commits ``{"error", "code",
    "tokens"}`` where ``tokens`` carries whatever partial stream the
    deadline allowed.
    """

    def __init__(self, engine, commit: Callable[[str, dict], None],
                 max_slots: int = 8, continuous: bool = True,
                 admission: Optional[AdmissionController] = None,
                 batcher: Optional[AdaptiveBatcher] = None,
                 idle_poll_s: float = 0.02, prefill_chunk: int = 0):
        self.engine = engine
        self._commit_cb = commit
        self.max_slots = max(int(max_slots), 1)
        self.continuous = bool(continuous)
        self.admission = admission
        self.batcher = batcher
        self.idle_poll_s = float(idle_poll_s)
        self.prefill_chunk = max(int(prefill_chunk), 0)

        self._queue: "queue.Queue[GenRequest]" = queue.Queue()
        self._queued_steps = 0      # decode-step budget still queued
        self._slots: List[Optional[_Slot]] = [None] * self.max_slots
        self._state = None
        self._capacity = 0
        self._committed = set()
        self._lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._drain = True
        self._thread: Optional[threading.Thread] = None
        self.counts = {"submitted": 0, "committed": 0, "tokens": 0,
                       "joins": 0, "evictions": 0, "shed": 0,
                       "duplicate_commits": 0}

    # -- public surface -------------------------------------------------
    def submit(self, req: GenRequest):
        with self._lock:
            self.counts["submitted"] += 1
            self._queued_steps += max(int(req.max_new_tokens), 1)
        self._queue.put(req)

    def _note_dequeued(self, req: GenRequest):
        with self._lock:
            self._queued_steps = max(
                self._queued_steps - max(int(req.max_new_tokens), 1), 0)

    def pending_decode_steps(self) -> int:
        """Decode-step backlog: queued requests' full token budgets plus
        the remaining budget of every active slot — the unit the fleet
        router and autoscaler reason in, so a 4-token ping and a
        512-token essay stop counting as the same \"one record\"."""
        with self._lock:
            queued = self._queued_steps
        remaining = 0
        for s in list(self._slots):
            if s is not None:
                remaining += max(
                    int(s.req.max_new_tokens) - len(s.tokens), 0)
        return int(queued + remaining)

    def _engine_prefix_cache(self):
        """The engine's prefix cache, reaching through a speculative
        wrapper to its target (the draft engine never caches)."""
        pc = getattr(self.engine, "prefix_cache", None)
        if pc is None:
            pc = getattr(getattr(self.engine, "target", None),
                         "prefix_cache", None)
        return pc

    def load_report(self, max_keys: int = 32) -> dict:
        """Free-slot / queued-step / prefix-digest snapshot for the
        fleet heartbeat (consumed by ``serving/routing.py``)."""
        active = sum(s is not None for s in self._slots)
        report = {"slots": self.max_slots,
                  "active_slots": active,
                  "free_slots": max(self.max_slots - active, 0),
                  "queue_depth": self._queue.qsize(),
                  "queued_steps": self.pending_decode_steps()}
        pc = self._engine_prefix_cache()
        if pc is not None:
            report["prefix_keys"] = pc.key_digest(limit=max_keys)
        return report

    def start(self):
        if self._thread is not None:
            raise RuntimeError("scheduler already started")
        self._thread = threading.Thread(target=self.run,
                                        name="zoo-generate-scheduler",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, drain: bool = True, timeout: Optional[float] = None):
        self._drain = bool(drain)
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def stats(self) -> dict:
        with self._lock:
            out = dict(self.counts)
        out["queue_depth"] = self._queue.qsize()
        out["active_slots"] = sum(s is not None for s in self._slots)
        out["capacity"] = self._capacity
        out["pending_steps"] = self.pending_decode_steps()
        eng_stats = getattr(self.engine, "stats", None)
        if callable(eng_stats):
            out["engine"] = eng_stats()
        return out

    # -- commit (exactly once) ------------------------------------------
    def _commit(self, uri: str, payload: dict):
        with self._lock:
            if uri in self._committed:
                self.counts["duplicate_commits"] += 1
                logger.error("duplicate commit suppressed for %r", uri)
                return
            self._committed.add(uri)
            self.counts["committed"] += 1
        self._commit_cb(uri, payload)

    def _shed(self, req: GenRequest, code: str, msg: str,
              tokens: Optional[List[int]] = None):
        with self._lock:
            self.counts["shed"] += 1
        telemetry.counter("zoo_generate_shed_total", code=code).inc()
        self._commit(req.uri, {"error": msg, "code": code,
                               "tokens": list(tokens or [])})

    # -- slot lifecycle --------------------------------------------------
    def _slack_ms(self, req: GenRequest) -> Optional[float]:
        if req.deadline_at_ms is None:
            return None
        return req.deadline_at_ms - now_ms()

    def _wants_chunked(self, req: GenRequest) -> bool:
        return (self.prefill_chunk > 0
                and getattr(self.engine, "prefill_chunk", None) is not None
                and int(req.prompt.size) > self.prefill_chunk)

    def _admit(self, req: GenRequest) -> bool:
        """Admission-time shed; True when the request may join."""
        if self.admission is not None:
            n_chunks = 1
            if self._wants_chunked(req):
                n_chunks = math.ceil(int(req.prompt.size)
                                     / self.prefill_chunk)
            tps = float(getattr(self.engine,
                                "expected_tokens_per_step", 1.0) or 1.0)
            ok, code = self.admission.admit_generate(
                self._slack_ms(req), req.max_new_tokens,
                queue_depth=self._queue.qsize(),
                prefill_chunks=n_chunks, tokens_per_step=tps)
            if not ok:
                self._shed(req, code, "deadline unmeetable at admission")
                return False
        try:
            need = pick_cache_bucket(
                int(req.prompt.size) + req.max_new_tokens,
                self.engine.buckets)
        except ValueError:
            self._shed(req, SHED_CAPACITY,
                       "prompt + max_new_tokens exceeds the largest "
                       "cache bucket")
            return False
        if self._state is None:
            self._capacity = need
            self._state = self.engine.alloc(self.max_slots, need)
        elif need > self._capacity:
            self._state = self.engine.grow(self._state, need)
            self._capacity = need
        return True

    def _seat(self, slot: int, req: GenRequest, first: int,
              cached: bool = False):
        """Common join bookkeeping once a slot has its first token."""
        if self._slots[slot] is None:
            self._slots[slot] = _Slot(req=req, t_join=time.perf_counter())
        with self._lock:
            self.counts["joins"] += 1
        telemetry.counter("zoo_generate_join_total").inc()
        telemetry.event("generate_join", uri=req.uri, slot=slot,
                        cached=cached, trace_id=req.trace_id)
        self._note_token(slot, int(first))

    def _join(self, slot: int, req: GenRequest):
        with span("generate/prefill", uri=req.uri, slot=slot,
                  prompt_len=int(req.prompt.size),
                  trace_id=req.trace_id):
            if req.trace_id:
                telemetry.flow("serving/request", req.trace_id, "f")
            self._state, first = self.engine.join(self._state, slot, req)
        self._seat(slot, req, first)

    def _join_batch(self, joins: List[tuple]):
        """Fuse same-boundary joiners into one prefill dispatch."""
        with span("generate/prefill_batch", n=len(joins)):
            for _, req in joins:
                if req.trace_id:
                    telemetry.flow("serving/request", req.trace_id, "f")
            self._state, firsts = self.engine.join_batch(self._state,
                                                         joins)
        telemetry.counter("zoo_generate_batched_join_total").inc(
            len(joins))
        for slot, req in joins:
            self._seat(slot, req, firsts[slot])

    def _try_cached_join(self, slot: int, req: GenRequest) -> bool:
        """Prefix-cache hit: splice rows, skip prefill entirely."""
        fn = getattr(self.engine, "try_cached_join", None)
        if fn is None:
            return False
        with span("generate/prefix_cache_join", uri=req.uri, slot=slot,
                  trace_id=req.trace_id):
            res = fn(self._state, slot, req)
        if res is None:
            return False
        if req.trace_id:
            telemetry.flow("serving/request", req.trace_id, "f")
        self._state, first = res
        self._seat(slot, req, first, cached=True)
        return True

    def _begin_chunked_join(self, slot: int, req: GenRequest):
        """Seat a long-prompt joiner and run its FIRST chunk; the rest
        interleave with decode steps (one chunk per token boundary)."""
        self._slots[slot] = _Slot(req=req, t_join=time.perf_counter(),
                                  prefill_next=0)
        if req.trace_id:
            telemetry.flow("serving/request", req.trace_id, "f")
        telemetry.event("generate_join_begin", uri=req.uri, slot=slot,
                        prompt_len=int(req.prompt.size),
                        trace_id=req.trace_id)
        self._prefill_one_chunk(slot)

    def _prefill_one_chunk(self, slot: int):
        s = self._slots[slot]
        start = s.prefill_next
        lp = int(s.req.prompt.size)
        end = min(start + self.prefill_chunk, lp)
        is_last = end >= lp
        t0 = time.perf_counter()
        with span("generate/prefill_chunk", uri=s.req.uri, slot=slot,
                  start=start, end=end, trace_id=s.req.trace_id):
            self._state, first = self.engine.prefill_chunk(
                self._state, slot, s.req, start, end, is_last)
        dt = time.perf_counter() - t0
        if self.admission is not None:
            self.admission.observe_prefill_chunk(dt)
        telemetry.summary("zoo_generate_prefill_chunk_ms").record(dt * 1e3)
        if is_last:
            s.prefill_next = None
            self._seat(slot, s.req, int(first))
        else:
            s.prefill_next = end

    def _prefill_step(self):
        """Advance every in-flight chunked prefill one chunk."""
        for i, s in enumerate(self._slots):
            if s is not None and s.prefill_next is not None:
                self._prefill_one_chunk(i)

    def _note_token(self, slot: int, tok: int):
        """Record one emitted token; set the slot's finish reason when
        this token ends the sequence (checked in priority order: stop
        token, token budget, deadline)."""
        s = self._slots[slot]
        t_now = time.perf_counter()
        if s.t_first_token is None:
            s.t_first_token = t_now
            telemetry.summary("zoo_generate_ttft_ms").record(
                (t_now - s.req.t_in) * 1e3)
        if telemetry.enabled():
            s.t_tokens.append(t_now)
        s.tokens.append(tok)
        s.last = tok
        with self._lock:
            self.counts["tokens"] += 1
        if s.req.stop_id is not None and tok == s.req.stop_id:
            s.finish = FINISH_STOP
        elif len(s.tokens) >= s.req.max_new_tokens:
            s.finish = FINISH_MAX_TOKENS
        elif self.admission is not None and self.admission.stream_expired(
                s.req.deadline_at_ms):
            s.finish = FINISH_DEADLINE

    def _evict(self, slot: int):
        s = self._slots[slot]
        self._state = self.engine.evict(self._state, slot)
        self._slots[slot] = None
        with self._lock:
            self.counts["evictions"] += 1
        telemetry.counter("zoo_generate_evict_total",
                          reason=s.finish).inc()
        telemetry.event("generate_evict", uri=s.req.uri, slot=slot,
                        reason=s.finish, n_tokens=len(s.tokens),
                        trace_id=s.req.trace_id)
        if s.finish == FINISH_DEADLINE:
            self._shed(s.req, SHED_DEADLINE,
                       "deadline exceeded mid-generation",
                       tokens=s.tokens)
            return
        t_done = time.perf_counter()
        decode_s = max(t_done - s.t_join, 1e-9)
        tokens_per_s = len(s.tokens) / decode_s
        telemetry.summary("zoo_generate_tokens_per_s").record(tokens_per_s)
        timing = {
            "ttft_ms": round((s.t_first_token - s.req.t_in) * 1e3, 3),
            "decode_ms": round(decode_s * 1e3, 3),
            "n_tokens": len(s.tokens),
            "tokens_per_s": round(tokens_per_s, 3),
        }
        if s.req.trace_id:
            timing["trace_id"] = s.req.trace_id
        if s.t_tokens:
            # per-token boundaries relative to join — the waterfall's
            # token ruler (`zoo-serving trace <id>`); recorded only
            # while telemetry is enabled to keep the hot path flat
            timing["token_ms"] = [round((t - s.t_join) * 1e3, 3)
                                  for t in s.t_tokens]
        if s.req.enqueue_ts_ms is not None:
            # lets the client complete the rtt/transport decomposition
            timing["enqueue_ts_ms"] = s.req.enqueue_ts_ms
            timing["server_ms"] = timing["ttft_ms"] + timing["decode_ms"]
            timing["done_ts_ms"] = now_ms()
        self._commit(s.req.uri, {"tokens": list(s.tokens),
                                 "finish": s.finish, "timing": timing})

    # -- loop stages -----------------------------------------------------
    def _evict_finished(self):
        for i, s in enumerate(self._slots):
            if s is not None and s.finish is not None:
                self._evict(i)

    def _oldest_active_deadline(self) -> Optional[float]:
        ds = [s.req.deadline_at_ms for s in self._slots
              if s is not None and s.req.deadline_at_ms is not None]
        return min(ds) if ds else None

    def _refill(self):
        """Fill free slots from the queue.  Static mode refills only
        when the gang is fully drained; continuous mode refills at
        every token boundary.  At empty-gang assembly the adaptive
        batcher may linger a bounded moment to round the gang up to
        the next padding-bucket boundary."""
        active = sum(s is not None for s in self._slots)
        if not self.continuous and active > 0:
            return
        gang_was_empty = active == 0
        free = [i for i, s in enumerate(self._slots) if s is None]
        pending: List[tuple] = []    # joiners for one fused dispatch
        while free:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                n_have = self.max_slots - len(free)
                if not (gang_was_empty and n_have > 0
                        and self.batcher is not None):
                    break
                budget = self.batcher.linger_budget_s(
                    n_have, self._oldest_active_deadline())
                if budget <= 0:
                    break
                try:
                    req = self._queue.get(timeout=budget)
                except queue.Empty:
                    break
            self._note_dequeued(req)
            if not self._admit(req):
                continue
            slot = free.pop(0)
            if self._try_cached_join(slot, req):
                continue
            if self._wants_chunked(req):
                self._begin_chunked_join(slot, req)
                continue
            pending.append((slot, req))
        if len(pending) > 1 and \
                getattr(self.engine, "join_batch", None) is not None:
            self._join_batch(pending)
        else:
            for slot, req in pending:
                self._join(slot, req)

    def _step(self):
        feeds = {i: s.last for i, s in enumerate(self._slots)
                 if s is not None and s.finish is None
                 and s.prefill_next is None}
        if not feeds:
            return
        temps = {i: self._slots[i].req.temperature for i in feeds}
        t0 = time.perf_counter()
        self._state, out = self.engine.step(self._state, feeds, temps)
        dt = time.perf_counter() - t0
        emitted = 0
        for slot, tok in out.items():
            s = self._slots[slot]
            toks = tok if isinstance(tok, (list, tuple)) else (tok,)
            for t in toks:
                # a speculative step can emit several tokens; the
                # sequence may finish mid-list, and trailing tokens
                # past the finish are discarded
                if s.finish is not None:
                    break
                self._note_token(slot, int(t))
                emitted += 1
        if self.admission is not None:
            self.admission.observe_tokens(emitted, dt)
        telemetry.counter("zoo_generate_tokens_total").inc(emitted)
        telemetry.summary("zoo_generate_step_ms").record(dt * 1e3)
        self._publish_occupancy()

    def _publish_occupancy(self):
        active = [s for s in self._slots if s is not None]
        telemetry.gauge("zoo_generate_active_slots").set(len(active))
        if self._capacity > 0:
            used = sum(int(s.req.prompt.size) + len(s.tokens)
                       for s in active)
            telemetry.gauge("zoo_generate_cache_occupancy").set(
                used / (self.max_slots * self._capacity))

    # -- main loop -------------------------------------------------------
    def run(self):
        """Process until :meth:`stop`.  ``stop(drain=True)`` lets the
        queue and gang empty first; ``drain=False`` cancels in-flight
        sequences (committed with ``code="cancelled"``)."""
        while True:
            self._evict_finished()
            self._refill()
            self._prefill_step()
            active = sum(s is not None for s in self._slots)
            if self._stop_evt.is_set():
                if not self._drain:
                    break
                if active == 0 and self._queue.empty():
                    break
            if active == 0:
                # idle: block briefly for the next request
                try:
                    req = self._queue.get(timeout=self.idle_poll_s)
                except queue.Empty:
                    continue
                self._queue.put(req)   # re-enter through _refill
                continue
            self._step()
        if not self._drain:
            for i, s in enumerate(self._slots):
                if s is not None:
                    s.finish = FINISH_CANCELLED
                    self._state = self.engine.evict(self._state, i)
                    self._slots[i] = None
                    self._shed(s.req, FINISH_CANCELLED,
                               "generation cancelled at shutdown",
                               tokens=s.tokens)
            while True:
                try:
                    req = self._queue.get_nowait()
                except queue.Empty:
                    break
                self._note_dequeued(req)
                self._shed(req, FINISH_CANCELLED,
                           "generation cancelled at shutdown")
