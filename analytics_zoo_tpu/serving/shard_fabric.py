"""ShardedStreamQueue: client-sharded broker fabric for Cluster Serving.

One :class:`~analytics_zoo_tpu.serving.socket_queue.StreamQueueBroker`
is a SPOF and a throughput ceiling (its stream lives under one lock in
one process).  This module breaks that ceiling without adding any
coordination service: N independent brokers plus **client-side
rendezvous (HRW) hashing** on the record key, so every producer and
consumer computes the same record→shard placement from nothing but the
shard list (docs/serving-network.md#sharding).

- ``data.src: shard://host:p1,host:p2,...`` behind the existing
  :func:`~analytics_zoo_tpu.serving.queue_backend.get_queue_backend`
  seam — serving loops, fleets, and clients are unchanged;
- **placement**: a record's uri is ranked against every shard with a
  stable hash; the top-ranked *healthy* shard gets the enqueue.  HRW's
  minimal-disruption property means a shard death only moves the keys
  it owned — every other key keeps its placement;
- **health**: a failed shard op marks the shard dead and starts a
  probe clock; probes (a cheap ``stream_len``) run at most every
  ``probe_interval_s`` and resurrect the shard when it answers again;
- **failover**: enqueue walks the HRW ranking past dead shards,
  reusing one dedup token across attempts so a retry that raced the
  original insert cannot double-insert on the same broker.  A bounded
  client-side pending ledger keeps (record, token) per uri until its
  result is seen, so :meth:`reenqueue_missing` can re-drive records a
  SIGKILLed broker swallowed — combined with per-uri idempotent
  results and each consumer's DeliveryLedger this preserves
  exactly-once *results* under at-least-once delivery;
- **consumption**: ``read_batch`` drains all healthy shards round-robin
  (FIFO holds *per shard*); redelivery-on-EOF and claim-timeout sweeps
  keep working unchanged per shard, because each shard is simply a
  broker.  ``put_results`` routes each result to the shard whose claim
  it releases (tracked at delivery), so the piggybacked ack still costs
  no extra round trip.

The fabric is thread-safe: the per-shard clients already keep one
connection per calling thread, and all fabric-level state (health,
claims, pending ledger) sits under one lock off the wire path.
"""

from __future__ import annotations

import hashlib
import subprocess
import sys
import time
import threading
import uuid
from collections import OrderedDict
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .queue_backend import StreamQueue
from .socket_queue import SocketStreamQueue, StreamQueueBroker

__all__ = ["ShardedStreamQueue", "LocalShardFabric", "parse_shard_spec",
           "rendezvous_rank", "spawn_broker_proc", "wait_broker_up"]

#: bounded client-side memories (uri -> claim shard / pending record)
CLAIM_WINDOW = 65536
PENDING_WINDOW = 8192

#: blocking slice per shard when polling more than one (read/wait loops)
POLL_SLICE_S = 0.05


def parse_shard_spec(spec: str) -> List[Tuple[str, int]]:
    """``shard://host:p1,host:p2,...`` -> [(host, port), ...].  An entry
    without a ``:`` is a bare port inheriting the previous entry's host
    (``shard://127.0.0.1:7001,7002``)."""
    rest = spec[len("shard://"):] if spec.startswith("shard://") else spec
    endpoints: List[Tuple[str, int]] = []
    host = None
    for entry in rest.split(","):
        entry = entry.strip()
        if not entry:
            continue
        if ":" in entry:
            host, _, port = entry.rpartition(":")
        else:
            port = entry
        if not host:
            raise ValueError(f"bad shard spec {spec!r} "
                             "(want shard://host:p1[,host:p2|,p3...])")
        endpoints.append((host, int(port)))
    if not endpoints:
        raise ValueError(f"bad shard spec {spec!r}: no endpoints")
    return endpoints


def _score(key: str, shard_id: str) -> int:
    # stable across processes and runs (python hash() is salted), cheap
    # enough for the enqueue hot path
    h = hashlib.blake2b(f"{key}|{shard_id}".encode(),
                        digest_size=8).digest()
    return int.from_bytes(h, "big")


def rendezvous_rank(key: str, shard_ids: Sequence[str]) -> List[int]:
    """Shard indices ordered by HRW score (winner first).  Removing one
    id never reorders the survivors — the minimal-movement property the
    failover path relies on."""
    return sorted(range(len(shard_ids)),
                  key=lambda i: _score(key, shard_ids[i]), reverse=True)


class _Shard:
    """One broker endpoint: its client handle + health state."""

    def __init__(self, host: str, port: int, connect_timeout: float):
        self.host, self.port = host, int(port)
        self.id = f"{host}:{port}"
        self.queue = SocketStreamQueue(host, port,
                                       connect_timeout=connect_timeout)
        self.alive = True
        self.next_probe = 0.0
        self.failures = 0

    @property
    def address(self) -> str:
        return f"socket://{self.host}:{self.port}"


class ShardedStreamQueue(StreamQueue):
    """The full StreamQueue contract over N broker shards (see module
    docstring for placement/health/failover semantics)."""

    #: wait_any() exists (polls shards with broker-side long-poll
    #: slices), so OutputQueue.wait_all uses it
    supports_long_poll = True

    def __init__(self, endpoints: Sequence[Tuple[str, int]],
                 name: str = "image_stream",
                 probe_interval_s: float = 1.0,
                 connect_timeout: float = 5.0):
        if not endpoints:
            raise ValueError("ShardedStreamQueue needs >= 1 endpoint")
        self.name = name
        self.probe_interval_s = float(probe_interval_s)
        self._shards = [_Shard(h, p, connect_timeout)
                        for h, p in endpoints]
        self._ids = [s.id for s in self._shards]
        self._lock = threading.Lock()
        self._rr = 0
        # uri -> shard index whose claim a put_results must release
        self._claim_shard: "OrderedDict[str, int]" = OrderedDict()
        # uri -> (record, token): re-drive ammunition for broker death
        self._pending: "OrderedDict[str, Tuple[dict, str]]" = OrderedDict()
        # counters (under _lock)
        self.failovers = 0
        self.reenqueued = 0
        self.probes = 0

    # -- placement ------------------------------------------------------
    def rank(self, key: str) -> List[int]:
        return rendezvous_rank(key, self._ids)

    def shard_for(self, key: str) -> int:
        """HRW winner for ``key`` ignoring health — the placement every
        peer agrees on while the fabric is whole."""
        return self.rank(key)[0]

    # -- health ---------------------------------------------------------
    def _mark_dead(self, i: int):
        s = self._shards[i]
        with self._lock:
            s.alive = False
            s.failures += 1
            s.next_probe = time.time() + self.probe_interval_s
        s.queue.close()

    def _usable(self, i: int, now: float) -> bool:
        s = self._shards[i]
        if s.alive:
            return True
        with self._lock:
            if now < s.next_probe:
                return False
            s.next_probe = now + self.probe_interval_s
            self.probes += 1
        try:
            s.queue.stream_len()
        except (ConnectionError, OSError):
            return False
        with self._lock:
            s.alive = True
        return True

    def _usable_order(self, now: float) -> List[int]:
        """Healthy shard indices, rotated so consecutive polls spread
        across the fabric instead of pinning shard 0."""
        order = [i for i in range(len(self._shards))
                 if self._usable(i, now)]
        if len(order) > 1:
            with self._lock:
                start = self._rr % len(order)
                self._rr += 1
            order = order[start:] + order[:start]
        return order

    def healthy(self) -> int:
        now = time.time()
        return sum(1 for i in range(len(self._shards))
                   if self._usable(i, now))

    # -- pending ledger -------------------------------------------------
    def _note_pending(self, uri: Optional[str], record: dict, token: str):
        if uri is None:
            return
        with self._lock:
            self._pending[uri] = (record, token)
            self._pending.move_to_end(uri)
            while len(self._pending) > PENDING_WINDOW:
                self._pending.popitem(last=False)

    def _forget_pending(self, uris: Iterable[str]):
        with self._lock:
            for uri in uris:
                self._pending.pop(uri, None)

    # -- StreamQueue contract -------------------------------------------
    def enqueue(self, record: dict) -> str:
        uri = record.get("uri") if isinstance(record, dict) else None
        key = uri if uri is not None else uuid.uuid4().hex
        token = uuid.uuid4().hex
        rid = self._enqueue_ranked(key, record, token)
        self._note_pending(uri, record, token)
        return rid

    def _enqueue_ranked(self, key: str, record: dict, token: str) -> str:
        now = time.time()
        last: Optional[Exception] = None
        for attempt, i in enumerate(self.rank(key)):
            if not self._usable(i, now):
                continue
            try:
                rid = self._shards[i].queue.enqueue(record, token=token)
            except (ConnectionError, OSError) as e:
                self._mark_dead(i)
                last = e
                continue
            if attempt:
                with self._lock:
                    self.failovers += 1
            return rid
        raise ConnectionError(
            f"no shard of {len(self._shards)} accepted enqueue: {last}")

    def reenqueue_missing(self, uris: Iterable[str]) -> int:
        """Re-drive records whose results never arrived (a dead broker
        took its stream with it).  Each re-send reuses the original
        dedup token, so a record that actually survived on a live broker
        is not double-inserted there; a record served twice across
        brokers collapses in the per-uri results map.  Returns how many
        were re-sent (uris outside the pending window are skipped)."""
        n = 0
        for uri in uris:
            with self._lock:
                entry = self._pending.get(uri)
            if entry is None:
                continue
            record, token = entry
            self._enqueue_ranked(uri, record, token)
            n += 1
        if n:
            with self._lock:
                self.reenqueued += n
        return n

    def _note_claims(self, i: int, items):
        with self._lock:
            for _rid, rec in items:
                uri = rec.get("uri") if isinstance(rec, dict) else None
                if uri is None:
                    continue
                self._claim_shard[uri] = i
                self._claim_shard.move_to_end(uri)
                while len(self._claim_shard) > CLAIM_WINDOW:
                    self._claim_shard.popitem(last=False)

    def read_batch(self, max_items: int, timeout: float = 1.0
                   ) -> List[Tuple[str, dict]]:
        """Drain healthy shards round-robin (FIFO per shard).  The first
        shard of a sweep may block a bounded slice broker-side; the rest
        are polled non-blocking, so one empty shard never starves a full
        one.  Records arrive already stamped/deduped by the per-shard
        client."""
        deadline = time.time() + float(timeout)
        out: List[Tuple[str, dict]] = []
        while True:
            now = time.time()
            order = self._usable_order(now)
            if not order:
                if now >= deadline:
                    return out
                time.sleep(min(POLL_SLICE_S, deadline - now))
                continue
            for k, i in enumerate(order):
                want = int(max_items) - len(out)
                if want <= 0:
                    break
                remaining = deadline - time.time()
                if k == 0 and not out:
                    per = max(remaining if len(order) == 1
                              else min(remaining, POLL_SLICE_S), 0.0)
                else:
                    per = 0.0
                try:
                    items = self._shards[i].queue.read_batch(
                        want, timeout=per)
                except (ConnectionError, OSError):
                    self._mark_dead(i)
                    continue
                if items:
                    self._note_claims(i, items)
                    out.extend(items)
            if out or time.time() >= deadline:
                return out

    def put_result(self, uri: str, value: bytes):
        self.put_results({uri: value})

    def put_results(self, results: Dict[str, bytes]):
        # group by the shard whose claim each commit releases (falling
        # back to the HRW winner for uris this instance never claimed),
        # so the piggybacked ack lands where the claim lives
        groups: Dict[int, Dict[str, bytes]] = {}
        with self._lock:
            claim = {u: self._claim_shard.pop(u, None) for u in results}
        for uri, value in results.items():
            i = claim.get(uri)
            if i is None:
                i = self.shard_for(uri)
            groups.setdefault(i, {})[uri] = value
        for i, chunk in groups.items():
            self._put_chunk(i, chunk)

    def _put_chunk(self, preferred: int, chunk: Dict[str, bytes]):
        first = next(iter(chunk))
        candidates = [preferred] + [j for j in self.rank(first)
                                    if j != preferred]
        now = time.time()
        last: Optional[Exception] = None
        for j in candidates:
            if not self._usable(j, now):
                continue
            try:
                self._shards[j].queue.put_results(chunk)
                return
            except (ConnectionError, OSError) as e:
                self._mark_dead(j)
                last = e
        raise ConnectionError(
            f"no shard accepted {len(chunk)} result(s): {last}")

    def get_result(self, uri: str, pop: bool = True) -> Optional[bytes]:
        # HRW winner first; failover may have landed the result (or its
        # claim) elsewhere, so walk the full ranking
        now = time.time()
        for i in self.rank(uri):
            if not self._usable(i, now):
                continue
            try:
                v = self._shards[i].queue.get_result(uri, pop=pop)
            except (ConnectionError, OSError):
                self._mark_dead(i)
                continue
            if v is not None:
                if pop:
                    self._forget_pending([uri])
                return v
        return None

    def all_results(self, pop: bool = True) -> Dict[str, bytes]:
        out: Dict[str, bytes] = {}
        now = time.time()
        for i in range(len(self._shards)):
            if not self._usable(i, now):
                continue
            try:
                out.update(self._shards[i].queue.all_results(pop=pop))
            except (ConnectionError, OSError):
                self._mark_dead(i)
        if pop and out:
            self._forget_pending(out.keys())
        return out

    def wait_any(self, uris, timeout: float = 1.0,
                 pop: bool = True) -> Dict[str, bytes]:
        """Result long-poll across shards: each healthy shard is polled
        with a bounded broker-side wait slice until any wanted uri lands
        (a uri's result lives on exactly one shard, so the first hit is
        the answer)."""
        uris = list(uris)
        deadline = time.time() + float(timeout)
        while True:
            now = time.time()
            order = self._usable_order(now)
            if not order:
                if now >= deadline:
                    return {}
                time.sleep(min(POLL_SLICE_S, deadline - now))
                continue
            for i in order:
                remaining = deadline - time.time()
                per = max(remaining if len(order) == 1
                          else min(remaining, POLL_SLICE_S), 0.0)
                try:
                    found = self._shards[i].queue.wait_any(
                        uris, timeout=per, pop=pop)
                except (ConnectionError, OSError):
                    self._mark_dead(i)
                    continue
                if found:
                    if pop:
                        self._forget_pending(found.keys())
                    return found
                if time.time() >= deadline:
                    return {}

    def stream_len(self) -> int:
        """Backlog summed across healthy shards — the satellite fix for
        the fleet autoscaler's sizing behind ``shard://`` (a dead shard
        contributes 0 until its probe resurrects it)."""
        total = 0
        now = time.time()
        for i in range(len(self._shards)):
            if not self._usable(i, now):
                continue
            try:
                total += self._shards[i].queue.stream_len()
            except (ConnectionError, OSError):
                self._mark_dead(i)
        return total

    def trim(self, keep_last: int):
        """Watermark trim, fanned out proportionally to shard depth
        (largest-remainder, so exactly ``keep_last`` survive) — each
        shard keeps its newest, matching per-shard FIFO."""
        keep_last = max(int(keep_last), 0)
        now = time.time()
        live: List[Tuple[int, int]] = []
        for i in range(len(self._shards)):
            if not self._usable(i, now):
                continue
            try:
                live.append((i, self._shards[i].queue.stream_len()))
            except (ConnectionError, OSError):
                self._mark_dead(i)
        total = sum(d for _i, d in live)
        if total <= keep_last:
            return
        quotas = []
        for i, d in live:
            exact = keep_last * d / total
            quotas.append([i, d, int(exact), exact - int(exact)])
        short = keep_last - sum(q[2] for q in quotas)
        for q in sorted(quotas, key=lambda q: q[3], reverse=True)[:short]:
            q[2] += 1
        for i, d, keep, _frac in quotas:
            keep = min(keep, d)
            if keep < d:
                try:
                    self._shards[i].queue.trim(keep)
                except (ConnectionError, OSError):
                    self._mark_dead(i)

    def close(self):
        for s in self._shards:
            s.queue.close()

    # -- observability ---------------------------------------------------
    def stats(self) -> dict:
        """Per-shard broker stats plus fabric counters — `zoo-serving
        status` renders one row per shard from this."""
        rows = []
        now = time.time()
        for i, s in enumerate(self._shards):
            row = {"address": s.address, "alive": False,
                   "failures": s.failures}
            if self._usable(i, now):
                try:
                    row.update(s.queue.stats())
                    row["alive"] = True
                except (ConnectionError, OSError):
                    self._mark_dead(i)
            rows.append(row)
        with self._lock:
            return {"shards": rows,
                    "healthy": sum(1 for r in rows if r["alive"]),
                    "failovers": self.failovers,
                    "reenqueued": self.reenqueued,
                    "probes": self.probes}

    def consumer_stats(self) -> dict:
        """Delivery-integrity counters summed over the per-shard
        ledgers (same keys as the file/socket transports)."""
        agg = {"duplicates": 0, "seq_gaps": 0, "producers_seen": 0}
        for s in self._shards:
            st = s.queue.consumer_stats()
            for k in agg:
                agg[k] += int(st.get(k, 0))
        agg["shards"] = len(self._shards)
        return agg


class LocalShardFabric:
    """N in-process brokers on one host — `zoo-serving broker --shards
    N`, tests, and bench arms.  ``base_port=0`` binds ephemeral ports."""

    def __init__(self, n: int, host: str = "127.0.0.1", base_port: int = 0,
                 claim_timeout_s: float = 60.0, op_cost_ms: float = 0.0):
        if n < 1:
            raise ValueError("need >= 1 shard")
        self.brokers = [
            StreamQueueBroker(
                host=host,
                port=0 if base_port == 0 else base_port + k,
                claim_timeout_s=claim_timeout_s, op_cost_ms=op_cost_ms)
            for k in range(int(n))]

    @property
    def spec(self) -> str:
        return "shard://" + ",".join(f"{b.host}:{b.port}"
                                     for b in self.brokers)

    @property
    def endpoints(self) -> List[Tuple[str, int]]:
        return [(b.host, b.port) for b in self.brokers]

    def start(self) -> "LocalShardFabric":
        for b in self.brokers:
            b.start()
        return self

    def queue(self, **kw) -> ShardedStreamQueue:
        return ShardedStreamQueue(self.endpoints, **kw)

    def shutdown(self):
        for b in self.brokers:
            b.shutdown()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()


def spawn_broker_proc(port: int, host: str = "127.0.0.1",
                      claim_timeout_s: float = 60.0,
                      op_cost_ms: float = 0.0) -> subprocess.Popen:
    """A broker in its OWN process (``python -m ...socket_queue``) so
    chaos legs can SIGKILL it — an in-process broker thread cannot model
    losing the stream."""
    return subprocess.Popen(
        [sys.executable, "-m", "analytics_zoo_tpu.serving.socket_queue",
         "--host", host, "--port", str(int(port)),
         "--claim-timeout-s", str(float(claim_timeout_s)),
         "--op-cost-ms", str(float(op_cost_ms))],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


def wait_broker_up(host: str, port: int, timeout: float = 15.0):
    """Block until a broker answers on (host, port); raises on timeout."""
    deadline = time.time() + timeout
    last: Optional[Exception] = None
    while time.time() < deadline:
        q = SocketStreamQueue(host, port, connect_timeout=1.0)
        try:
            q.stream_len()
            return
        except (ConnectionError, OSError) as e:
            last = e
            time.sleep(0.05)
        finally:
            q.close()
    raise ConnectionError(f"broker {host}:{port} not up in {timeout}s: "
                          f"{last}")
