"""Cluster Serving lifecycle CLI (ops tier).

Parity: ``/root/reference/scripts/cluster-serving/cluster-serving-{init,
start,stop,restart,shutdown}`` — the reference's scripts prepare a working
directory with ``config.yaml``, spark-submit the serving job, and manage a
``running`` flag file. TPU-native equivalent: one Python CLI (the shell
wrappers in ``scripts/`` exec it) that writes a config template (``init``),
runs the serve loop as a daemonized process with a pidfile (``start``),
signals it (``stop``/``restart``), and cleans the working dir
(``shutdown``). No Spark, no Redis requirement — the transport comes from
``data.src`` in the config (``file:<dir>`` for multi-process on one host,
``host:port`` for redis, in-process for tests/embedding).

Usage::

    python -m analytics_zoo_tpu.serving.cli init   [--dir DIR]
    python -m analytics_zoo_tpu.serving.cli start  [--dir DIR] [--foreground]
                                                   [--warmup]
    python -m analytics_zoo_tpu.serving.cli fleet  [--dir DIR] [--workers N]
                                                   [--transport socket://H:P]
    python -m analytics_zoo_tpu.serving.cli broker [--transport socket://H:P]
    python -m analytics_zoo_tpu.serving.cli status [--dir DIR] [--watch SEC]
    python -m analytics_zoo_tpu.serving.cli top    [--dir DIR]
                                                   [--interval SEC]
    python -m analytics_zoo_tpu.serving.cli trace  TRACE_ID [--dir DIR]
    python -m analytics_zoo_tpu.serving.cli stop   [--dir DIR]
    python -m analytics_zoo_tpu.serving.cli restart [--dir DIR]
    python -m analytics_zoo_tpu.serving.cli shutdown [--dir DIR]
    python -m analytics_zoo_tpu.serving.cli generate [--dir DIR]
                                                   --prompt "7, 3"
                                                   [--max-new-tokens N]
                                                   [--stop-id ID]
                                                   [--deadline-ms MS]

Model-registry verbs (config has a ``registry:`` section —
docs/model-registry.md).  Against a *running* server they go through the
file-RPC control plane (load + AOT warmup happen in the server, off the
serve path); with no server running they edit the persisted manifest
offline, and the next ``start`` loads the result::

    ... deploy   --path DIR [--model NAME] [--weight W] [--no-activate]
    ... promote  --model NAME --version N
    ... undeploy --model NAME [--version N]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time
from typing import Optional

from ..utils import telemetry

PIDFILE = "cluster-serving.pid"
LOGFILE = "cluster-serving.log"
CONFIG = "config.yaml"
STATSFILE = "stats.json"

CONFIG_TEMPLATE = """\
## Analytics-Zoo-TPU Cluster Serving configuration
## (schema parity: reference scripts/cluster-serving/config.yaml)

model:
  # directory of a saved zoo model (KerasNet.save_model output)
  path: /opt/work/model

data:
  # transport: "file:<dir>" | "socket://<host>:<port>" (network broker,
  # docs/serving-network.md) | "shard://<host>:<p1>,<host>:<p2>,..."
  # (HRW-sharded broker fabric, docs/serving-network.md#sharding) |
  # "<redis-host>:<port>" | empty in-process;
  # `--transport` on the CLI overrides this without editing the file
  src: file:/tmp/zoo-serving-stream
  # C, H, W of the decoded image tensor
  image_shape: 3, 224, 224

params:
  batch_size: 32
  top_n: 5
  stream_maxlen: 10000
  ## pipelined serving engine (docs/serving-pipeline.md):
  # pipelined: true          # false = single-thread baseline loop
  # decode_workers: 2        # threads decoding records alongside compute
  # queue_depth: 64          # bound on each inter-stage queue
  # bucket_sizes: 1,2,4,8,16,32   # padding buckets (default: powers of 2)
  # warmup: false            # pre-compile all buckets before serving
  ## serving fleet + deadline-aware admission (docs/serving-fleet.md):
  # workers: 2               # fleet size for `zoo-serving fleet`
  # health_interval: 1.0     # worker heartbeat period, seconds
  # health_timeout: 10.0     # stale heartbeat -> restart threshold
  # default_deadline_ms: 250 # deadline for records that carry none
  # admission_safety_ms: 2.0 # slop subtracted from every slack estimate
  # linger_ms: 0             # max wait to round batches up to a bucket
  ## backlog-driven autoscaling (docs/serving-network.md#autoscaling);
  ## active when min_workers < max_workers:
  # min_workers: 1           # floor the fleet shrinks to when idle
  # max_workers: 4           # ceiling the fleet grows to under burst
  # autoscale_target_ms: 250 # wait budget scaling defends (default:
  #                          # default_deadline_ms)
  # scale_up_fraction: 0.5   # grow when predicted wait > fraction*target
  # scale_down_idle_s: 3.0   # sustained-empty backlog before shrinking
  # autoscale_interval: 0.5  # supervisor decision period, seconds

## generative serving (docs/serving-generate.md): uncomment to serve a
## `generate` endpoint with KV-cache decode + continuous batching
# generate:
#   slots: 4                 # in-flight sequences (cache slots)
#   continuous: true         # false = static batching (bench baseline)
#   max_len: 1024            # largest prompt+generation a slab can hold
#   max_new_tokens: 32       # default token budget per request
#   stop_id: 0               # default stop token (omit for none)
#   stub_ms_per_step: 1.0    # deterministic stub engine (smoke/bench);
#                            # omit and inject a real engine via
#                            # ClusterServing.set_generate_engine
#   ## generative fast path (docs/serving-generate.md#fast-path)
#   prefill_chunk: 0          # >0: long prompts prefill in chunks of
#                             # this many tokens, interleaved with decode
#   kv_cache: f32             # int8 = Int8KVSlab storage (0.375x bytes;
#                             # applied by build_transformer_engine)
#   prefix_cache_mb: 0        # >0: shared-prefix KV cache budget (MiB)
#   speculative:              # draft-and-verify decode
#     k: 0                    # draft tokens per round (0 = off)
#     draft_ms_per_step: 0.1  # stub draft cost (device drafts are
#                             # injected via set_generate_engine)

## model registry (docs/model-registry.md): uncomment to serve many
## named, versioned models with hot-swap + canary rollout
# registry:
#   root: /tmp/zoo-serving-registry   # manifest + control-plane dir
#   default_model: default       # model routed when records carry none
#   canary_error_threshold: 0.5  # canary error rate that triggers rollback
#   canary_min_requests: 20      # observations before rollback can fire
#   drain_timeout: 10.0          # seconds to drain a retiring version

## SLO engine (docs/observability.md#slo): declarative objectives with
## multi-window error-budget burn-rate alerts, rendered by
## `zoo-serving top` and gated by the bench soak leg
# slo:
#   fast_window_s: 10            # detection window
#   slow_window_s: 60            # blip-immunity window
#   burn_threshold: 2.0          # alert when burn exceeds this in BOTH
#   objectives:
#     - name: latency
#       p99_ms: 250              # 99% of requests within 250ms
#     - name: sheds
#       shed_fraction: 0.05      # at most 5% of requests shed
#   ## multi-tenant SLO classes (docs/multi-tenancy.md): per-(model,
#   ## version) tenants with weighted-fair intake + priority sheds
#   classes:
#     - name: premium
#       model: resnet50          # omit for a catch-all class
#       weight: 3                # deficit-round-robin fair share
#       priority: 0              # lower number sheds LAST
#       objectives:
#         - name: latency
#           p99_ms: 250
#     - name: batch
#       model: embedder
#       weight: 1
#       priority: 1              # first to shed under pressure
#       shed_wait_ms: 150        # shed queued records past this wait
"""


def _paths(workdir: str):
    return (os.path.join(workdir, CONFIG), os.path.join(workdir, PIDFILE),
            os.path.join(workdir, LOGFILE))


def _read_pid(pidfile: str):
    try:
        with open(pidfile) as f:
            pid = int(f.read().strip())
    except (OSError, ValueError):
        return None
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return None
    except PermissionError:
        pass
    return pid


def cmd_init(workdir: str) -> int:
    os.makedirs(workdir, exist_ok=True)
    cfg, _, _ = _paths(workdir)
    if os.path.exists(cfg):
        print(f"{cfg} already exists; not overwriting")
        return 1
    with open(cfg, "w") as f:
        f.write(CONFIG_TEMPLATE)
    print(f"wrote {cfg}; edit model.path/data.src then "
          f"`cluster-serving-start`")
    return 0


def _build_serving(cfg: str, workdir: str):
    """ClusterServing for plain configs; RoutedClusterServing (registry
    mode: ModelRegistry recovered from its manifest, default model
    deployed from model.path, control server polling) when the config
    has a ``registry:`` section.  Either way a periodic stats snapshot
    lands in <workdir>/stats.json for `zoo-serving status`."""
    from .cluster_serving import ClusterServing, ClusterServingHelper

    helper = ClusterServingHelper(config_path=cfg)
    if not helper.stats_path:
        helper.stats_path = os.path.join(workdir, STATSFILE)
    if not helper.request_log and (helper.telemetry or telemetry.enabled()):
        # committed per-request timings — `zoo-serving trace <id>` scans
        # every requests*.jsonl under the workdir for its waterfall
        helper.request_log = os.path.join(workdir, "requests.jsonl")
    if not helper.registry_root:
        return ClusterServing(helper=helper), None
    from .registry import ModelRegistry, RegistryControlServer
    from .router import RoutedClusterServing

    registry = ModelRegistry(
        root=helper.registry_root,
        default_model=helper.default_model,
        canary_error_threshold=helper.canary_error_threshold,
        canary_min_requests=helper.canary_min_requests)
    serving = RoutedClusterServing(registry, helper=helper)
    registry.recover(load=True, warmup=serving.registry_warmup())
    if helper.model_path and not registry.routed_versions():
        serving.deploy(path=helper.model_path)
    ctl = RegistryControlServer(registry, helper.registry_root,
                                serving=serving).start()
    return serving, ctl


def _serve(cfg: str, warmup: bool = False, workdir: str = "."):
    # honor JAX_PLATFORMS even when a TPU plugin is registered (the env
    # var alone is ignored then; the config update is authoritative)
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        try:
            import jax
            jax.config.update("jax_platforms", plat)
        except Exception:  # noqa: BLE001 - serving may not need jax yet
            pass
    serving, _ctl = _build_serving(cfg, workdir)
    if serving.helper.telemetry or telemetry.enabled():
        telemetry.configure(enabled=True,
                            trace_dir=serving.helper.trace_dir,
                            service="serving")
    if warmup or serving.helper.warmup:
        # pre-compile every padding-bucket signature before the loop
        # accepts traffic; per-bucket compile time goes to the log
        t0 = time.time()
        times = serving.warmup()
        for bucket in sorted(times):
            print(f"warmup: bucket {bucket} compiled in "
                  f"{times[bucket]:.3f}s", flush=True)
        print(f"warmup: {len(times)}/{len(serving.buckets)} buckets in "
              f"{time.time() - t0:.3f}s", flush=True)

    def _term(sig, _frm):
        telemetry.event("serving/drain", signal=sig)
        telemetry.dump_flight(f"zoo-serving draining on signal {sig}")
        serving._stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    serving.serve_forever()


def cmd_start(workdir: str, foreground: bool = False,
              warmup: bool = False) -> int:
    cfg, pidfile, logfile = _paths(workdir)
    if not os.path.exists(cfg):
        print(f"no {cfg}; run `cluster-serving-init` first",
              file=sys.stderr)
        return 1
    if _read_pid(pidfile) is not None:
        print("Serving is already running!", file=sys.stderr)
        return 1
    if foreground:
        _serve(cfg, warmup=warmup, workdir=workdir)
        return 0
    # double-fork daemonization, pidfile written by the grandchild
    pid = os.fork()
    if pid > 0:
        # parent: wait for the pidfile so `start && stop` can't race
        for _ in range(100):
            if _read_pid(pidfile) is not None:
                print(f"cluster serving started (pid "
                      f"{_read_pid(pidfile)}), log: {logfile}")
                return 0
            time.sleep(0.1)
        print("serving process did not come up; check " + logfile,
              file=sys.stderr)
        return 1
    os.setsid()
    if os.fork() > 0:
        os._exit(0)
    with open(logfile, "ab", buffering=0) as log:
        os.dup2(log.fileno(), 1)
        os.dup2(log.fileno(), 2)
    with open(pidfile, "w") as f:
        f.write(str(os.getpid()))
    try:
        _serve(cfg, warmup=warmup, workdir=workdir)
    finally:
        try:
            os.remove(pidfile)
        except OSError:
            pass
    os._exit(0)


class _BrokerSet:
    """Shutdown handle over the in-process shard brokers cmd_fleet
    started (mirrors the single-broker handle's interface)."""

    def __init__(self, brokers):
        self.brokers = brokers

    def shutdown(self):
        for b in self.brokers:
            b.shutdown()


def _maybe_local_broker(src):
    """When ``data.src`` is socket:// (or shard://) and its port(s) are
    free locally, start the broker(s) in this process (single-host
    convenience); a bound port means an external broker owns that
    address — use it."""
    src = src or ""
    from .socket_queue import StreamQueueBroker, parse_socket_spec

    if src.startswith("shard://"):
        from .shard_fabric import parse_shard_spec

        endpoints = parse_shard_spec(src)
        started = []
        for host, port in endpoints:
            bind = ("0.0.0.0" if host not in ("localhost", "127.0.0.1")
                    else host)
            try:
                started.append(
                    StreamQueueBroker(host=bind, port=port).start())
            except OSError:
                continue    # shard owned by an external broker
        if not started:
            return None
        print(f"broker: serving {len(started)}/{len(endpoints)} shard(s) "
              f"of {src} in-process", flush=True)
        return _BrokerSet(started)
    if not src.startswith("socket://"):
        return None
    host, port = parse_socket_spec(src)
    bind = "0.0.0.0" if host not in ("localhost", "127.0.0.1") else host
    try:
        broker = StreamQueueBroker(host=bind, port=port).start()
    except OSError:
        return None    # address in use: external broker
    print(f"broker: serving {src} in-process", flush=True)
    return broker


def cmd_broker(src: str, shards: int = None) -> int:
    """Run a standalone stream broker in the foreground
    (docs/serving-network.md) — the front door fleet workers and
    clients on other hosts connect to.  ``--shards N`` (or a shard://
    src) launches the whole fabric locally and prints the shard:// spec
    to point ``data.src`` at (docs/serving-network.md#sharding)."""
    from .socket_queue import StreamQueueBroker, parse_socket_spec

    src = src or "socket://0.0.0.0:6380"
    if src.startswith("shard://") or (shards or 0) > 1:
        from .shard_fabric import parse_shard_spec

        if src.startswith("shard://"):
            endpoints = parse_shard_spec(src)
        else:
            host, port = parse_socket_spec(src)
            endpoints = [(host, port + k if port else 0)
                         for k in range(int(shards))]
        brokers = [StreamQueueBroker(host=h, port=p)
                   for h, p in endpoints]
        spec = "shard://" + ",".join(f"{b.host}:{b.port}"
                                     for b in brokers)
        print(f"broker: fabric of {len(brokers)} shard(s) on {spec}\n"
              f"broker: point data.src (or ZOO_SERVING_TRANSPORT) at "
              f"that spec; Ctrl-C to stop", flush=True)
        handle = _BrokerSet(brokers)
        # server.shutdown() blocks until serve_forever acks — which can
        # never happen on the thread serve_forever runs on, so the
        # handler must hand off to a helper thread.
        signal.signal(signal.SIGTERM, lambda _s, _f: threading.Thread(
            target=handle.shutdown, daemon=True).start())
        for b in brokers[1:]:
            b.start()
        try:
            brokers[0].run_forever()
        except KeyboardInterrupt:
            pass
        finally:
            handle.shutdown()
        return 0
    host, port = parse_socket_spec(src)
    broker = StreamQueueBroker(host=host, port=port)
    print(f"broker: serving on {broker.address}; Ctrl-C to stop",
          flush=True)
    signal.signal(signal.SIGTERM, lambda _s, _f: threading.Thread(
        target=broker.shutdown, daemon=True).start())
    try:
        broker.run_forever()
    except KeyboardInterrupt:
        pass
    finally:
        broker.shutdown()
    return 0


def cmd_fleet(workdir: str, workers=None) -> int:
    """Run a supervised multi-worker serving fleet in the foreground
    (docs/serving-fleet.md): N worker processes over the shared
    transport, heartbeat-watched, dead workers restarted — and, with
    min_workers < max_workers, autoscaled against the stream backlog
    (docs/serving-network.md#autoscaling)."""
    cfg, _, _ = _paths(workdir)
    if not os.path.exists(cfg):
        print(f"no {cfg}; run `cluster-serving-init` first",
              file=sys.stderr)
        return 1
    from .cluster_serving import ClusterServingHelper
    from .fleet import ServingFleet

    broker = _maybe_local_broker(ClusterServingHelper(config_path=cfg).src)
    fleet = ServingFleet(cfg, workdir, workers=workers).start()
    band = (f" (autoscale {fleet.min_workers}..{fleet.max_workers})"
            if fleet.autoscaler else "")
    print(f"fleet: supervising {fleet.workers} worker(s){band}; "
          f"Ctrl-C to stop", flush=True)
    signal.signal(signal.SIGTERM, lambda _s, _f: fleet.stop())
    try:
        fleet.supervise()
    except KeyboardInterrupt:
        fleet.shutdown()
    finally:
        if broker is not None:
            broker.shutdown()
    return 0


def _load_config(workdir: str) -> dict:
    cfg, _, _ = _paths(workdir)
    try:
        import yaml

        with open(cfg) as f:
            return yaml.safe_load(f) or {}
    except OSError:
        return {}


def _registry_root(workdir: str):
    return (_load_config(workdir).get("registry") or {}).get("root")


def _print_stage_percentiles(stats: dict):
    stages = stats.get("stages") or {}
    for name in sorted(stages):
        s = stages[name]
        print(f"  stage {name:10s} p50={s.get('p50', 0):8.2f}ms "
              f"p95={s.get('p95', 0):8.2f}ms "
              f"p99={s.get('p99', 0):8.2f}ms "
              f"(n={s.get('count', 0)})")


def _print_models(models: dict):
    for name in sorted(models):
        m = models[name]
        can = m.get("canary")
        canary = (f", canary v{can['version']} @ {can['weight']:.2f} "
                  f"({can['errors']}/{can['requests']} errors)"
                  if can else "")
        print(f"  model {name}: active=v{m.get('active')}{canary}")
        for v, vs in sorted((m.get("versions") or {}).items(),
                            key=lambda kv: int(kv[0])):
            print(f"    v{v}: {vs.get('state'):9s} "
                  f"requests={vs.get('requests', 0)} "
                  f"errors={vs.get('errors', 0)} "
                  f"inflight={vs.get('inflight', 0)}")


def _print_fleet(workdir: str) -> bool:
    """Per-worker rows from the fleet's health files (fleet mode only);
    returns True when any worker row was printed."""
    from .fleet import fleet_status

    rows = fleet_status(workdir)
    now = time.time()
    for r in rows:
        if r.get("crash_looped"):
            state = "CRASH-LOOP"
        elif not r["alive"] and r.get("backoff_until", 0) > now:
            state = f"backoff({r['backoff_until'] - now:.1f}s)"
        elif r["alive"]:
            state = "up"
        else:
            state = "DOWN"
        if r.get("stale"):
            # alive by signal-0 but the heartbeat/stats file stopped
            # refreshing: wedged, and the supervisor hasn't acted yet
            state = "STALE"
        age = (f"{r['health_age_s']:.1f}s"
               if r.get("health_age_s") is not None else "-")
        dump = (f" flight_dump={r['flight_dump']}"
                if r.get("flight_dump") else "")
        print(f"  worker {r['worker_id']}: pid={r['pid']} {state:4s} "
              f"health_age={age} "
              f"served={r['records_served']} shed={r['shed']} "
              f"restarts={r['restarts']}{dump}")
    return bool(rows)


def _print_fleet_metrics(workdir: str):
    """Merged per-worker telemetry counters/gauges (fleet totals) —
    present only when workers run with telemetry on."""
    from .fleet import fleet_metrics

    view = fleet_metrics(workdir)
    if not view["workers"]:
        return
    ages = ", ".join(f"w{w['worker_id']}={w['age_s']:.1f}s"
                     for w in view["workers"])
    print(f"  metrics snapshots: {ages}")
    for m in view["merged"]:
        lbl = ",".join(f"{k}={v}" for k, v in sorted(m["labels"].items()))
        lbl = f"{{{lbl}}}" if lbl else ""
        print(f"    {m['name']}{lbl} = {m['value']:g}")


def _effective_src(workdir: str):
    return os.environ.get("ZOO_SERVING_TRANSPORT") or \
        (_load_config(workdir).get("data") or {}).get("src")


def _print_transport(workdir: str):
    """Socket-transport row (docs/serving-network.md): one stats op
    against the broker — connections, claims outstanding, redeliveries,
    stream depth.  Non-socket transports print nothing; an unreachable
    broker prints that instead of hiding the outage.  A shard:// fabric
    prints one row per shard (health included), so a dead shard is
    visible at a glance."""
    src = _effective_src(workdir)
    if (src or "").startswith("shard://"):
        from .shard_fabric import ShardedStreamQueue, parse_shard_spec

        q = ShardedStreamQueue(parse_shard_spec(src), connect_timeout=2.0)
        try:
            st = q.stats()
        finally:
            q.close()
        print(f"  transport {src}: "
              f"healthy={st['healthy']}/{len(st['shards'])} "
              f"failovers={st['failovers']} reenqueued={st['reenqueued']}")
        for row in st["shards"]:
            if row["alive"]:
                print(f"    shard {row['address']}: health=up "
                      f"connections={row['connections']} "
                      f"stream_len={row['stream_len']} "
                      f"claims_outstanding={row['claims_outstanding']} "
                      f"redelivered={row['redelivered']} "
                      f"results_pending={row['results_pending']}")
            else:
                print(f"    shard {row['address']}: health=DOWN "
                      f"(failures={row['failures']})")
        return
    if not (src or "").startswith("socket://"):
        return
    from .socket_queue import SocketStreamQueue, parse_socket_spec

    host, port = parse_socket_spec(src)
    q = SocketStreamQueue(host, port, connect_timeout=2.0)
    try:
        st = q.stats()
    except (OSError, RuntimeError) as e:
        print(f"  transport {src}: UNREACHABLE ({e})")
        return
    finally:
        q.close()
    print(f"  transport {src}: connections={st['connections']} "
          f"consumers={st['consumers']} stream_len={st['stream_len']} "
          f"claims_outstanding={st['claims_outstanding']} "
          f"redelivered={st['redelivered']} "
          f"results_pending={st['results_pending']}")


def _print_autoscale(workdir: str):
    """Autoscale band + most recent scale events (health/autoscale.json,
    written by the supervising fleet)."""
    from .fleet import autoscale_path

    try:
        with open(autoscale_path(workdir)) as f:
            state = json.load(f)
    except (OSError, ValueError):
        return
    events = state.get("events", [])
    print(f"  autoscale: active={state.get('active')} "
          f"band={state.get('min_workers')}..{state.get('max_workers')} "
          f"events={len(events)}")
    for e in events[-3:]:
        print(f"    {time.strftime('%H:%M:%S', time.localtime(e['ts']))} "
              f"{e['action']} -> {e['active']} ({e['reason']})")


def _slo_line(label: str, o: dict):
    mark = "ALERT" if o.get("alerting") else "ok"
    print(f"  slo {label:12s} [{o.get('kind')} <= {o.get('bound'):g}] "
          f"burn fast={o.get('burn_fast', 0):.2f} "
          f"slow={o.get('burn_slow', 0):.2f} "
          f"budget={o.get('budget_remaining', 0) * 100:.1f}% "
          f"alerts={o.get('alerts_fired', 0)} {mark}")


def _print_slo(stats: dict):
    """Per-objective burn-rate/budget lines (present when the config has
    an ``slo:`` section — utils/slo.py), plus per-tenant class burn
    rates and scheduler counters when ``classes:`` are declared
    (docs/multi-tenancy.md)."""
    slo = stats.get("slo") or {}
    for name in sorted(slo):
        _slo_line(name, slo[name])
    classes = stats.get("slo_classes") or {}
    for cname in sorted(classes):
        for oname in sorted(classes[cname]):
            _slo_line(f"{cname}/{oname}", classes[cname][oname])
    tenants = stats.get("tenants") or {}
    for tname in sorted(tenants):
        t = tenants[tname]
        bound = t.get("shed_wait_ms")
        print(f"  tenant {tname}: weight={t.get('weight'):g} "
              f"priority={t.get('priority')} "
              f"queued={t.get('queued')} drained={t.get('drained')} "
              f"shed_capacity={t.get('shed_capacity')}"
              + (f" shed_wait_ms={bound:g}" if bound is not None else ""))


def _read_stats_files(workdir: str):
    """Every live pipeline_stats() snapshot under the workdir:
    ``stats.json`` (single process) plus ``stats-worker-N.json`` (fleet)
    — (source_name, stats_dict) pairs, unreadable files skipped."""
    names = [STATSFILE]
    try:
        names += sorted(n for n in os.listdir(workdir)
                        if n.startswith("stats-worker-")
                        and n.endswith(".json"))
    except FileNotFoundError:
        pass
    out = []
    for name in names:
        try:
            with open(os.path.join(workdir, name)) as f:
                out.append((name, json.load(f)))
        except (OSError, ValueError):
            continue
    return out


def _render_status(workdir: str) -> int:
    """One status frame — the shared render path of ``status``,
    ``status --watch`` and ``top``."""
    _, pidfile, _ = _paths(workdir)
    pid = _read_pid(pidfile)
    if pid is not None:
        print(f"running (pid {pid})")
    fleet_rows = _print_fleet(workdir)
    if fleet_rows:
        _print_fleet_metrics(workdir)
    _print_transport(workdir)
    _print_autoscale(workdir)
    if pid is None and not fleet_rows:
        print("not running")
        return 3
    # pipeline stats: the serving process dumps pipeline_stats() to
    # stats.json every ~2s (atomic rename, safe to read concurrently)
    stats = None
    try:
        with open(os.path.join(workdir, STATSFILE)) as f:
            stats = json.load(f)
    except (OSError, ValueError):
        pass
    if stats:
        print(f"  records_in={stats.get('records_in', 0)} "
              f"results_out={stats.get('results_out', 0)} "
              f"dropped={stats.get('dropped', 0)} "
              f"dead_letters={stats.get('dead_letters', 0)} "
              f"batches={stats.get('batches', 0)}")
        _print_stage_percentiles(stats)
        _print_slo(stats)
        _print_fleet_generation(_read_stats_files(workdir))
        _print_routing_rows(workdir)
        if stats.get("models"):
            _print_models(stats["models"])
            return 0
    elif fleet_rows:
        frames = _read_stats_files(workdir)
        for name, st in frames:
            if name == STATSFILE:
                continue
            _print_slo(st)
        _print_fleet_generation(frames)
        _print_routing_rows(workdir)
    # registry mode but no stats dump yet: fall back to the manifest
    root = _registry_root(workdir)
    if root:
        from .registry import ModelRegistry

        reg = ModelRegistry(root=root).recover(load=False)
        _print_models(reg.stats()["models"])
    return 0


def cmd_status(workdir: str, watch: float = None) -> int:
    if watch is None:
        return _render_status(workdir)
    try:
        while True:
            if sys.stdout.isatty():
                sys.stdout.write("\x1b[2J\x1b[H")
            print(f"zoo-serving status  {time.strftime('%H:%M:%S')}  "
                  f"(refresh {watch:g}s, Ctrl-C to exit)")
            _render_status(workdir)
            sys.stdout.flush()
            time.sleep(watch)
    except KeyboardInterrupt:
        pass
    return 0


def _merged_generation(frames) -> Optional[dict]:
    """Fleet-merged generate section over every stats frame carrying
    one: counter sums, weighted prefix hit ratio (Σhits over Σlookups,
    not a mean of per-worker ratios), mean draft acceptance."""
    tot = {"frames": 0, "active": 0, "cap": 0, "queue": 0,
           "pending_steps": 0, "tokens": 0, "joins": 0, "shed": 0,
           "prefills": 0, "hits": 0, "lookups": 0, "bytes": 0,
           "accept_sum": 0.0, "accept_n": 0, "tps_sum": 0.0}
    for _name, st in frames:
        gen = st.get("generation")
        if not gen:
            continue
        tot["frames"] += 1
        tot["active"] += gen.get("active_slots", 0)
        tot["cap"] += gen.get("capacity", 0)
        tot["queue"] += gen.get("queue_depth", 0)
        tot["pending_steps"] += gen.get("pending_steps", 0)
        tot["tokens"] += gen.get("tokens", 0)
        tot["joins"] += gen.get("joins", 0)
        tot["shed"] += gen.get("shed", 0)
        eng = gen.get("engine") or {}
        target = eng.get("target") or {}
        tot["prefills"] += eng.get("prefill_calls",
                                   target.get("prefill_calls", 0)) or 0
        pc = eng.get("prefix_cache") or target.get("prefix_cache")
        if pc:
            tot["hits"] += pc.get("hits", 0)
            tot["lookups"] += pc.get("hits", 0) + pc.get("misses", 0)
            tot["bytes"] += pc.get("bytes", 0)
        if "acceptance_rate" in eng:
            tot["accept_sum"] += eng["acceptance_rate"]
            tot["tps_sum"] += eng.get("tokens_per_step", 1.0)
            tot["accept_n"] += 1
    return tot if tot["frames"] else None


def _print_fleet_generation(frames, tok_per_s: Optional[float] = None):
    """The fleet-level ``generate:`` line — one merged view instead of
    the old per-worker (in practice worker-0-only) lines."""
    m = _merged_generation(frames)
    if not m:
        return
    line = (f"  generate: workers={m['frames']} "
            f"active={m['active']}/{m['cap']}cap "
            f"queue={m['queue']} pending_steps={m['pending_steps']} "
            f"tokens={m['tokens']} joins={m['joins']} shed={m['shed']}")
    if tok_per_s is not None:
        line += f" tok/s={tok_per_s:.1f}"
    if m["prefills"]:
        line += f" prefills={m['prefills']}"
    if m["lookups"]:
        line += (f" prefix_hit={m['hits'] / m['lookups']:.0%}"
                 f"({m['hits']}/{m['lookups']})"
                 f" prefix_mb={m['bytes'] / (1 << 20):.1f}")
    if m["accept_n"]:
        line += (f" draft_accept={m['accept_sum'] / m['accept_n']:.0%}"
                 f" tok/step={m['tps_sum'] / m['accept_n']:.2f}")
    print(line)


def _print_routing_rows(workdir: str):
    """Per-worker routing rows from the heartbeat load reports
    (serving/routing.py): free slots, queued decode steps, routed
    arrivals and how many landed on a warm prefix."""
    from .routing import STALE_AFTER_S, load_reports

    reports = load_reports(workdir)
    now = time.time()
    for wid in sorted(reports):
        r = reports[wid]
        stale = " STALE" if r.age_s(now) > STALE_AFTER_S else ""
        print(f"    route worker-{wid}: free={r.free_slots} "
              f"queued_steps={r.queued_steps:.0f} "
              f"routed_in={r.routed_in} "
              f"affinity_hits={r.affinity_hits} "
              f"keys={len(r.prefix_keys)}{stale}")


def cmd_top(workdir: str, interval: float = 2.0,
            iterations: int = None) -> int:
    """Live fleet view (docs/observability.md#slo): qps (delta of
    results_out between refreshes), stage percentiles, per-objective SLO
    budget, per-worker health — refreshed every ``interval`` seconds.
    ``iterations`` bounds the loop (tests / one-shot snapshots)."""
    prev = {}
    prev_tok = {}
    done = 0
    try:
        while iterations is None or done < iterations:
            frames = _read_stats_files(workdir)
            now = time.time()
            if sys.stdout.isatty():
                sys.stdout.write("\x1b[2J\x1b[H")
            print(f"zoo-serving top  {time.strftime('%H:%M:%S')}  "
                  f"(refresh {interval:g}s, Ctrl-C to exit)")
            total_qps = 0.0
            tok_per_s = None
            for name, st in frames:
                out = st.get("results_out", 0)
                qps = None
                if name in prev:
                    p_out, p_t = prev[name]
                    if now > p_t:
                        qps = max(out - p_out, 0) / (now - p_t)
                        total_qps += qps
                prev[name] = (out, now)
                gen = st.get("generation")
                if gen:
                    toks = gen.get("tokens", 0)
                    if name in prev_tok:
                        p_toks, p_t = prev_tok[name]
                        if now > p_t:
                            tok_per_s = (tok_per_s or 0.0) + \
                                max(toks - p_toks, 0) / (now - p_t)
                    prev_tok[name] = (toks, now)
                e2e = (st.get("stages") or {}).get("e2e") or {}
                qps_s = f"{qps:7.1f}" if qps is not None else "      -"
                print(f"  {name:24s} qps={qps_s} served={out} "
                      f"shed={st.get('shed', 0)} "
                      f"p50={e2e.get('p50', 0):.1f}ms "
                      f"p99={e2e.get('p99', 0):.1f}ms")
                _print_slo(st)
            if len(frames) > 1:
                print(f"  fleet qps={total_qps:.1f}")
            _print_fleet_generation(frames, tok_per_s=tok_per_s)
            _print_routing_rows(workdir)
            _print_fleet(workdir)
            sys.stdout.flush()
            done += 1
            if iterations is None or done < iterations:
                time.sleep(interval)
    except KeyboardInterrupt:
        pass
    return 0


def _request_log_rows(workdir: str):
    """Committed timing payloads from every request log under the
    workdir (``requests.jsonl`` single process, ``requests-worker-N.jsonl``
    fleet, plus their rotated ``.1`` generations) as (source, row)."""
    try:
        names = sorted(n for n in os.listdir(workdir)
                       if n.startswith("requests")
                       and (n.endswith(".jsonl") or n.endswith(".jsonl.1")))
    except FileNotFoundError:
        return
    for name in names:
        try:
            with open(os.path.join(workdir, name)) as f:
                for line in f:
                    try:
                        yield name, json.loads(line)
                    except ValueError:
                        continue
        except OSError:
            continue


def _print_waterfall(row: dict, src: str, width: int = 36):
    """One request's committed timing as an offset bar chart."""
    kind = row.get("kind", "predict")
    print(f"{row.get('trace_id', '?')}  {kind}  uri={row.get('uri')}  "
          f"[{src}]")
    if row.get("error"):
        print(f"  error: {row['error']}")
    if kind == "generate":
        ttft = row.get("ttft_ms")
        decode = row.get("decode_ms")
        stages = [("ttft", 0.0, ttft), ("decode", ttft or 0.0, decode)]
    else:
        transport = row.get("transport_in_ms")
        queue_ms = row.get("queue_ms")
        device = row.get("device_ms")
        server = row.get("server_ms")
        # the writer tail: everything of server_ms not accounted for by
        # queue wait + device time (host transfer already in device_ms)
        write = None
        if server is not None:
            write = max(server - (queue_ms or 0.0) - (device or 0.0), 0.0)
        off = 0.0
        stages = []
        for nm, v in (("transport", transport), ("queue", queue_ms),
                      ("device", device), ("write", write)):
            stages.append((nm, off, v))
            off += v or 0.0
    total = max((off + (v or 0.0)) for _, off, v in stages) or 1.0
    for nm, off, v in stages:
        if v is None:
            continue
        pad = " " * int(width * off / total)
        bar = "#" * max(int(width * v / total), 1)
        print(f"  {nm:10s} {v:9.3f}ms  {pad}{bar}")
    if row.get("server_ms") is not None:
        print(f"  {'server':10s} {row['server_ms']:9.3f}ms")
    if kind == "generate":
        n = row.get("n_tokens")
        tps = row.get("tokens_per_s")
        print(f"  tokens: {n} @ {tps:g} tok/s" if tps is not None
              else f"  tokens: {n}")
        toks = row.get("token_ms") or []
        if toks:
            shown = ", ".join(f"{t:.1f}" for t in toks[:16])
            more = f", … +{len(toks) - 16}" if len(toks) > 16 else ""
            print(f"  token boundaries (ms after join): [{shown}{more}]")


def cmd_trace(workdir: str, trace_id: str) -> int:
    """Render the per-request waterfall for one trace id from the
    committed request logs.  (The full cross-process span tree — every
    queue/decode/dispatch slice with flow arrows — comes from
    ``zoo-trace show <id> --dir <trace-dir>``.)"""
    if not trace_id:
        print("trace needs a trace id (clients print it at enqueue; "
              "`zoo-trace ls --dir <trace-dir>` lists them)",
              file=sys.stderr)
        return 1
    hits = [(src, row) for src, row in _request_log_rows(workdir)
            if row.get("trace_id") == trace_id]
    if not hits:
        print(f"trace id {trace_id!r} not found in any requests*.jsonl "
              f"under {workdir} (was the run telemetry-enabled?)",
              file=sys.stderr)
        return 1
    for src, row in hits:
        _print_waterfall(row, src)
    return 0


def _registry_op(workdir: str, op: str, **kw) -> int:
    """deploy/promote/undeploy/canary: through the control plane when
    the server runs (it loads + warms off the serve path), else offline
    against the manifest (next start picks it up)."""
    reg_cfg = _load_config(workdir).get("registry") or {}
    root = reg_cfg.get("root")
    if not root:
        print("config has no `registry:` section; registry verbs need "
              "one (see docs/model-registry.md)", file=sys.stderr)
        return 1
    _, pidfile, _ = _paths(workdir)
    from .registry import (ModelRegistry, RegistryError, control_request)

    if _read_pid(pidfile) is not None:
        try:
            resp = control_request(root, op, **kw)
        except TimeoutError as e:
            print(str(e), file=sys.stderr)
            return 1
        print(json.dumps(resp))
        return 0 if resp.get("ok") else 1
    reg = ModelRegistry(
        root=root,
        default_model=reg_cfg.get("default_model") or "default",
    ).recover(load=False)
    try:
        if op == "deploy":
            mv = reg.deploy(kw.get("model"), path=kw["path"], load=False,
                            activate=kw.get("activate", True) and
                            kw.get("canary_weight") is None,
                            quantize=bool(kw.get("quantize", False)),
                            calibration=kw.get("calibration"))
            if kw.get("canary_weight") is not None:
                reg.set_canary(mv.name, mv.version,
                               float(kw["canary_weight"]))
            print(f"registered {mv.key} [{mv.dtype}] (offline; loads on "
                  f"next start)")
        elif op == "promote":
            mv = reg.promote(kw["model"], int(kw["version"]), load=False)
            print(f"promoted {mv.key} (offline; loads on next start)")
        else:
            removed = reg.undeploy(
                kw["model"],
                int(kw["version"]) if kw.get("version") is not None
                else None)
            print(f"undeployed {kw['model']} versions {removed}")
    except (RegistryError, ValueError) as e:
        print(str(e), file=sys.stderr)
        return 1
    return 0


def cmd_generate(workdir: str, prompt: str, max_new_tokens=None,
                 stop_id=None, temperature=None, deadline_ms=None,
                 timeout: float = 30.0) -> int:
    """Submit one generate request against the running server's
    transport and print the token stream as JSON (the client-side smoke
    for docs/serving-generate.md)."""
    cfg = _load_config(workdir)
    src = (cfg.get("data") or {}).get("src")
    if not src:
        print("config has no data.src; `generate` needs a shared "
              "transport (file:<dir> or redis)", file=sys.stderr)
        return 1
    from .client import InputQueue, OutputQueue, ServingError

    try:
        tokens = [int(t) for t in prompt.replace(",", " ").split()]
    except ValueError:
        print(f"--prompt must be int token ids, got {prompt!r}",
              file=sys.stderr)
        return 1
    iq = InputQueue(address=src)
    oq = OutputQueue(backend=iq.db)
    uri = f"gen-{os.getpid()}-{time.time_ns()}"
    iq.enqueue_generate(uri, tokens, max_new_tokens=max_new_tokens,
                        stop_id=stop_id, temperature=temperature,
                        deadline_ms=deadline_ms)
    if iq.last_trace_id:
        print(f"trace_id: {iq.last_trace_id}", file=sys.stderr)
    got = oq.wait_all([uri], timeout=timeout)
    res = got.get(uri)
    if res is None:
        print(f"no result for {uri} within {timeout:.0f}s (is the "
              f"server running with a generate engine?)", file=sys.stderr)
        return 1
    if isinstance(res, ServingError):
        out = {"uri": uri, "error": res.message,
               "code": getattr(res, "code", None)}
        partial = getattr(res, "tokens", None)
        if partial is not None:
            out["tokens"] = [int(t) for t in partial]
        print(json.dumps(out), file=sys.stderr)
        return 1
    print(json.dumps({"uri": uri, "tokens": [int(t) for t in res],
                      "finish": res.finish, "timing": res.timing}))
    return 0


def cmd_stop(workdir: str, timeout: float = 10.0) -> int:
    _, pidfile, _ = _paths(workdir)
    pid = _read_pid(pidfile)
    if pid is None:
        print("not running")
        return 0
    # the daemon may exit between any probe and signal: an already-dead
    # target is a successful stop, not a crash
    try:
        os.kill(pid, signal.SIGTERM)
    except ProcessLookupError:
        pass
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            break
        time.sleep(0.1)
    else:
        try:
            os.kill(pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
    try:
        os.remove(pidfile)
    except OSError:
        pass
    print("stopped")
    return 0


def cmd_restart(workdir: str) -> int:
    cmd_stop(workdir)
    return cmd_start(workdir)


def cmd_shutdown(workdir: str) -> int:
    rc = cmd_stop(workdir)
    _, _, logfile = _paths(workdir)
    for path in (logfile, os.path.join(workdir, STATSFILE)):
        try:
            os.remove(path)
        except OSError:
            pass
    print("shut down")
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="zoo-serving")
    ap.add_argument("command", choices=["init", "start", "fleet", "broker",
                                        "status", "stop", "restart",
                                        "shutdown", "deploy", "promote",
                                        "undeploy", "generate", "trace",
                                        "top"])
    ap.add_argument("trace_id", nargs="?", default=None,
                    help="trace: the request's trace id (clients print "
                         "it at enqueue)")
    ap.add_argument("--dir", default=".", help="serving working directory")
    ap.add_argument("--watch", default=None, type=float, metavar="SEC",
                    help="status: refresh every SEC seconds until Ctrl-C")
    ap.add_argument("--interval", default=2.0, type=float,
                    help="top: refresh period in seconds")
    ap.add_argument("--iterations", default=None, type=int,
                    help="top: stop after N refreshes (default: forever)")
    ap.add_argument("--workers", default=None, type=int,
                    help="fleet: worker process count (default: config "
                         "params.workers)")
    ap.add_argument("--transport", default=None, metavar="SRC",
                    help="override data.src for this invocation — e.g. "
                         "socket://host:port (the network broker, "
                         "docs/serving-network.md), shard://h:p1,h:p2 "
                         "(broker fabric), file:<dir>, or "
                         "host:port for redis; fleet workers inherit it")
    ap.add_argument("--shards", default=None, type=int,
                    help="broker: launch a local fabric of N shard "
                         "brokers and print its shard:// spec "
                         "(docs/serving-network.md#sharding)")
    ap.add_argument("--foreground", action="store_true",
                    help="start: run in the foreground (containers)")
    ap.add_argument("--warmup", action="store_true",
                    help="start: pre-compile all padding buckets before "
                         "accepting traffic (logs compile time per bucket)")
    ap.add_argument("--trace-dir", default=None,
                    help="enable telemetry and write Chrome-trace + "
                         "metrics.json files under this directory "
                         "(fleet workers inherit via the environment)")
    ap.add_argument("--model", default=None,
                    help="registry verbs: model name (deploy defaults to "
                         "the registry's default model)")
    ap.add_argument("--path", default=None,
                    help="deploy: saved model directory to load")
    ap.add_argument("--version", default=None, type=int,
                    help="promote/undeploy: version number")
    ap.add_argument("--weight", default=None, type=float,
                    help="deploy: canary weight in [0,1] — deploy as a "
                         "canary at this traffic fraction instead of "
                         "activating")
    ap.add_argument("--no-activate", action="store_true",
                    help="deploy: register + warm but do not route "
                         "traffic (promote later)")
    ap.add_argument("--quantize", action="store_true",
                    help="deploy: load the version as int8 (fused "
                         "requantization chains when calibration scales "
                         "are available) — typically combined with "
                         "--weight for a side-by-side int8 canary")
    ap.add_argument("--calibration", default=None,
                    help="deploy --quantize: exported calibration-scales "
                         "JSON (defaults to calibration.json inside the "
                         "model directory when present)")
    ap.add_argument("--prompt", default=None,
                    help="generate: prompt token ids (comma/space "
                         "separated ints)")
    ap.add_argument("--max-new-tokens", default=None, type=int,
                    help="generate: token budget (default: server config)")
    ap.add_argument("--stop-id", default=None, type=int,
                    help="generate: stop token id")
    ap.add_argument("--temperature", default=None, type=float,
                    help="generate: sampling temperature (0 = greedy)")
    ap.add_argument("--deadline-ms", default=None, type=float,
                    help="generate: end-to-end deadline; unmeetable "
                         "requests are shed with a typed rejection")
    ap.add_argument("--timeout", default=30.0, type=float,
                    help="generate: seconds to wait for the result")
    args = ap.parse_args(argv)
    workdir = os.path.abspath(args.dir)
    if args.transport:
        # ClusterServingHelper reads this ahead of data.src; exporting
        # it (rather than rewriting the yaml) lets daemonized starts
        # and fleet worker subprocesses inherit the override
        os.environ["ZOO_SERVING_TRANSPORT"] = args.transport
    if args.trace_dir:
        # exports ZOO_TPU_TELEMETRY / ZOO_TPU_TRACE_DIR so daemonized
        # starts and fleet worker subprocesses inherit the settings
        telemetry.configure(enabled=True, trace_dir=args.trace_dir,
                            service="serving")
    if args.command == "init":
        return cmd_init(workdir)
    if args.command == "start":
        return cmd_start(workdir, foreground=args.foreground,
                         warmup=args.warmup)
    if args.command == "fleet":
        return cmd_fleet(workdir, workers=args.workers)
    if args.command == "broker":
        return cmd_broker(args.transport or _effective_src(workdir),
                          shards=args.shards)
    if args.command == "status":
        return cmd_status(workdir, watch=args.watch)
    if args.command == "trace":
        return cmd_trace(workdir, args.trace_id)
    if args.command == "top":
        return cmd_top(workdir, interval=args.interval,
                       iterations=args.iterations)
    if args.command == "stop":
        return cmd_stop(workdir)
    if args.command == "restart":
        return cmd_restart(workdir)
    if args.command == "deploy":
        if not args.path:
            print("deploy needs --path <saved-model-dir>", file=sys.stderr)
            return 1
        return _registry_op(workdir, "deploy", model=args.model,
                            path=args.path, canary_weight=args.weight,
                            activate=not args.no_activate,
                            quantize=args.quantize,
                            calibration=args.calibration)
    if args.command == "promote":
        if not args.model or args.version is None:
            print("promote needs --model and --version", file=sys.stderr)
            return 1
        return _registry_op(workdir, "promote", model=args.model,
                            version=args.version)
    if args.command == "undeploy":
        if not args.model:
            print("undeploy needs --model", file=sys.stderr)
            return 1
        return _registry_op(workdir, "undeploy", model=args.model,
                            version=args.version)
    if args.command == "generate":
        if not args.prompt:
            print("generate needs --prompt <token ids>", file=sys.stderr)
            return 1
        return cmd_generate(workdir, args.prompt,
                            max_new_tokens=args.max_new_tokens,
                            stop_id=args.stop_id,
                            temperature=args.temperature,
                            deadline_ms=args.deadline_ms,
                            timeout=args.timeout)
    return cmd_shutdown(workdir)


if __name__ == "__main__":
    sys.exit(main())
