"""Cluster Serving lifecycle CLI (ops tier).

Parity: ``/root/reference/scripts/cluster-serving/cluster-serving-{init,
start,stop,restart,shutdown}`` — the reference's scripts prepare a working
directory with ``config.yaml``, spark-submit the serving job, and manage a
``running`` flag file. TPU-native equivalent: one Python CLI (the shell
wrappers in ``scripts/`` exec it) that writes a config template (``init``),
runs the serve loop as a daemonized process with a pidfile (``start``),
signals it (``stop``/``restart``), and cleans the working dir
(``shutdown``). No Spark, no Redis requirement — the transport comes from
``data.src`` in the config (``file:<dir>`` for multi-process on one host,
``host:port`` for redis, in-process for tests/embedding).

Usage::

    python -m analytics_zoo_tpu.serving.cli init   [--dir DIR]
    python -m analytics_zoo_tpu.serving.cli start  [--dir DIR] [--foreground]
                                                   [--warmup]
    python -m analytics_zoo_tpu.serving.cli status [--dir DIR]
    python -m analytics_zoo_tpu.serving.cli stop   [--dir DIR]
    python -m analytics_zoo_tpu.serving.cli restart [--dir DIR]
    python -m analytics_zoo_tpu.serving.cli shutdown [--dir DIR]
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import time

PIDFILE = "cluster-serving.pid"
LOGFILE = "cluster-serving.log"
CONFIG = "config.yaml"

CONFIG_TEMPLATE = """\
## Analytics-Zoo-TPU Cluster Serving configuration
## (schema parity: reference scripts/cluster-serving/config.yaml)

model:
  # directory of a saved zoo model (KerasNet.save_model output)
  path: /opt/work/model

data:
  # transport: "file:<dir>" | "<redis-host>:<port>" | empty for in-process
  src: file:/tmp/zoo-serving-stream
  # C, H, W of the decoded image tensor
  image_shape: 3, 224, 224

params:
  batch_size: 32
  top_n: 5
  stream_maxlen: 10000
  ## pipelined serving engine (docs/serving-pipeline.md):
  # pipelined: true          # false = single-thread baseline loop
  # decode_workers: 2        # threads decoding records alongside compute
  # queue_depth: 64          # bound on each inter-stage queue
  # bucket_sizes: 1,2,4,8,16,32   # padding buckets (default: powers of 2)
  # warmup: false            # pre-compile all buckets before serving
"""


def _paths(workdir: str):
    return (os.path.join(workdir, CONFIG), os.path.join(workdir, PIDFILE),
            os.path.join(workdir, LOGFILE))


def _read_pid(pidfile: str):
    try:
        with open(pidfile) as f:
            pid = int(f.read().strip())
    except (OSError, ValueError):
        return None
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return None
    except PermissionError:
        pass
    return pid


def cmd_init(workdir: str) -> int:
    os.makedirs(workdir, exist_ok=True)
    cfg, _, _ = _paths(workdir)
    if os.path.exists(cfg):
        print(f"{cfg} already exists; not overwriting")
        return 1
    with open(cfg, "w") as f:
        f.write(CONFIG_TEMPLATE)
    print(f"wrote {cfg}; edit model.path/data.src then "
          f"`cluster-serving-start`")
    return 0


def _serve(cfg: str, warmup: bool = False):
    # honor JAX_PLATFORMS even when a TPU plugin is registered (the env
    # var alone is ignored then; the config update is authoritative)
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        try:
            import jax
            jax.config.update("jax_platforms", plat)
        except Exception:  # noqa: BLE001 - serving may not need jax yet
            pass
    from .cluster_serving import ClusterServing

    serving = ClusterServing(config_path=cfg)
    if warmup or serving.helper.warmup:
        # pre-compile every padding-bucket signature before the loop
        # accepts traffic; per-bucket compile time goes to the log
        t0 = time.time()
        times = serving.warmup()
        for bucket in sorted(times):
            print(f"warmup: bucket {bucket} compiled in "
                  f"{times[bucket]:.3f}s", flush=True)
        print(f"warmup: {len(times)}/{len(serving.buckets)} buckets in "
              f"{time.time() - t0:.3f}s", flush=True)

    def _term(_sig, _frm):
        serving._stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    serving.serve_forever()


def cmd_start(workdir: str, foreground: bool = False,
              warmup: bool = False) -> int:
    cfg, pidfile, logfile = _paths(workdir)
    if not os.path.exists(cfg):
        print(f"no {cfg}; run `cluster-serving-init` first",
              file=sys.stderr)
        return 1
    if _read_pid(pidfile) is not None:
        print("Serving is already running!", file=sys.stderr)
        return 1
    if foreground:
        _serve(cfg, warmup=warmup)
        return 0
    # double-fork daemonization, pidfile written by the grandchild
    pid = os.fork()
    if pid > 0:
        # parent: wait for the pidfile so `start && stop` can't race
        for _ in range(100):
            if _read_pid(pidfile) is not None:
                print(f"cluster serving started (pid "
                      f"{_read_pid(pidfile)}), log: {logfile}")
                return 0
            time.sleep(0.1)
        print("serving process did not come up; check " + logfile,
              file=sys.stderr)
        return 1
    os.setsid()
    if os.fork() > 0:
        os._exit(0)
    with open(logfile, "ab", buffering=0) as log:
        os.dup2(log.fileno(), 1)
        os.dup2(log.fileno(), 2)
    with open(pidfile, "w") as f:
        f.write(str(os.getpid()))
    try:
        _serve(cfg, warmup=warmup)
    finally:
        try:
            os.remove(pidfile)
        except OSError:
            pass
    os._exit(0)


def cmd_status(workdir: str) -> int:
    _, pidfile, _ = _paths(workdir)
    pid = _read_pid(pidfile)
    if pid is None:
        print("not running")
        return 3
    print(f"running (pid {pid})")
    return 0


def cmd_stop(workdir: str, timeout: float = 10.0) -> int:
    _, pidfile, _ = _paths(workdir)
    pid = _read_pid(pidfile)
    if pid is None:
        print("not running")
        return 0
    # the daemon may exit between any probe and signal: an already-dead
    # target is a successful stop, not a crash
    try:
        os.kill(pid, signal.SIGTERM)
    except ProcessLookupError:
        pass
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            break
        time.sleep(0.1)
    else:
        try:
            os.kill(pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
    try:
        os.remove(pidfile)
    except OSError:
        pass
    print("stopped")
    return 0


def cmd_restart(workdir: str) -> int:
    cmd_stop(workdir)
    return cmd_start(workdir)


def cmd_shutdown(workdir: str) -> int:
    rc = cmd_stop(workdir)
    _, _, logfile = _paths(workdir)
    for path in (logfile,):
        try:
            os.remove(path)
        except OSError:
            pass
    print("shut down")
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="cluster-serving")
    ap.add_argument("command", choices=["init", "start", "status", "stop",
                                        "restart", "shutdown"])
    ap.add_argument("--dir", default=".", help="serving working directory")
    ap.add_argument("--foreground", action="store_true",
                    help="start: run in the foreground (containers)")
    ap.add_argument("--warmup", action="store_true",
                    help="start: pre-compile all padding buckets before "
                         "accepting traffic (logs compile time per bucket)")
    args = ap.parse_args(argv)
    workdir = os.path.abspath(args.dir)
    if args.command == "init":
        return cmd_init(workdir)
    if args.command == "start":
        return cmd_start(workdir, foreground=args.foreground,
                         warmup=args.warmup)
    if args.command == "status":
        return cmd_status(workdir)
    if args.command == "stop":
        return cmd_stop(workdir)
    if args.command == "restart":
        return cmd_restart(workdir)
    return cmd_shutdown(workdir)


if __name__ == "__main__":
    sys.exit(main())
