"""Length- and cache-aware placement for generative fleet traffic.

Fleet placement used to be blind: every worker claims any record off
the shared stream, so one worker's long generations head-of-line-block
another's short ones, and a warm :class:`PrefixCache` entry is wasted
whenever the repeat prompt lands on a cold worker.  This module closes
ROADMAP item 3d (docs/serving-generate.md#fleet-routing):

- **load reports** piggyback on the existing fleet heartbeats
  (``health/worker-N.json``): free cache slots, queued decode steps,
  the admission EWMA token/prefill-chunk costs, and a bounded digest
  of resident prefix-cache keys — no new RPC, no coordinator;
- :class:`GenerateRouter` scores candidate workers by **estimated
  completion cost** — prefill chunks x chunk_ms + expected decode
  steps x token_ms + predicted queue wait — with a strong affinity
  bonus for workers already holding the request's prefix hash warm
  (a warm worker also skips the prefill term entirely).  With no EWMA
  observations yet it falls back to least-loaded; with no fresh report
  at all it returns None and the caller degrades to today's any-claim
  behavior;
- **per-worker substreams**: a routed record lands in the target
  worker's own FIFO stream (``<root>/gen-wN/`` next to the shared
  stream).  Claims stay atomic renames, so exactly-once holds per
  substream exactly as it does fleet-wide, and placement ties break on
  the shard fabric's rendezvous ranking so equal-cost prompts spread
  deterministically;
- **redelivery**: :meth:`RoutedGenerateQueue.sweep_worker` atomically
  moves a dead worker's unclaimed substream records back onto the
  shared any-claim stream (a rename exists in exactly one stream at a
  time — nothing is lost, nothing is duplicated), and
  :meth:`RoutedGenerateQueue.reenqueue_missing` re-drives records a
  SIGKILLed worker claimed-but-never-committed from a bounded pending
  ledger, rewriting the ORIGINAL rid so a consumer that did serve it
  drops the duplicate through its delivery ledger — the shard fabric's
  dedup-token move over files.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import msgpack
import numpy as np

from ..utils import telemetry
from .generation import prompt_key
from .queue_backend import FileStreamQueue, StreamQueue, get_queue_backend
from .shard_fabric import rendezvous_rank

__all__ = ["WorkerReport", "RouteDecision", "GenerateRouter",
           "RoutedGenerateQueue", "WorkerIntakeQueue", "gen_substream",
           "load_reports", "substream_backlog", "sweep_substream",
           "file_root"]

#: reports older than this are not trusted for placement
STALE_AFTER_S = 5.0
#: bounded producer-side (uri -> record) re-drive ammunition
PENDING_WINDOW = 8192
#: prefix-key digest: how many resident keys ride a heartbeat, and how
#: many hex chars of each (sha1 truncation; 12 nibbles ~ no collisions
#: at any plausible cache size)
PREFIX_DIGEST_KEYS = 32
PREFIX_KEY_WIDTH = 12


def gen_substream(worker_id: int) -> str:
    """Stream name of worker N's private generate substream."""
    return f"gen-w{int(worker_id)}"


def file_root(src: Optional[str]) -> Optional[str]:
    """Directory root of a ``file:`` transport spec; None for any other
    transport (no substream support — routing degrades to any-claim)."""
    if src and src.startswith("file:"):
        return src[len("file:"):]
    return None


# ---------------------------------------------------------------------------
# load reports
# ---------------------------------------------------------------------------

@dataclass
class WorkerReport:
    """One worker's heartbeat-borne routing snapshot."""

    worker_id: int
    ts: float
    free_slots: int = 0
    active_slots: int = 0
    queue_depth: int = 0
    queued_steps: float = 0.0
    token_ms: float = 0.0
    chunk_ms: float = 0.0
    prefix_keys: Tuple[str, ...] = ()
    routed_in: int = 0
    affinity_hits: int = 0

    def age_s(self, now: Optional[float] = None) -> float:
        return max((time.time() if now is None else now) - self.ts, 0.0)

    def holds_prefix(self, key: str) -> bool:
        """True when this worker's cache digest covers ``key`` (digest
        entries are truncated hashes, so match on the prefix)."""
        return any(key.startswith(k) for k in self.prefix_keys if k)

    @classmethod
    def from_health(cls, worker_id: int, payload: dict) -> "WorkerReport":
        routing = payload.get("routing") or {}
        adm = payload.get("admission") or {}
        return cls(
            worker_id=int(worker_id),
            ts=float(payload.get("ts") or 0.0),
            free_slots=int(routing.get("free_slots") or 0),
            active_slots=int(routing.get("active_slots") or 0),
            queue_depth=int(routing.get("queue_depth") or 0),
            queued_steps=float(routing.get("queued_steps") or 0.0),
            token_ms=float(adm.get("est_token_ms") or 0.0),
            chunk_ms=float(adm.get("est_chunk_ms") or 0.0),
            prefix_keys=tuple(routing.get("prefix_keys") or ()),
            routed_in=int(routing.get("routed_in") or 0),
            affinity_hits=int(routing.get("affinity_hits") or 0))


def load_reports(workdir: str) -> Dict[int, WorkerReport]:
    """Parse every heartbeat under ``<workdir>/health`` that carries a
    routing section (workers without a generate engine publish none)."""
    from .fleet import HEALTH_DIR, read_health

    out: Dict[int, WorkerReport] = {}
    hdir = os.path.join(workdir, HEALTH_DIR)
    try:
        names = os.listdir(hdir)
    except OSError:
        return out
    for n in names:
        if not (n.startswith("worker-") and n.endswith(".json")):
            continue
        try:
            wid = int(n[len("worker-"):-len(".json")])
        except ValueError:
            continue
        payload = read_health(workdir, wid)
        if payload and payload.get("routing") is not None:
            out[wid] = WorkerReport.from_health(wid, payload)
    return out


# ---------------------------------------------------------------------------
# policy
# ---------------------------------------------------------------------------

@dataclass
class RouteDecision:
    worker_id: int
    reason: str              # "affinity" | "cost" | "least_loaded"
    est_cost_ms: float
    affinity: bool


class GenerateRouter:
    """Cost-model placement policy over worker load reports.

    Pure decision logic (no I/O): callers feed it the parsed reports
    and a request's prompt + token budget; it answers with the target
    worker or None when every report is stale — the signal to degrade
    to the shared any-claim stream.
    """

    def __init__(self, stale_after_s: float = STALE_AFTER_S,
                 affinity_bonus_ms: float = 50.0,
                 default_steps: int = 32):
        self.stale_after_s = float(stale_after_s)
        self.affinity_bonus_ms = float(affinity_bonus_ms)
        self.default_steps = max(int(default_steps), 1)
        self.counts = {"decisions": 0, "affinity": 0, "cost": 0,
                       "least_loaded": 0, "stale_fallback": 0}

    def decide(self, prompt, max_new_tokens: int,
               reports, prefill_chunks: int = 1,
               now: Optional[float] = None) -> Optional[RouteDecision]:
        """Pick the worker with the lowest estimated completion cost.

        - fresh reports + EWMA costs: prefill + decode + queue-wait
          scoring with the affinity bonus (a warm worker skips the
          prefill term AND gets ``affinity_bonus_ms`` off);
        - fresh reports, no cost observations yet: least-loaded
          (queued steps, then free slots);
        - no fresh report: None (caller uses the shared stream).

        Ties break on the shard fabric's rendezvous ranking of the
        prompt key, so equal-cost placement is deterministic and
        spreads across the fleet instead of pinning worker 0.
        """
        now = time.time() if now is None else now
        rows = list(reports.values()) if isinstance(reports, dict) \
            else list(reports)
        fresh = [r for r in rows if r.age_s(now) <= self.stale_after_s]
        telemetry.gauge("zoo_route_fresh_workers").set(len(fresh))
        if not fresh:
            self.counts["stale_fallback"] += 1
            telemetry.counter("zoo_route_stale_fallback_total").inc()
            return None
        key = prompt_key(np.asarray(prompt, np.int64))
        order = rendezvous_rank(key, [str(r.worker_id) for r in fresh])
        hrw_pos = {fresh[i].worker_id: pos for pos, i in enumerate(order)}
        steps = max(int(max_new_tokens or self.default_steps), 1)
        chunks = max(int(prefill_chunks), 1)
        toks = [r.token_ms for r in fresh if r.token_ms > 0]
        mean_token_ms = sum(toks) / len(toks) if toks else 0.0
        have_costs = mean_token_ms > 0
        best: Optional[Tuple[float, int, WorkerReport, bool]] = None
        for r in fresh:
            warm = r.holds_prefix(key)
            if have_costs:
                token_ms = r.token_ms or mean_token_ms
                chunk_ms = r.chunk_ms or token_ms
                prefill = 0.0 if warm else chunks * chunk_ms
                queue_wait = (r.queued_steps * token_ms
                              / max(r.free_slots, 1))
                cost = prefill + steps * token_ms + queue_wait
            else:
                # least-loaded: pending decode steps dominate, queued
                # records weigh their full budget, free slots credit
                cost = (r.queued_steps + r.queue_depth * steps
                        - r.free_slots)
            if warm:
                cost -= self.affinity_bonus_ms
            cand = (cost, hrw_pos[r.worker_id], r, warm)
            if best is None or cand[:2] < best[:2]:
                best = cand
        cost, _pos, row, warm = best
        self.counts["decisions"] += 1
        telemetry.counter("zoo_route_decisions_total").inc()
        if warm:
            reason = "affinity"
            self.counts["affinity"] += 1
            telemetry.counter("zoo_route_affinity_total").inc()
        elif have_costs:
            reason = "cost"
            self.counts["cost"] += 1
        else:
            reason = "least_loaded"
            self.counts["least_loaded"] += 1
            telemetry.counter("zoo_route_least_loaded_total").inc()
        return RouteDecision(worker_id=row.worker_id, reason=reason,
                             est_cost_ms=float(cost), affinity=warm)

    def stats(self) -> dict:
        return dict(self.counts)


# ---------------------------------------------------------------------------
# substream plumbing (file transport)
# ---------------------------------------------------------------------------

def _stream_files(dirpath: str) -> List[str]:
    try:
        return sorted(n for n in os.listdir(dirpath)
                      if n.endswith(".msgpack"))
    except OSError:
        return []


def substream_backlog(root: str) -> int:
    """Unclaimed records across every ``gen-w*`` substream — the part
    of the fleet backlog the shared stream's ``stream_len`` can't see."""
    total = 0
    try:
        names = os.listdir(root)
    except OSError:
        return 0
    for n in names:
        if n.startswith("gen-w"):
            total += len(_stream_files(os.path.join(root, n)))
    return total


def sweep_substream(root: str, worker_id: int,
                    shared_name: str = "image_stream") -> int:
    """Atomically move worker N's unclaimed substream records onto the
    shared any-claim stream (dead/retired worker re-drive).  Filenames
    (rids) are preserved, so FIFO order and consumer-ledger dedup both
    survive the move; a rename lives in exactly one stream at a time,
    so nothing is lost or double-claimed."""
    sdir = os.path.join(root, gen_substream(worker_id))
    shared = os.path.join(root, shared_name)
    os.makedirs(shared, exist_ok=True)
    n = 0
    for name in _stream_files(sdir):
        try:
            os.rename(os.path.join(sdir, name),
                      os.path.join(shared, name))
            n += 1
        except OSError:
            continue   # claimed (or swept) by someone else mid-walk
    if n:
        telemetry.counter("zoo_route_swept_total").inc(n)
    return n


def _write_with_rid(dirpath: str, rid: str, record: dict):
    """Atomic stream write under a caller-chosen rid — the re-drive
    path reuses the ORIGINAL rid so a consumer that already served the
    record drops the redelivery via its DeliveryLedger (the shard
    fabric's reused-dedup-token move, in files)."""
    payload = msgpack.packb(record, use_bin_type=True)
    os.makedirs(dirpath, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=dirpath, suffix=".tmp")
    with os.fdopen(fd, "wb") as f:
        f.write(payload)
    os.rename(tmp, os.path.join(dirpath, rid + ".msgpack"))


class RoutedGenerateQueue:
    """Producer-side routed placement over per-worker substreams.

    Wraps the shared transport handle: generate records are placed on
    the routed worker's private substream when a fresh load report
    says so, and on the shared any-claim stream otherwise (stale
    reports, non-file transports, non-generate records) — so the worst
    case is exactly today's behavior.  Result access delegates to the
    shared handle (results are per-root, substreams share them).
    """

    def __init__(self, workdir: str, src: Optional[str] = None,
                 base: Optional[StreamQueue] = None,
                 router: Optional[GenerateRouter] = None):
        self.workdir = workdir
        self.src = src or f"file:{workdir}"
        self.base = base if base is not None else \
            get_queue_backend(self.src)
        self.root = file_root(self.src)
        self.router = router or GenerateRouter()
        self._subs: Dict[int, FileStreamQueue] = {}
        self._pending: "OrderedDict[str, Tuple[dict, str]]" = OrderedDict()
        self._lock = threading.Lock()
        self.routed = 0
        self.unrouted = 0
        self.swept = 0
        self.reenqueued = 0

    # -- placement ------------------------------------------------------
    def _substream(self, wid: int) -> FileStreamQueue:
        q = self._subs.get(wid)
        if q is None:
            q = self._subs[wid] = FileStreamQueue(
                self.root, name=gen_substream(wid))
        return q

    def reports(self) -> Dict[int, WorkerReport]:
        return load_reports(self.workdir)

    def enqueue(self, record: dict) -> str:
        rid, _decision = self.enqueue_routed(record)
        return rid

    def enqueue_routed(self, record: dict
                       ) -> Tuple[str, Optional[RouteDecision]]:
        """Place one wire record; returns (rid, decision) where a None
        decision means the shared any-claim stream took it."""
        gen = record.get("generate") if isinstance(record, dict) else None
        decision = None
        if gen is not None and self.root is not None:
            decision = self.router.decide(
                gen.get("prompt") or [],
                int(gen.get("max_new_tokens") or 0),
                self.reports())
        if decision is None:
            rid = self.base.enqueue(record)
            self.unrouted += 1
        else:
            record = dict(record, routed_to=decision.worker_id)
            rid = self._substream(decision.worker_id).enqueue(record)
            self.routed += 1
        self._note_pending(record, rid)
        return rid, decision

    def _note_pending(self, record: dict, rid: str):
        uri = record.get("uri") if isinstance(record, dict) else None
        if uri is None:
            return
        with self._lock:
            self._pending[uri] = (record, rid)
            self._pending.move_to_end(uri)
            while len(self._pending) > PENDING_WINDOW:
                self._pending.popitem(last=False)

    def _forget_pending(self, uris: Iterable[str]):
        with self._lock:
            for uri in uris:
                self._pending.pop(uri, None)

    # -- redelivery -----------------------------------------------------
    def sweep_worker(self, worker_id: int) -> int:
        """Move a dead worker's unclaimed substream records back onto
        the shared stream (see :func:`sweep_substream`)."""
        if self.root is None:
            return 0
        shared_name = getattr(self.base, "stream_dir", None)
        name = os.path.basename(shared_name) if shared_name \
            else "image_stream"
        n = sweep_substream(self.root, worker_id, shared_name=name)
        self.swept += n
        return n

    def _rid_still_queued(self, record: dict, rid: str) -> bool:
        """True while the original enqueue file is still unclaimed on
        the shared stream or its routed substream — re-driving such a
        record would put TWO claimable copies in flight (a restarted
        worker serves one, a survivor the other: double delivery)."""
        fname = rid + ".msgpack"
        dirs = [getattr(self.base, "stream_dir", None) or
                os.path.join(self.root, "image_stream")]
        wid = record.get("routed_to")
        if wid is not None:
            dirs.append(os.path.join(self.root, gen_substream(wid)))
        return any(os.path.exists(os.path.join(d, fname)) for d in dirs)

    def reenqueue_missing(self, uris: Iterable[str]) -> int:
        """Re-drive records whose results never arrived (claimed by a
        SIGKILLed worker that died before committing).  Rewrites each
        record onto the shared stream under its original rid, so a
        consumer that did serve it skips the duplicate.  Records still
        queued (unclaimed file on disk) are skipped — they will be
        served or swept, and a second copy would double-deliver.
        Returns how many were re-sent; uris outside the pending window
        are skipped."""
        if self.root is None:
            return 0
        shared_dir = getattr(self.base, "stream_dir", None) or \
            os.path.join(self.root, "image_stream")
        n = 0
        for uri in uris:
            with self._lock:
                entry = self._pending.get(uri)
            if entry is None:
                continue
            record, rid = entry
            if self._rid_still_queued(record, rid):
                continue
            _write_with_rid(shared_dir, rid, record)
            n += 1
        if n:
            self.reenqueued += n
            telemetry.counter("zoo_route_reenqueued_total").inc(n)
        return n

    # -- result access (delegated; results are shared per root) ---------
    def get_result(self, uri: str, pop: bool = True):
        v = self.base.get_result(uri, pop=pop)
        if v is not None and pop:
            self._forget_pending([uri])
        return v

    def all_results(self, pop: bool = True) -> Dict[str, bytes]:
        out = self.base.all_results(pop=pop)
        if pop and out:
            self._forget_pending(out.keys())
        return out

    def put_results(self, results: Dict[str, bytes]):
        self.base.put_results(results)

    def stream_len(self) -> int:
        n = self.base.stream_len()
        if self.root is not None:
            n += substream_backlog(self.root)
        return n

    def stats(self) -> dict:
        return {"routed": self.routed, "unrouted": self.unrouted,
                "swept": self.swept, "reenqueued": self.reenqueued,
                "router": self.router.stats()}


class WorkerIntakeQueue(StreamQueue):
    """Worker-side intake over (own substream, shared stream).

    ``read_batch`` drains the worker's private substream first (routed
    records keep FIFO within their substream), then tops up from the
    shared any-claim stream — so a routed fleet still serves unrouted
    traffic, and a fleet with no router behaves exactly as before
    (the substream is simply empty).  Everything else — results,
    trim, enqueue — delegates to the shared handle, which owns the
    per-root results map.
    """

    def __init__(self, root: str, worker_id: int,
                 shared: Optional[FileStreamQueue] = None):
        self.worker_id = int(worker_id)
        self.shared = shared if shared is not None \
            else FileStreamQueue(root)
        self.sub = FileStreamQueue(root, name=gen_substream(worker_id))

    def enqueue(self, record: dict) -> str:
        return self.shared.enqueue(record)

    def read_batch(self, max_items: int, timeout: float = 1.0):
        out = self.sub.read_batch(max_items, timeout=0.0)
        want = int(max_items) - len(out)
        if want > 0:
            out.extend(self.shared.read_batch(
                want, timeout=0.0 if out else timeout))
        return out

    def put_result(self, uri: str, value: bytes):
        self.shared.put_result(uri, value)

    def put_results(self, results: Dict[str, bytes]):
        self.shared.put_results(results)

    def get_result(self, uri: str, pop: bool = True):
        return self.shared.get_result(uri, pop=pop)

    def all_results(self, pop: bool = True):
        return self.shared.all_results(pop=pop)

    def stream_len(self) -> int:
        return self.shared.stream_len() + self.sub.stream_len()

    def trim(self, keep_last: int):
        self.shared.trim(keep_last)

    def consumer_stats(self) -> dict:
        agg = dict(self.shared.consumer_stats())
        for k, v in self.sub.consumer_stats().items():
            if isinstance(v, (int, float)):
                agg[k] = agg.get(k, 0) + v
        return agg
