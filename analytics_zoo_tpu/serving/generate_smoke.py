"""Generate-serving smoke: continuous batching through the full wire
path, asserting the three scheduler invariants CI cares about.

CI/tooling entry (``scripts/generate-smoke``): a live
:class:`ClusterServing` with the stub decode engine serves two
overlapping generate requests over the in-process transport —

- **join-mid-generation**: request B is submitted after request A's
  generation is underway and must *finish and commit while A is still
  decoding* (iteration-level scheduling; static batching would hold B's
  result until A drained);
- **stop-token eviction**: B's scripted stream emits the stop token
  early; its result must carry ``finish == "stop_id"`` with the stream
  cut at the stop token;
- **exactly-once results**: every submitted request produces exactly
  one committed payload (queried twice: present once, absent after the
  pop) and the scheduler counts zero duplicate commits.

A second phase re-serves with the generative fast path configured
(``prefill_chunk`` + ``speculative``) and asserts the two config-driven
legs: a **long prompt** joins through chunked prefill with the exact
same stream a monolithic join produces, and **speculative decoding**
emits the exact greedy stream while verifying multiple tokens per
target step (acceptance rate lands in the stats).

Exit 0 on success, 1 on any violated invariant, printing one JSON line
of pipeline stats per phase either way.

Usage::

    python -m analytics_zoo_tpu.serving.generate_smoke [--step-ms 20]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _fastpath_phase(args, failures):
    """Chunked-prefill + speculative-decode legs over the wire path."""
    from .client import GenerationResult, InputQueue, OutputQueue
    from .cluster_serving import ClusterServing, ClusterServingHelper
    from .queue_backend import InProcessStreamQueue

    chunk = 16
    helper = ClusterServingHelper(config={
        "data": {},
        "params": {"batch_size": 4},
        "generate": {"slots": 2, "continuous": True,
                     "stub_ms_per_step": args.step_ms, "stop_id": 0,
                     "max_len": 1024,
                     "prefill_chunk": chunk,
                     "speculative": {"k": 3,
                                     "draft_ms_per_step":
                                         args.step_ms / 20.0}}})
    backend = InProcessStreamQueue()
    serving = ClusterServing(model=None, helper=helper,
                             backend=backend).start()
    in_q = InputQueue(backend=backend)
    out_q = OutputQueue(backend=backend)
    try:
        # C: 120-token prompt > prefill_chunk — joins in ceil(120/16)
        # interleaved chunk dispatches; stub stream base = prompt[0]
        in_q.enqueue_generate("gen-C", [7] + [0] * 119, max_new_tokens=6)
        # D: short prompt with a scripted stop mid-speculation round
        in_q.enqueue_generate("gen-D", [50, 3], max_new_tokens=20,
                              stop_id=0)
        got = out_q.wait_all(["gen-C", "gen-D"], timeout=args.timeout)
    finally:
        serving.stop()

    stats = serving.pipeline_stats()
    gen = stats.get("generation", {})
    c, d = got.get("gen-C"), got.get("gen-D")
    if not isinstance(c, GenerationResult) or \
            c.tolist() != list(range(8, 14)):
        failures.append(f"long-prompt chunked stream wrong: "
                        f"{getattr(c, 'tolist', lambda: c)()}")
    if not isinstance(d, GenerationResult) or d.tolist() != [51, 52, 0] \
            or d.finish != "stop_id":
        failures.append(f"speculative stop stream wrong: "
                        f"{getattr(d, 'tolist', lambda: d)()}")
    eng = gen.get("engine") or {}
    if eng.get("acceptance_rate", 0) < 1.0:
        failures.append(f"stub draft acceptance {eng.get('acceptance_rate')}"
                        f" != 1.0")
    target = eng.get("target") or {}
    # chunked join dispatches: ceil(120/16) chunks for C + 1 join for D
    want = -(-120 // chunk) + 1
    if target.get("prefill_calls") != want:
        failures.append(f"prefill dispatches {target.get('prefill_calls')}"
                        f" != {want} (chunked join not engaged?)")
    print(json.dumps(stats))
    return gen


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="generate-smoke")
    ap.add_argument("--step-ms", type=float, default=20.0,
                    help="stub decode-step wall time (gang-wide)")
    ap.add_argument("--timeout", type=float, default=30.0)
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from .client import GenerationResult, InputQueue, OutputQueue
    from .cluster_serving import ClusterServing, ClusterServingHelper
    from .queue_backend import InProcessStreamQueue

    helper = ClusterServingHelper(config={
        "data": {},
        "params": {"batch_size": 4},
        "generate": {"slots": 2, "continuous": True,
                     "stub_ms_per_step": args.step_ms, "stop_id": 0}})
    backend = InProcessStreamQueue()
    serving = ClusterServing(model=None, helper=helper,
                             backend=backend).start()
    in_q = InputQueue(backend=backend)
    out_q = OutputQueue(backend=backend)
    failures = []

    try:
        # A: long stream — 30 tokens at step_ms each keeps the gang busy
        in_q.enqueue_generate("gen-A", [10], max_new_tokens=30)
        # wait until A's generation is underway before submitting B
        deadline = time.time() + args.timeout
        while time.time() < deadline:
            if serving.pipeline_stats().get(
                    "generation", {}).get("joins", 0) >= 1:
                break
            time.sleep(0.005)
        else:
            failures.append("request A never joined the gang")
        # B: scripted to emit the stop token at position 3 (prompt[1])
        in_q.enqueue_generate("gen-B", [50, 3], max_new_tokens=20,
                              stop_id=0)
        # join-mid-generation: B's result must land while A still decodes
        b_res, a_still_running = None, False
        deadline = time.time() + args.timeout
        while time.time() < deadline:
            b_res = out_q.query("gen-B")
            if b_res is not None:
                a_still_running = out_q.query("gen-A") is None
                break
            time.sleep(args.step_ms / 4e3)
        if b_res is None:
            failures.append("no result for gen-B")
        elif not a_still_running:
            failures.append("gen-B did not commit while gen-A was "
                            "still generating (continuous batching "
                            "not engaged)")
        got = out_q.wait_all(["gen-A", "gen-B"], timeout=args.timeout)
    finally:
        serving.stop()

    stats = serving.pipeline_stats()
    gen = stats.get("generation", {})
    a, b = got.get("gen-A"), got.get("gen-B")
    if not isinstance(a, GenerationResult):
        failures.append(f"gen-A result wrong type: {type(a).__name__}")
    else:
        if a.tolist() != list(range(11, 41)):
            failures.append(f"gen-A tokens wrong: {a.tolist()}")
        if a.finish != "max_new_tokens":
            failures.append(f"gen-A finish={a.finish}")
    if not isinstance(b, GenerationResult):
        failures.append(f"gen-B result wrong type: {type(b).__name__}")
    else:
        # stop-token eviction: stream cut at the scripted stop position
        if b.tolist() != [51, 52, 0]:
            failures.append(f"gen-B tokens wrong: {b.tolist()}")
        if b.finish != "stop_id":
            failures.append(f"gen-B finish={b.finish}")
    # exactly-once: wait_all popped both; a second read must find nothing
    for uri in ("gen-A", "gen-B"):
        if out_q.query(uri) is not None:
            failures.append(f"{uri} result still present after pop "
                            f"(committed more than once?)")
    if gen.get("duplicate_commits", 0):
        failures.append(f"{gen['duplicate_commits']} duplicate commits")
    if gen.get("committed") != gen.get("submitted"):
        failures.append(f"committed={gen.get('committed')} != "
                        f"submitted={gen.get('submitted')}")

    print(json.dumps(stats))
    gen2 = _fastpath_phase(args, failures)
    if failures:
        print("SMOKE FAILED: " + "; ".join(failures), file=sys.stderr)
        return 1
    print(f"SMOKE OK: 4 sequences, "
          f"{gen.get('tokens', 0) + gen2.get('tokens', 0)} tokens, "
          f"join-mid-generation + stop-token eviction + exactly-once + "
          f"chunked long-prompt join + speculative decode all held",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
