"""Serving-fleet end-to-end smoke (``scripts/fleet-smoke``; CI fast tier).

Brings up a 2-worker :class:`ServingFleet` over the file queue backend
with the deterministic echo stub model and asserts the fleet contract
(docs/serving-fleet.md):

- **no double-serving**: every enqueued uri gets exactly one result with
  *its own* record's value, the workers' combined ``results_out`` equals
  the offered record count, and no worker's consumer ledger saw a
  duplicate delivery;
- **restart**: a SIGKILLed worker is detected and replaced (new pid,
  fresh heartbeat) within the health timeout, and the fleet keeps
  serving afterwards;
- **typed shedding**: a request with an unmeetable ``deadline_ms`` comes
  back as a typed rejection (``shed_deadline``/``shed_expired``), not a
  silent timeout.

Exit 0 on success, 1 on any violated assertion (printing the fan-in
worker log for diagnosis).
"""

from __future__ import annotations

import argparse
import io
import os
import shutil
import signal
import sys
import tempfile
import threading
import time

CONFIG_TMPL = """\
model:
  stub_ms_per_batch: {stub_ms}

data:
  src: file:{stream_dir}
  image_shape: 3, 4, 4

params:
  batch_size: 8
  top_n: 0
  workers: 2
  health_interval: 0.25
  health_timeout: {health_timeout}
"""


def run_smoke(records: int = 96, stub_ms: float = 2.0,
              health_timeout: float = 3.0, stream=None) -> int:
    import numpy as np

    from .client import (InputQueue, OutputQueue, ServingRejected)
    from .fleet import ServingFleet, read_health
    from .queue_backend import FileStreamQueue

    out = stream if stream is not None else sys.stdout
    workdir = tempfile.mkdtemp(prefix="zoo_fleet_smoke_")
    stream_dir = os.path.join(workdir, "stream")
    cfg = os.path.join(workdir, "config.yaml")
    with open(cfg, "w") as f:
        f.write(CONFIG_TMPL.format(stub_ms=stub_ms, stream_dir=stream_dir,
                                   health_timeout=health_timeout))
    shape = (3, 4, 4)
    cap = io.StringIO()

    def fail(msg):
        out.write(cap.getvalue())
        out.write(f"FLEET_SMOKE_FAIL: {msg}\n")
        return 1

    fleet = ServingFleet(cfg, workdir, stream=cap,
                         env={"JAX_PLATFORMS": "cpu"})
    sup = threading.Thread(target=fleet.supervise, daemon=True)
    try:
        fleet.start()
        sup.start()
        if not fleet.wait_healthy(timeout=90.0):
            return fail("workers never became healthy")

        # -- phase 1: partitioned serving, no double-delivery ----------
        in_q = InputQueue(backend=FileStreamQueue(stream_dir))
        out_q = OutputQueue(backend=FileStreamQueue(stream_dir))
        uris = [f"u-{i}" for i in range(records)]
        for i, uri in enumerate(uris):
            in_q.enqueue(uri, input=np.full(shape, i, np.float32))
        got = out_q.wait_all(uris, timeout=90.0)
        if len(got) != records:
            return fail(f"only {len(got)}/{records} results")
        for i, uri in enumerate(uris):
            v = got[uri]
            if isinstance(v, Exception):
                return fail(f"{uri} errored: {v}")
            if abs(float(np.asarray(v).ravel()[0]) - i) > 1e-4:
                return fail(f"{uri} value {float(v)} != {i} (cross-wired)")
        # the workers' own counters must account for every record exactly
        # once (stats dumps are periodic — poll until they catch up)
        deadline = time.time() + 20.0
        served = split = None
        while time.time() < deadline:
            stats = fleet.worker_stats()
            split = {s["worker_id"]: s.get("results_out", 0) for s in stats}
            served = sum(split.values())
            dups = sum((s.get("queue") or {}).get("duplicates", 0)
                       for s in stats)
            if served >= records and len(split) == fleet.workers:
                break
            time.sleep(0.5)
        if served != records:
            return fail(f"combined results_out {served} != {records} "
                        f"(split {split}) — double or lost serving")
        if dups:
            return fail(f"{dups} duplicate deliveries in consumer ledgers")

        # -- phase 2: SIGKILL a worker; supervision must replace it ----
        victim = 1
        h0 = read_health(workdir, victim)
        if not h0:
            return fail("no health file for victim worker")
        os.kill(int(h0["pid"]), signal.SIGKILL)
        t_kill = time.time()
        replaced = False
        while time.time() - t_kill < health_timeout + 60.0:
            h1 = read_health(workdir, victim)
            if h1 and h1["pid"] != h0["pid"]:
                replaced = True
                break
            time.sleep(0.1)
        if not replaced:
            return fail(f"worker {victim} not replaced after SIGKILL")
        if fleet.restarts.get(victim, 0) < 1:
            return fail("fleet restart counter did not move")
        # fleet still serves end-to-end after the restart
        uris2 = [f"v-{i}" for i in range(16)]
        for i, uri in enumerate(uris2):
            in_q.enqueue(uri, input=np.full(shape, 100 + i, np.float32))
        got2 = out_q.wait_all(uris2, timeout=60.0)
        if len(got2) != len(uris2):
            return fail(f"post-restart: only {len(got2)}/{len(uris2)} "
                        f"results")

        # -- phase 3: unmeetable deadline -> typed rejection -----------
        in_q.enqueue("doomed", deadline_ms=1.0,
                     input=np.full(shape, 1, np.float32))
        got3 = out_q.wait_all(["doomed"], timeout=30.0)
        v = got3.get("doomed")
        if not isinstance(v, ServingRejected):
            return fail(f"expected ServingRejected for doomed request, "
                        f"got {type(v).__name__}: {v}")
        if v.code not in ("shed_deadline", "shed_expired"):
            return fail(f"unexpected shed code {v.code!r}")

        out.write(f"FLEET_SMOKE_OK workers={fleet.workers} "
                  f"records={records} split={split} "
                  f"restarted=worker-{victim} shed_code={v.code}\n")
        return 0
    finally:
        fleet.stop()
        sup.join(timeout=30.0)
        fleet.shutdown()
        shutil.rmtree(workdir, ignore_errors=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="fleet-smoke")
    ap.add_argument("--records", type=int, default=96)
    ap.add_argument("--stub-ms", type=float, default=2.0)
    ap.add_argument("--health-timeout", type=float, default=3.0)
    args = ap.parse_args(argv)
    return run_smoke(records=args.records, stub_ms=args.stub_ms,
                     health_timeout=args.health_timeout)


if __name__ == "__main__":
    sys.exit(main())
