"""ClusterServing: the streaming inference service loop.

Parity: ``zoo/.../serving/ClusterServing.scala:44-392`` — read a micro-batch
from the input stream (:105-116), base64-decode images, predict with a
shared InferenceModel, write results to the results map, apply the memory
watermark trim (:130-136); config comes from ``config.yaml``
(``ClusterServingHelper.initArgs``, serving/utils/ClusterServingHelper.scala
:104) and throughput/latency land in the InferenceSummary (:96-97).

TPU redesign: Spark Structured Streaming becomes a host-driven pipeline
feeding AOT-compiled XLA executables.  The hot path is three overlapped
stages connected by bounded queues (backpressure propagates to the
stream read):

1. **decode** — a pool of ``decode_workers`` threads pulls records off
   the :class:`StreamQueue` and produces ready tensors concurrently with
   compute (base64/cv2 decode is host work the accelerator should never
   wait on);
2. **compute** — a single thread assembles ready tensors into
   power-of-two **padding buckets** (each bucket is its own AOT
   signature in :class:`InferenceModel`, pre-compiled by
   :meth:`ClusterServing.warmup`), so a half-full batch no longer pays
   full-batch MXU time, and dispatches **asynchronously** — batch *k+1*
   is submitted before batch *k*'s host transfer completes;
3. **write** — a thread drains predictions (the ``np.asarray`` host
   transfer is its synchronization point) and commits results to the
   queue backend.

The original single-thread loop survives as ``pipelined=False`` (config
``params.pipelined``) and is the baseline the ``bench.py`` serving leg
and the slow comparison test measure against.  Per-stage latency
percentiles, queue depths, and bucket usage are recorded in
:class:`InferenceSummary` so the overlap is observable.

Deadline-aware admission + latency decomposition (docs/serving-fleet.md):
records carrying ``deadline_ms`` pass through an
:class:`~analytics_zoo_tpu.serving.admission.AdmissionController` at
intake (unmeetable → typed ``shed_deadline`` rejection) and again at
dispatch (``shed_expired``); the compute stage may *linger* a bounded
moment (``params.linger_ms``) to round partial batches up to the next
padding bucket.  Each record's ``enqueue_ts_ms`` (client) and
``dequeue_ts_ms`` (backend) stamps travel in a :class:`RecordMeta`
through the stages, and the writer emits a per-row ``timing`` payload
splitting ``transport_in_ms`` / ``queue_ms`` / ``device_ms`` /
``server_ms`` — so a fat tail is attributable to the wire or the
accelerator, not guessed at.
"""

from __future__ import annotations

import base64
import json
import logging
import os
import queue
import threading
import time
from collections import Counter
from typing import Dict, List, NamedTuple, Optional, Sequence

import numpy as np

from ..pipeline.inference import InferenceModel
from ..pipeline.inference.inference_model import AbstractModel
from ..pipeline.inference.inference_summary import InferenceSummary
from ..utils import telemetry
from ..utils.slo import SloEngine, parse_slo_class_config, parse_slo_config
from ..utils.telemetry import span
from .admission import (AdaptiveBatcher, AdmissionController, SHED_CAPACITY,
                        SHED_DEADLINE, SHED_EXPIRED, TenantScheduler, now_ms)
from .queue_backend import StreamQueue, get_queue_backend

logger = logging.getLogger("analytics_zoo_tpu.serving")

#: shutdown marker passed through the stage queues
_SENTINEL = object()


class RecordMeta(NamedTuple):
    """Per-record identity + timestamps threaded through the pipeline
    stages (all ``*_ms`` are epoch milliseconds; ``t_in`` is the server's
    perf_counter at intake, for the e2e stage percentile)."""

    t_in: float
    uri: str
    enqueue_ts_ms: Optional[float]   # stamped by the client
    dequeue_ts_ms: Optional[float]   # stamped by the queue backend
    deadline_at_ms: Optional[float]  # absolute deadline; None = no deadline
    trace_id: Optional[str] = None   # client-stamped request trace context
    tenant: Optional[str] = None     # SLO class name (multi-tenancy)


class _RequestLog:
    """Append-only jsonl of committed request timings keyed by trace id
    — the data source `zoo-serving trace <id>` renders its waterfall
    from.  Size-rotated (one ``.1`` generation) so a long-running worker
    cannot fill the disk; writes never raise into the serve path."""

    def __init__(self, path: str, max_bytes: int = 16 << 20):
        self.path = os.path.abspath(path)
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._f = None
        self._written = 0

    def append(self, obj: dict):
        try:
            line = json.dumps(obj) + "\n"
            with self._lock:
                if self._f is None:
                    os.makedirs(os.path.dirname(self.path), exist_ok=True)
                    self._f = open(self.path, "a")
                    self._written = self._f.tell()
                self._f.write(line)
                self._f.flush()
                self._written += len(line)
                if self._written > self.max_bytes:
                    self._f.close()
                    os.replace(self.path, self.path + ".1")
                    self._f = open(self.path, "a")
                    self._written = 0
        except OSError:
            pass

    def close(self):
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


class EchoStubModel(AbstractModel):
    """Deterministic stand-in for a real model: sleeps a fixed
    ``ms_per_batch`` (a perfectly flat "device" time) and echoes each
    row's mean.  Lets fleet workers, smoke tests, and bench legs exercise
    the full wire path in subprocesses without a saved model — enabled
    via config ``model.stub_ms_per_batch``."""

    def __init__(self, ms_per_batch: float = 5.0):
        self.ms_per_batch = float(ms_per_batch)

    def predict(self, batch):
        batch = np.asarray(batch, np.float32)
        if self.ms_per_batch > 0:
            time.sleep(self.ms_per_batch / 1e3)
        return batch.reshape(batch.shape[0], -1).mean(axis=1, keepdims=True)

    def predict_async(self, batch):
        return self.predict(batch)


def power_of_two_buckets(batch_size: int) -> List[int]:
    """Padding buckets 1, 2, 4, ... capped by (and always including)
    ``batch_size`` — each bucket is one AOT signature."""
    batch_size = max(int(batch_size), 1)
    buckets, b = [], 1
    while b < batch_size:
        buckets.append(b)
        b *= 2
    buckets.append(batch_size)
    return sorted(set(buckets))


def pick_bucket(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= n (``buckets`` sorted ascending); the largest
    bucket when n exceeds them all (callers chunk at batch_size)."""
    for b in buckets:
        if b >= n:
            return b
    return buckets[-1]


def _parse_bool(value, default: bool) -> bool:
    if value is None:
        return default
    if isinstance(value, str):
        return value.strip().lower() in ("1", "true", "yes", "on")
    return bool(value)


class ClusterServingHelper:
    """Parses the serving yaml (ClusterServingHelper.initArgs parity)."""

    def __init__(self, config_path: Optional[str] = None,
                 config: Optional[dict] = None):
        if config is None:
            import yaml

            with open(config_path) as f:
                config = yaml.safe_load(f) or {}
        model = config.get("model") or {}
        data = config.get("data") or {}
        params = config.get("params") or {}
        self.model_path = model.get("path")
        # deterministic echo stub (EchoStubModel) instead of a saved
        # model — fleet smoke / bench workers (docs/serving-fleet.md)
        raw_stub = model.get("stub_ms_per_batch")
        self.stub_ms_per_batch = None if raw_stub is None else float(raw_stub)
        # transport spec; ZOO_SERVING_TRANSPORT (the CLI's --transport
        # flag) overrides the config so one yaml serves every wire —
        # fleet workers inherit the override through their environment
        self.src = os.environ.get("ZOO_SERVING_TRANSPORT") or \
            data.get("src")
        shape = data.get("image_shape") or "3, 224, 224"
        if isinstance(shape, str):
            shape = [int(s) for s in shape.split(",")]
        self.image_shape = tuple(shape)
        self.batch_size = int(params.get("batch_size") or 4)
        # explicit 0 means raw output (no top-n formatting), so the
        # falsy-default idiom would silently re-enable it
        raw_top = params.get("top_n")
        self.top_n = 1 if raw_top is None else int(raw_top)
        # watermark: trim stream when it exceeds maxlen (60%*80% parity)
        self.stream_maxlen = int(params.get("stream_maxlen") or 10000)
        # -- pipeline knobs (docs/serving-pipeline.md) ------------------
        self.pipelined = _parse_bool(params.get("pipelined"), True)
        self.decode_workers = int(params.get("decode_workers") or 2)
        self.queue_depth = int(params.get("queue_depth") or
                               max(2 * self.batch_size, 16))
        raw = params.get("bucket_sizes")
        if isinstance(raw, str):
            raw = [int(s) for s in raw.split(",") if s.strip()]
        self.bucket_sizes = sorted({int(b) for b in raw}) if raw else None
        self.warmup = _parse_bool(params.get("warmup"), False)
        # periodic pipeline_stats() JSON dump for `zoo-serving status`
        # (the CLI start path defaults this to <workdir>/stats.json)
        self.stats_path = params.get("stats_path")
        # -- admission / adaptive batching (docs/serving-fleet.md) ------
        self.linger_ms = float(params.get("linger_ms") or 0.0)
        raw_dl = params.get("default_deadline_ms")
        self.default_deadline_ms = None if raw_dl is None else float(raw_dl)
        self.admission_safety_ms = float(
            params.get("admission_safety_ms") or 2.0)
        # -- fleet (serving/fleet.py) -----------------------------------
        self.workers = int(params.get("workers") or 1)
        self.health_interval = float(params.get("health_interval") or 1.0)
        self.health_timeout = float(params.get("health_timeout") or 10.0)
        # fleet crash-loop protection (docs/fault-tolerance.md): cap on
        # consecutive restarts per worker, and the initial backoff the
        # supervise loop doubles per restart
        self.max_restarts = int(params.get("max_restarts") or 10)
        self.restart_backoff_s = float(
            params.get("restart_backoff_s") or 0.5)
        # backlog-driven autoscaling (serving/admission.BacklogAutoscaler,
        # docs/serving-network.md#autoscaling): enabled when the
        # min..max band is wider than a point; the band defaults to the
        # fixed worker count, i.e. autoscaling off
        self.min_workers = int(params.get("min_workers") or self.workers)
        self.max_workers = int(params.get("max_workers") or self.workers)
        self.autoscale_target_ms = float(
            params.get("autoscale_target_ms") or
            (self.default_deadline_ms or 250.0))
        self.autoscale_interval = float(
            params.get("autoscale_interval") or 0.5)
        self.scale_up_fraction = float(
            params.get("scale_up_fraction") or 0.5)
        self.scale_down_idle_s = float(
            params.get("scale_down_idle_s") or 3.0)
        self.autoscale_cooldown_s = float(
            params.get("autoscale_cooldown_s") or 2.0)
        # -- telemetry (docs/observability.md): span tracing + per-process
        # metrics.json; the CLI --trace-dir flag overrides trace_dir
        self.telemetry = _parse_bool(params.get("telemetry"), False)
        self.trace_dir = params.get("trace_dir")
        # committed request timings (jsonl) for `zoo-serving trace <id>`;
        # the CLI/fleet default this under the workdir when telemetry is on
        self.request_log = params.get("request_log")
        # -- SLO objectives (utils/slo.py, docs/observability.md#slo) ----
        self.slo_config = config.get("slo") or {}
        self.slo_objectives = parse_slo_config(self.slo_config)
        # named SLO classes bound to (model, version) with weights and
        # shed priorities (docs/multi-tenancy.md)
        self.slo_classes = parse_slo_class_config(self.slo_config)
        # -- generative serving (docs/serving-generate.md) --------------
        gen = config.get("generate") or {}
        self.generate_slots = int(gen.get("slots") or 4)
        self.generate_continuous = _parse_bool(gen.get("continuous"), True)
        self.generate_max_len = int(gen.get("max_len") or 1024)
        self.generate_max_new_tokens = int(gen.get("max_new_tokens") or 32)
        raw_gstop = gen.get("stop_id")
        self.generate_stop_id = None if raw_gstop is None else int(raw_gstop)
        # deterministic stub decode engine (StubDecodeEngine) — fleet
        # smoke / bench workers, mirrors model.stub_ms_per_batch
        raw_gstub = gen.get("stub_ms_per_step")
        self.generate_stub_ms_per_step = \
            None if raw_gstub is None else float(raw_gstub)
        # -- generative fast path (docs/serving-generate.md#fast-path) --
        # chunked prefill width in tokens; 0 disables interleaving
        self.generate_prefill_chunk = int(gen.get("prefill_chunk") or 0)
        # KV slab dtype: "f32" (default) or "int8" (Int8KVSlab storage)
        self.generate_kv_dtype = str(gen.get("kv_cache") or "f32").lower()
        # shared-prefix cache budget in MiB; 0 disables the cache
        self.generate_prefix_cache_mb = float(
            gen.get("prefix_cache_mb") or 0)
        # speculative decoding: {"k": 3, "draft_ms_per_step": 0.1}; the
        # stub path builds a draft stub, the device path needs a draft
        # engine injected via set_generate_engine
        spec = gen.get("speculative") or {}
        self.generate_speculative_k = int(spec.get("k") or 0)
        raw_draft = spec.get("draft_ms_per_step")
        self.generate_draft_ms_per_step = \
            None if raw_draft is None else float(raw_draft)
        # -- model registry (docs/model-registry.md) --------------------
        reg = config.get("registry") or {}
        self.registry_root = reg.get("root")
        self.default_model = reg.get("default_model") or "default"
        self.canary_error_threshold = float(
            reg.get("canary_error_threshold") or 0.5)
        self.canary_min_requests = int(reg.get("canary_min_requests") or 20)
        self.drain_timeout = float(reg.get("drain_timeout") or 10.0)

    def load_inference_model(self, concurrent_num: int = 1) -> InferenceModel:
        model = InferenceModel(supported_concurrent_num=concurrent_num)
        model.load(self.model_path)
        return model


class ClusterServing:
    """The serving loop.  ``serve_forever`` blocks; ``start``/``stop`` run
    it on a daemon thread (tests, notebooks)."""

    def __init__(self, model: Optional[InferenceModel] = None,
                 helper: Optional[ClusterServingHelper] = None,
                 backend: Optional[StreamQueue] = None,
                 config_path: Optional[str] = None,
                 summary: Optional[InferenceSummary] = None,
                 preprocessing=None):
        self.helper = helper or ClusterServingHelper(config_path=config_path)
        self.model = model if model is not None else self._default_model()
        self.db = backend if backend is not None else \
            get_queue_backend(self.helper.src)
        # always keep a summary: log_dir=None is stats-only (percentiles
        # + queue depths without event files)
        self.summary = summary if summary is not None else InferenceSummary()
        self.preprocessing = preprocessing
        h = self.helper
        self.pipelined = bool(getattr(h, "pipelined", True))
        self.decode_workers = max(1, int(getattr(h, "decode_workers", 2)))
        self.queue_depth = max(2, int(getattr(h, "queue_depth", 0) or
                                      max(2 * h.batch_size, 16)))
        self.buckets = list(getattr(h, "bucket_sizes", None) or
                            power_of_two_buckets(h.batch_size))
        if self.buckets[-1] < h.batch_size:
            self.buckets.append(int(h.batch_size))
        # pipeline counters (guarded by _ctr_lock; read via pipeline_stats)
        self._ctr_lock = threading.Lock()
        self.records_in = 0
        self.results_out = 0
        self.dropped = 0
        self.dead_letters = 0
        self.shed = 0
        self.batches = 0
        # routed-placement intake accounting (serving/routing.py):
        # routed_in counts records stamped `routed_to` us; affinity_hits
        # counts those whose prompt was warm in our prefix cache
        self.routed_in = 0
        self.affinity_hits = 0
        self.bucket_counts: Counter = Counter()
        self.stats_path = getattr(h, "stats_path", None)
        # deadline-aware admission + bounded linger (serving/admission.py)
        self.admission = AdmissionController(
            safety_ms=float(getattr(h, "admission_safety_ms", 2.0)))
        self.batcher = AdaptiveBatcher(
            self.buckets, self.admission,
            linger_ms=float(getattr(h, "linger_ms", 0.0)))
        self.default_deadline_ms = getattr(h, "default_deadline_ms", None)
        # SLO engine (utils/slo.py): armed when the config declares
        # objectives; evaluated live by the stats loop, fed by the
        # writer/shed/dead-letter paths through _count/_record_row_timing
        self.slo: Optional[SloEngine] = None
        if getattr(h, "slo_objectives", None):
            self.slo = SloEngine(h.slo_objectives)
        # multi-tenant intake (serving/admission.TenantScheduler,
        # docs/multi-tenancy.md): armed when the config declares SLO
        # classes; one SloEngine per class with objectives, so burn
        # rates are evaluated per tenant
        self.tenants: Optional[TenantScheduler] = None
        self._class_slo: Dict[str, SloEngine] = {}
        if getattr(h, "slo_classes", None):
            self.tenants = TenantScheduler(h.slo_classes)
            self._class_slo = {c.name: SloEngine(c.objectives,
                                                 service=c.name)
                               for c in h.slo_classes if c.objectives}
        # committed-timing jsonl for `zoo-serving trace <id>`
        self._request_log: Optional[_RequestLog] = None
        if getattr(h, "request_log", None):
            self._request_log = _RequestLog(h.request_log)
        # intake backlog sources, populated by _serve_pipelined (admission
        # reads live queue depths instead of guessing from counters)
        self._backlog_queues: List[queue.Queue] = []
        # generative serving (serving/generation.py): engine injected via
        # set_generate_engine or built from config; scheduler starts
        # lazily on the first generate record
        self._gen_engine = None
        self._gen_sched = None
        self._gen_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _default_model(self):
        """Model used when none is injected; the registry router
        overrides this (models come from the ModelRegistry instead)."""
        if getattr(self.helper, "stub_ms_per_batch", None) is not None:
            return EchoStubModel(self.helper.stub_ms_per_batch)
        if self.helper.model_path:
            return self.helper.load_inference_model()
        return None

    # -- record decode (the foreachBatch mapPartitions body) -----------
    def _decode_record(self, rec: dict) -> np.ndarray:
        if "image" in rec:
            import cv2

            raw = base64.b64decode(rec["image"])
            img = cv2.imdecode(np.frombuffer(raw, np.uint8),
                               cv2.IMREAD_COLOR)
            if img is None:
                raise ValueError(f"undecodable image for {rec.get('uri')}")
            c, h, w = self.helper.image_shape
            img = cv2.resize(img, (w, h)).astype(np.float32)
            if self.preprocessing is not None:
                img = self.preprocessing(img)
            return np.transpose(img, (2, 0, 1))  # NCHW like the reference
        tensors = rec["tensors"]
        arrays = [np.frombuffer(t["data"], np.float32).reshape(t["shape"])
                  for t in tensors.values()]
        out = arrays[0] if len(arrays) == 1 else arrays
        if self.preprocessing is not None and len(arrays) == 1:
            out = self.preprocessing(out)
        return out

    def _format_result(self, p: np.ndarray) -> dict:
        if self.helper.top_n and p.ndim == 1 and \
                p.shape[0] > self.helper.top_n:
            top = np.argsort(p)[::-1][:self.helper.top_n]
            return {"value": [[int(i), float(p[i])] for i in top]}
        return {"value": p.tolist()}

    def _count(self, **deltas):
        with self._ctr_lock:
            for name, d in deltas.items():
                setattr(self, name, getattr(self, name) + d)
        # every shed / dead letter is one bad event in the SLO stream
        # (served rows enter through _record_row_timing with a latency)
        if self.slo is not None:
            for _ in range(int(deltas.get("shed", 0))):
                self.slo.record(shed=True)
            for _ in range(int(deltas.get("dead_letters", 0))):
                self.slo.record(error=True)

    def pipeline_stats(self) -> dict:
        """Counters + per-stage percentiles + queue depths — the payload
        the bench leg, smoke entry, and tests assert on."""
        with self._ctr_lock:
            out = {"records_in": self.records_in,
                   "results_out": self.results_out,
                   "dropped": self.dropped,
                   "dead_letters": self.dead_letters,
                   "shed": self.shed,
                   "batches": self.batches,
                   "buckets": dict(self.bucket_counts)}
        out["admission"] = self.admission.stats()
        if self.slo is not None:
            out["slo"] = self.slo.status()
        if self.tenants is not None:
            out["tenants"] = self.tenants.stats()
        if self._class_slo:
            out["slo_classes"] = {n: e.status()
                                  for n, e in self._class_slo.items()}
        if self._gen_sched is not None:
            out["generation"] = self._gen_sched.stats()
        report = self.generate_load_report()
        if report is not None:
            out["routing"] = report
        if hasattr(self.db, "consumer_stats"):
            out["queue"] = self.db.consumer_stats()
        out.update(self.summary.snapshot())
        return out

    def generate_load_report(self, max_keys: int = 32) -> Optional[dict]:
        """Heartbeat payload section for the fleet router
        (serving/routing.py); None when this server has no generate
        engine configured.  Before the scheduler lazily starts, an
        all-free report advertises the configured capacity so routing
        works from the first request."""
        sched = self._gen_sched
        if sched is None:
            h = self.helper
            if self._gen_engine is None and \
                    getattr(h, "generate_stub_ms_per_step", None) is None:
                return None
            slots = max(int(getattr(h, "generate_slots", 4) or 4), 1)
            report = {"slots": slots, "active_slots": 0,
                      "free_slots": slots, "queue_depth": 0,
                      "queued_steps": 0, "prefix_keys": []}
        else:
            report = sched.load_report(max_keys=max_keys)
        with self._ctr_lock:
            report["routed_in"] = self.routed_in
            report["affinity_hits"] = self.affinity_hits
        return report

    # -- deadline admission + timing decomposition ----------------------
    def _meta_for(self, rid: str, rec: dict, t_in: float) -> RecordMeta:
        enq = rec.get("enqueue_ts_ms")
        deadline_ms = rec.get("deadline_ms", self.default_deadline_ms)
        deadline_at = None
        if deadline_ms is not None:
            # relative to the client stamp when present, else to arrival
            deadline_at = (enq if enq is not None else now_ms()) \
                + float(deadline_ms)
        trace_id = rec.get("trace_id") or rec.get(b"trace_id")
        if isinstance(trace_id, (bytes, bytearray)):
            trace_id = trace_id.decode()
        tenant = None
        if self.tenants is not None:
            model = rec.get("model") or rec.get(b"model")
            version = rec.get("version") or rec.get(b"version")
            if isinstance(model, (bytes, bytearray)):
                model = model.decode()
            if isinstance(version, (bytes, bytearray)):
                version = version.decode()
            tenant = self.tenants.classify(
                None if model is None else str(model),
                None if version is None else str(version))
        return RecordMeta(t_in, rec.get("uri", rid), enq,
                          rec.get("dequeue_ts_ms"), deadline_at, trace_id,
                          tenant)

    def _backlog(self) -> int:
        n = sum(q.qsize() for q in self._backlog_queues)
        if self.tenants is not None:
            n += self.tenants.queued_total()
        return n

    def _shed(self, metas: Sequence[RecordMeta], code: str):
        """Commit typed rejection payloads for records that cannot meet
        their deadline (clients decode these as ServingRejected)."""
        if not metas:
            return
        msg = {SHED_DEADLINE: "deadline unmeetable at admission",
               SHED_EXPIRED: "deadline expired in queue",
               SHED_CAPACITY: "shed by tenant policy under pressure",
               }.get(code, code)
        payload = {}
        for m in metas:
            payload[m.uri] = json.dumps(
                {"error": msg, "code": code}).encode()
            # typed shed tagged with the request's trace context, so a
            # rejected request still shows its (truncated) causal tree
            telemetry.event("serving/shed", code=code, uri=m.uri,
                            trace_id=m.trace_id)
            # a shed is one bad event in the tenant's own SLO stream too
            eng = self._class_slo.get(m.tenant) if m.tenant else None
            if eng is not None:
                eng.record(shed=True)
        self.db.put_results(payload)
        self._count(shed=len(metas))
        telemetry.counter("zoo_serving_shed_total", code=code).inc(len(metas))

    @staticmethod
    def _timing_payload(meta: RecordMeta, disp_ts_ms: float,
                        device_ms: float, done_ms: float) -> dict:
        """Per-row latency decomposition committed with the result:
        transport_in_ms (client enqueue → backend dequeue), queue_ms
        (dequeue → dispatch), device_ms (dispatch → host transfer done),
        server_ms (dequeue → result committed).  The client adds
        rtt_ms/transport_ms from its own receive stamp."""
        t = {"device_ms": round(device_ms, 3), "done_ts_ms": round(done_ms, 3),
             "uri": meta.uri}
        if meta.trace_id:
            t["trace_id"] = meta.trace_id
        if meta.tenant:
            t["tenant"] = meta.tenant
        if meta.enqueue_ts_ms is not None:
            t["enqueue_ts_ms"] = meta.enqueue_ts_ms
        if meta.dequeue_ts_ms is not None:
            t["dequeue_ts_ms"] = meta.dequeue_ts_ms
            t["queue_ms"] = round(max(disp_ts_ms - meta.dequeue_ts_ms,
                                      0.0), 3)
            t["server_ms"] = round(max(done_ms - meta.dequeue_ts_ms,
                                       0.0), 3)
            if meta.enqueue_ts_ms is not None:
                t["transport_in_ms"] = round(
                    max(meta.dequeue_ts_ms - meta.enqueue_ts_ms, 0.0), 3)
        return t

    def _record_row_timing(self, timing: dict):
        """Feed the decomposition into the summary so percentiles for
        the new stages ride the existing snapshot machinery — plus the
        SLO stream (one good/bad event per served row) and the
        committed-timing request log (`zoo-serving trace <id>`)."""
        self.summary.record_stage("device", timing["device_ms"] / 1e3)
        if "transport_in_ms" in timing:
            self.summary.record_stage("transport",
                                      timing["transport_in_ms"] / 1e3)
        if "queue_ms" in timing:
            self.summary.record_stage("queue_wait", timing["queue_ms"] / 1e3)
        if self.slo is not None or self._class_slo:
            if timing.get("enqueue_ts_ms") is not None:
                lat = timing["done_ts_ms"] - timing["enqueue_ts_ms"]
            else:
                lat = timing.get("server_ms", timing["device_ms"])
            if self.slo is not None:
                self.slo.record(latency_ms=lat)
            eng = self._class_slo.get(timing.get("tenant"))
            if eng is not None:
                eng.record(latency_ms=lat)
        if self._request_log is not None:
            self._request_log.append(dict(timing, kind="predict"))

    # ------------------------------------------------------------------
    # generative serving (docs/serving-generate.md)
    # ------------------------------------------------------------------
    def set_generate_engine(self, engine):
        """Inject a gang-decode engine (TransformerDecodeEngine or any
        object with the alloc/grow/join/step/evict protocol) before the
        first generate record arrives."""
        self._gen_engine = engine
        return self

    def build_transformer_engine(self, layer, params, max_len=None):
        """Construct and inject a ``TransformerDecodeEngine`` honouring
        the ``generate`` config block: ``kv_cache: int8`` selects
        ``Int8KVSlab`` storage, ``prefix_cache_mb`` attaches a
        shared-prefix cache, ``speculative.k`` is NOT applied here (a
        device draft model must be paired explicitly — wrap with
        ``SpeculativeDecodeEngine`` before injecting)."""
        from .generation import TransformerDecodeEngine

        kv = str(getattr(self.helper, "generate_kv_dtype", "f32")).lower()
        engine = TransformerDecodeEngine(
            layer, params,
            max_len=max_len or getattr(self.helper, "generate_max_len",
                                       None),
            kv_dtype="int8" if kv == "int8" else None,
            prefix_cache=self._prefix_cache())
        return self.set_generate_engine(engine)

    def _prefix_cache(self):
        mb = float(getattr(self.helper, "generate_prefix_cache_mb", 0))
        if mb <= 0:
            return None
        from .generation import PrefixCache

        return PrefixCache(max_bytes=int(mb * (1 << 20)))

    def _generate_engine(self):
        if self._gen_engine is None and \
                getattr(self.helper, "generate_stub_ms_per_step",
                        None) is not None:
            from .generation import StubDecodeEngine
            from ..ops.kv_cache import cache_length_buckets

            buckets = cache_length_buckets(self.helper.generate_max_len)
            self._gen_engine = StubDecodeEngine(
                ms_per_step=self.helper.generate_stub_ms_per_step,
                stop_id=self.helper.generate_stop_id or 0,
                capacity_buckets=buckets,
                prefix_cache=self._prefix_cache())
            k = int(getattr(self.helper, "generate_speculative_k", 0))
            if k > 0:
                from .generation import SpeculativeDecodeEngine

                draft_ms = getattr(self.helper,
                                   "generate_draft_ms_per_step", None)
                if draft_ms is None:
                    draft_ms = self.helper.generate_stub_ms_per_step / 10.0
                draft = StubDecodeEngine(
                    ms_per_step=draft_ms,
                    stop_id=self.helper.generate_stop_id or 0,
                    capacity_buckets=buckets)
                self._gen_engine = SpeculativeDecodeEngine(
                    self._gen_engine, draft, k=k)
        return self._gen_engine

    def _gen_scheduler(self):
        """The continuous-batching scheduler, started on first use (its
        loop thread only exists when the workload includes generation)."""
        with self._gen_lock:
            if self._gen_sched is None:
                engine = self._generate_engine()
                if engine is None:
                    return None
                from .generation import ContinuousBatchScheduler

                slots = int(getattr(self.helper, "generate_slots", 4))
                batcher = AdaptiveBatcher(
                    power_of_two_buckets(slots), self.admission,
                    linger_ms=float(getattr(self.helper, "linger_ms", 0.0)))
                self._gen_sched = ContinuousBatchScheduler(
                    engine, commit=self._gen_commit, max_slots=slots,
                    continuous=bool(getattr(self.helper,
                                            "generate_continuous", True)),
                    admission=self.admission, batcher=batcher,
                    prefill_chunk=int(getattr(
                        self.helper, "generate_prefill_chunk", 0))).start()
            return self._gen_sched

    def _gen_commit(self, uri: str, payload: dict):
        """Scheduler results land in the same results map as
        predictions; sequences finish at different steps, so each commit
        is a single-uri write the moment its sequence evicts."""
        timing = payload.get("timing") or {}
        if "error" in payload:
            self._count(shed=1)
            if self.slo is not None:
                self.slo.record(shed=True)
        else:
            self._count(results_out=1)
            if self.slo is not None:
                lat = timing.get("server_ms")
                if timing.get("enqueue_ts_ms") is not None and \
                        timing.get("done_ts_ms") is not None:
                    lat = timing["done_ts_ms"] - timing["enqueue_ts_ms"]
                self.slo.record(latency_ms=lat)
        if self._request_log is not None:
            row = dict(timing, kind="generate", uri=uri)
            if "error" in payload:
                row["error"] = payload.get("code") or payload["error"]
            self._request_log.append(row)
        self.db.put_results({uri: json.dumps(payload).encode()})

    def _maybe_generate(self, rid: str, rec: dict,
                        t_in: float) -> bool:
        """Divert a generate record to the continuous-batching
        scheduler; True when the record was one (handled), False when
        it belongs to the predict pipeline."""
        gen = rec.get("generate") or rec.get(b"generate")
        if gen is None:
            return False
        meta = self._meta_for(rid, rec, t_in)
        if meta.trace_id:
            # step the client's flow arrow at the intake hop; the
            # scheduler's prefill span finishes it (same trace_id)
            telemetry.flow("serving/request", meta.trace_id, "t")
            telemetry.event("generate/intake", uri=meta.uri,
                            trace_id=meta.trace_id)
        if isinstance(gen, (bytes, bytearray)):
            # redis transports msgpack non-scalar fields
            import msgpack

            gen = msgpack.unpackb(gen, raw=False)
        sched = self._gen_scheduler()
        if sched is None:
            self.db.put_results({meta.uri: json.dumps(
                {"error": "no generate engine configured",
                 "code": "no_engine"}).encode()})
            self._count(dead_letters=1)
            return True
        from .generation import GenRequest

        prompt = np.asarray(gen.get("prompt") or [], np.int64)
        routed_to = rec.get("routed_to", rec.get(b"routed_to"))
        if routed_to is not None:
            # router placed this record on our substream; count whether
            # the affinity bet paid off (warm membership probe only —
            # the real hit/miss counters move in the engine's lookup)
            pc = sched._engine_prefix_cache()
            warm = bool(pc is not None and pc.contains(prompt))
            self._count(routed_in=1, affinity_hits=1 if warm else 0)
            telemetry.counter("zoo_route_landed_total").inc()
            if warm:
                telemetry.counter("zoo_route_landed_warm_total").inc()
        stop_id = gen.get("stop_id")
        if stop_id is None:
            stop_id = getattr(self.helper, "generate_stop_id", None)
        sched.submit(GenRequest(
            uri=meta.uri,
            prompt=prompt,
            max_new_tokens=int(gen.get("max_new_tokens") or
                               getattr(self.helper,
                                       "generate_max_new_tokens", 32)),
            stop_id=None if stop_id is None else int(stop_id),
            temperature=float(gen.get("temperature") or 0.0),
            deadline_at_ms=meta.deadline_at_ms,
            enqueue_ts_ms=meta.enqueue_ts_ms,
            t_in=t_in,
            trace_id=meta.trace_id))
        return True

    # ------------------------------------------------------------------
    # synchronous loop (the pre-pipeline baseline, pipelined=False)
    # ------------------------------------------------------------------
    def _process_batch(self, items, t_in: Optional[float] = None):
        # never trust a StreamQueue backend to cap read_batch: chunk
        # oversized reads instead of compiling a giant signature
        bs = self.helper.batch_size
        for i in range(0, len(items), bs):
            self._process_chunk(items[i:i + bs], t_in)

    def _process_chunk(self, items, t_in: Optional[float] = None):
        metas, arrays = [], []
        for rid, rec in items:
            if self._maybe_generate(rid, rec,
                                    t_in or time.perf_counter()):
                continue
            try:
                meta = self._meta_for(rid, rec,
                                      t_in or time.perf_counter())
                with span("serving/decode", trace_id=meta.trace_id,
                          uri=meta.uri):
                    if meta.trace_id:
                        telemetry.flow("serving/request", meta.trace_id, "f")
                    arrays.append(self._decode_record(rec))
                metas.append(meta)
            except Exception as e:  # bad record: report, keep serving
                logger.warning("skipping record %s: %s", rid, e)
                self._count(dropped=1)
        if not arrays:
            return
        n = len(arrays)
        batch = np.stack(arrays)
        # pad to the configured batch size: one AOT signature on the MXU
        # (skipped when the batch is exactly full)
        if n < self.helper.batch_size:
            pad = np.repeat(batch[-1:], self.helper.batch_size - n, axis=0)
            batch = np.concatenate([batch, pad])
        disp_ts_ms = now_ms()
        t0 = time.perf_counter()
        preds = np.asarray(self.model.predict(batch))[:n]
        dt = time.perf_counter() - t0
        self.summary.record_batch(n, dt)
        self.admission.observe_batch(n, dt)
        self._count(batches=1, records_in=n)
        self.bucket_counts[batch.shape[0]] += 1
        done_ms = now_ms()
        results = {}
        for meta, p in zip(metas, preds):
            obj = self._format_result(p)
            obj["timing"] = self._timing_payload(
                meta, disp_ts_ms, dt * 1e3, done_ms)
            self._record_row_timing(obj["timing"])
            results[meta.uri] = json.dumps(obj).encode()
        self.db.put_results(results)
        self._count(results_out=n)
        if t_in is not None:
            now = time.perf_counter()
            for _ in range(n):
                self.summary.record_stage("e2e", now - t_in)

    def _serve_sync(self, poll_timeout: float = 0.5):
        while not self._stop.is_set():
            items = self.db.read_batch(self.helper.batch_size,
                                       timeout=poll_timeout)
            if items:
                self._process_batch(items, t_in=time.perf_counter())
            # watermark trim (ClusterServing.scala:130-136)
            if self.db.stream_len() > self.helper.stream_maxlen:
                self.db.trim(int(self.helper.stream_maxlen * 0.6 * 0.8))

    # ------------------------------------------------------------------
    # pipelined loop (decode pool -> bucketed async compute -> writer)
    # ------------------------------------------------------------------
    def _ready_item(self, meta: RecordMeta, rec: dict, arr):
        """Tuple pushed onto the ready queue for one decoded record; the
        registry router appends the record's routing fields."""
        return (meta, arr)

    def _on_decode_error(self, rid: str, rec: dict, exc: Exception):
        """Undecodable record; the router dead-letters instead."""
        logger.warning("skipping record %s: %s", rid, exc)
        self._count(dropped=1)

    def _decode_worker(self, decode_in: queue.Queue, ready: queue.Queue):
        while True:
            item = decode_in.get()
            if item is _SENTINEL:
                return
            meta, rid, rec = item
            t0 = time.perf_counter()
            try:
                with span("serving/decode", trace_id=meta.trace_id,
                          uri=meta.uri):
                    if meta.trace_id:
                        # bind the client's flow arrow to this slice
                        telemetry.flow("serving/request", meta.trace_id, "f")
                    arr = self._decode_record(rec)
            except Exception as e:  # bad record: report, keep serving
                self._on_decode_error(rid, rec, e)
                continue
            self.summary.record_stage("decode", time.perf_counter() - t0)
            ready.put(self._ready_item(meta, rec, arr))

    @staticmethod
    def _oldest_deadline(batch_items) -> Optional[float]:
        deadlines = [it[0].deadline_at_ms for it in batch_items
                     if it[0].deadline_at_ms is not None]
        return min(deadlines) if deadlines else None

    def _compute_loop(self, ready: queue.Queue, write_q: queue.Queue):
        bs = self.helper.batch_size
        while True:
            item = ready.get()
            if item is _SENTINEL:
                return
            batch_items, saw_sentinel = [item], False
            # greedy assembly: take whatever is already decoded, up to
            # batch_size; with a linger budget (params.linger_ms) the
            # assembler may additionally block a bounded moment to round
            # a partial batch up to the next padding bucket — never past
            # the oldest queued record's deadline slack
            while len(batch_items) < bs:
                try:
                    nxt = ready.get_nowait()
                except queue.Empty:
                    budget = self.batcher.linger_budget_s(
                        len(batch_items),
                        self._oldest_deadline(batch_items))
                    if budget <= 0.0:
                        break
                    telemetry.event("serving/linger", n=len(batch_items),
                                    budget_ms=round(budget * 1e3, 3))
                    try:
                        with span("serving/linger_wait", n=len(batch_items)):
                            nxt = ready.get(timeout=budget)
                    except queue.Empty:
                        break
                if nxt is _SENTINEL:
                    saw_sentinel = True
                    break
                batch_items.append(nxt)
            self._dispatch_batch(batch_items, write_q)
            if saw_sentinel:
                return

    def _dispatch_batch(self, batch_items, write_q: queue.Queue):
        # second shed point: a record whose deadline expired while it
        # sat decoded in the ready queue gets a typed rejection instead
        # of a batch slot nobody is waiting on
        at = now_ms()
        live, expired = [], []
        for it in batch_items:
            if self.admission.expired(it[0].deadline_at_ms, at):
                expired.append(it[0])
            else:
                live.append(it)
        self._shed(expired, SHED_EXPIRED)
        if not live:
            return
        metas = [it[0] for it in live]
        arrays = [it[1] for it in live]
        n = len(arrays)
        bucket = pick_bucket(n, self.buckets)
        trace_ids = [m.trace_id for m in metas if m.trace_id]
        try:
            with span("serving/dispatch", n=n, bucket=bucket,
                      trace_ids=trace_ids):
                batch = np.stack(arrays)
                if n < bucket:
                    pad = np.repeat(batch[-1:], bucket - n, axis=0)
                    batch = np.concatenate([batch, pad])
                disp_ts_ms = now_ms()
                t0 = time.perf_counter()
                # async dispatch: don't block on the host transfer of
                # batch k before submitting k+1 — the writer stage
                # synchronizes
                out = self.model.predict_async(batch)
        except Exception as e:
            logger.warning("dropping batch of %d (%s)", n, e)
            self._count(dropped=n)
            return
        self.summary.record_stage("dispatch", time.perf_counter() - t0)
        self._count(batches=1)
        with self._ctr_lock:
            self.bucket_counts[bucket] += 1
        write_q.put((metas, n, t0, disp_ts_ms, out))

    def _writer_loop(self, write_q: queue.Queue):
        while True:
            item = write_q.get()
            if item is _SENTINEL:
                return
            metas, n, t_disp, disp_ts_ms, out = item
            trace_ids = [m.trace_id for m in metas if m.trace_id]
            try:
                with span("serving/device_sync", n=n, trace_ids=trace_ids):
                    preds = np.asarray(out)[:n]  # host transfer sync point
            except Exception as e:
                logger.warning("dropping results for %d records (%s)",
                               n, e)
                self._count(dropped=n)
                continue
            dt = time.perf_counter() - t_disp
            self.summary.record_batch(n, dt)   # Throughput/LatencyMs parity
            self.summary.record_stage("compute", dt, batch_size=n)
            # feed the admission controller's service-time estimates
            self.admission.observe_batch(n, dt)
            done_ms = now_ms()
            t0 = time.perf_counter()
            with span("serving/write", n=n, trace_ids=trace_ids):
                results = {}
                for meta, p in zip(metas, preds):
                    obj = self._format_result(p)
                    obj["timing"] = self._timing_payload(
                        meta, disp_ts_ms, dt * 1e3, done_ms)
                    self._record_row_timing(obj["timing"])
                    results[meta.uri] = json.dumps(obj).encode()
                self.db.put_results(results)
            now = time.perf_counter()
            self.summary.record_stage("write", now - t0, batch_size=n)
            for meta in metas:
                self.summary.record_stage("e2e", now - meta.t_in)
            self._count(results_out=n)

    def _serve_pipelined(self, poll_timeout: float = 0.5):
        decode_in: queue.Queue = queue.Queue(self.queue_depth)
        ready: queue.Queue = queue.Queue(self.queue_depth)
        write_q: queue.Queue = queue.Queue(self.queue_depth)
        self._backlog_queues = [decode_in, ready]
        decoders = [threading.Thread(target=self._decode_worker,
                                     args=(decode_in, ready), daemon=True,
                                     name=f"serving-decode-{i}")
                    for i in range(self.decode_workers)]
        compute = threading.Thread(target=self._compute_loop,
                                   args=(ready, write_q), daemon=True,
                                   name="serving-compute")
        writer = threading.Thread(target=self._writer_loop,
                                  args=(write_q,), daemon=True,
                                  name="serving-write")
        for t in decoders + [compute, writer]:
            t.start()
        try:
            while not self._stop.is_set():
                # bound the per-tenant staging queues: past the cap, stop
                # pulling from the stream (it has its own watermark trim)
                # and let the pressure sheds / drain catch up
                if (self.tenants is not None and
                        self.tenants.queued_total() >= 4 * self.queue_depth):
                    items = []
                    time.sleep(min(poll_timeout, 0.05))
                else:
                    items = self.db.read_batch(self.helper.batch_size,
                                               timeout=poll_timeout)
                if items:
                    now = time.perf_counter()
                    for rid, rec in items:
                        # generate records divert to the continuous-
                        # batching scheduler (their admission happens at
                        # slot-refill time, with the per-token estimate)
                        if self._maybe_generate(rid, rec, now):
                            continue
                        meta = self._meta_for(rid, rec, now)
                        # first shed point: admission control against the
                        # measured service time + live backlog
                        if meta.deadline_at_ms is not None:
                            slack = meta.deadline_at_ms - now_ms()
                            ok, code = self.admission.admit(
                                slack, self._backlog())
                            if not ok:
                                self._shed([meta], code)
                                continue
                        if self.tenants is not None:
                            # stage per tenant; the DRR drain below picks
                            # the weighted-fair order into the pipeline
                            self.tenants.offer(meta.tenant,
                                               (meta, rid, rec))
                        else:
                            decode_in.put((meta, rid, rec))  # backpressure
                    self._count(records_in=len(items))
                if self.tenants is not None:
                    # second shed point: capacity policy — the least
                    # important class gives up its oldest queued records
                    # while any class's predicted wait overruns its bound
                    pipe_backlog = sum(q.qsize()
                                       for q in self._backlog_queues)
                    victims = self.tenants.shed_under_pressure(
                        self.admission, pipe_backlog)
                    if victims:
                        self._shed([item[0] for _t, item in victims],
                                   SHED_CAPACITY)
                    for item in self.tenants.drain(self.queue_depth):
                        decode_in.put(item)  # backpressure here
                if items or self.tenants is not None:
                    self.summary.record_queue_depth("decode",
                                                    decode_in.qsize())
                    self.summary.record_queue_depth("ready", ready.qsize())
                    self.summary.record_queue_depth("write", write_q.qsize())
                # watermark trim (ClusterServing.scala:130-136)
                if self.db.stream_len() > self.helper.stream_maxlen:
                    self.db.trim(int(self.helper.stream_maxlen * 0.6 * 0.8))
        finally:
            # orderly drain: each stage fully flushes before the next
            # stage sees its sentinel, so no in-flight record is lost
            if self.tenants is not None:
                for item in self.tenants.drain(1 << 30):
                    decode_in.put(item)
            for _ in decoders:
                decode_in.put(_SENTINEL)
            for t in decoders:
                t.join()
            ready.put(_SENTINEL)
            compute.join()
            write_q.put(_SENTINEL)
            writer.join()

    # ------------------------------------------------------------------
    def warmup(self, shape: Optional[Sequence[int]] = None) -> dict:
        """Pre-compile every padding bucket's AOT signature before the
        loop accepts traffic.  ``shape`` is the per-record tensor shape
        (defaults to the configured ``image_shape``).  Returns
        {bucket: seconds}; failures are logged and skipped (foreign
        backends may reject the synthetic input)."""
        shape = tuple(shape if shape is not None else
                      self.helper.image_shape)
        times = {}
        for b in self.buckets:
            try:
                times.update(self.model.warm(shape, [b]))
            except Exception as e:  # noqa: BLE001 - warmup is best-effort
                logger.warning("warmup: bucket %d failed: %s", b, e)
                continue
            logger.info("warmup: bucket %d compiled in %.3fs", b, times[b])
        return times

    def _stats_dump_loop(self, interval: float = 2.0):
        """Periodically snapshot pipeline_stats() to ``stats_path`` (atomic
        rename) so `zoo-serving status` can report live percentiles from
        outside the process — and, when SLO objectives are armed, run one
        burn-rate evaluation pass per tick (gauges + edge-triggered
        alerts; utils/slo.py)."""
        from ..utils import file_io

        while True:
            engines = ([self.slo] if self.slo is not None else []) \
                + list(self._class_slo.values())
            for eng in engines:
                try:
                    eng.evaluate()
                except Exception as e:  # noqa: BLE001 - observability only
                    logger.debug("slo evaluate failed: %s", e)
            if self.stats_path:
                try:
                    file_io.write_bytes_atomic(
                        self.stats_path,
                        json.dumps(self.pipeline_stats()).encode())
                except Exception as e:  # noqa: BLE001 - observability only
                    logger.debug("stats dump failed: %s", e)
            if self._stop.wait(interval):
                return

    def serve_forever(self, poll_timeout: float = 0.5):
        logger.info("cluster serving started (batch=%d, %s, buckets=%s)",
                    self.helper.batch_size,
                    "pipelined" if self.pipelined else "synchronous",
                    self.buckets if self.pipelined else "n/a")
        if self.stats_path or self.slo is not None or self._class_slo:
            threading.Thread(target=self._stats_dump_loop, daemon=True,
                             name="serving-stats").start()
        if self.pipelined:
            self._serve_pipelined(poll_timeout)
        else:
            self._serve_sync(poll_timeout)
        # drain the generation gang last: in-flight sequences finish (or
        # shed) and every submitted request gets exactly one result
        with self._gen_lock:
            sched = self._gen_sched
        if sched is not None:
            sched.stop(drain=True, timeout=30)

    def start(self):
        self._stop.clear()
        self._thread = threading.Thread(target=self.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if self._request_log is not None:
            self._request_log.close()
