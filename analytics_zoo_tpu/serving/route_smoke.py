"""Fleet-routing chaos smoke (``scripts/route-smoke``; CI fast tier).

Brings up the routed generative fleet's production shape — a 2-worker
:class:`ServingFleet` over the file transport with the stub decode
engine and a prefix cache per worker, a :class:`RoutedGenerateQueue`
producer placing requests by load report — and asserts the PR's
contract (docs/serving-generate.md#fleet-routing):

- **affinity**: a repeat prompt routes to the worker whose heartbeat
  digest shows its prefix warm, lands there (`routed_to` accounting),
  and the decision is flagged ``affinity``;
- **skewed mix**: a 3:1 short/long + repeat-prompt burst is placed by
  cost (every record gets a routing decision once reports are fresh);
- **SIGKILL redelivery**: one worker is SIGKILLed mid-burst; the
  supervisor restarts it, unclaimed substream records are swept back
  to the shared stream, claimed-but-uncommitted ones are re-driven
  from the producer's pending ledger — every uri ends with exactly one
  result carrying *its own* token stream, and nothing re-appears after
  settle (zero lost, zero duplicated);
- **status**: the fleet-level ``generate:`` line and per-worker
  ``route worker-N`` rows render from the heartbeat reports.

Exit 0 on success, 1 on any violated assertion.
"""

from __future__ import annotations

import argparse
import contextlib
import io
import json
import os
import random
import shutil
import signal
import sys
import tempfile
import threading
import time

CONFIG_TMPL = """\
model:
  stub_ms_per_batch: 1.0

data:
  src: file:{stream_dir}
  image_shape: 3, 4, 4

params:
  batch_size: 4
  top_n: 0
  workers: 2
  health_interval: 0.25
  health_timeout: {health_timeout}

generate:
  slots: 4
  stub_ms_per_step: {stub_ms}
  max_new_tokens: 8
  prefix_cache_mb: 8
"""

WARM_PROMPT = [100, 0, 7, 7, 7, 7]


def _prompt_for(i: int, rng: random.Random):
    """Skewed 3:1 short/long mix with ~30% repeats of the warm prompt.
    Second token 0 keeps the stub's scripted stop disabled."""
    if rng.random() < 0.30:
        return WARM_PROMPT, 8
    if rng.random() < 0.75:
        return [200 + i, 0], 4            # short
    return [200 + i, 0, 1, 1, 1, 1], 32   # long


def run_smoke(records: int = 24, stub_ms: float = 2.0,
              health_timeout: float = 3.0, stream=None) -> int:
    import numpy as np

    from .client import OutputQueue
    from .fleet import ServingFleet, read_health
    from .generation import prompt_key
    from .queue_backend import FileStreamQueue
    from .routing import RoutedGenerateQueue, load_reports

    out = stream if stream is not None else sys.stdout
    workdir = tempfile.mkdtemp(prefix="zoo_route_smoke_")
    stream_dir = os.path.join(workdir, "stream")
    cfg = os.path.join(workdir, "config.yaml")
    with open(cfg, "w") as f:
        f.write(CONFIG_TMPL.format(stream_dir=stream_dir,
                                   stub_ms=stub_ms,
                                   health_timeout=health_timeout))
    cap = io.StringIO()

    def fail(msg):
        out.write(cap.getvalue())
        out.write(f"ROUTE_SMOKE_FAIL: {msg}\n")
        return 1

    fleet = ServingFleet(cfg, workdir, stream=cap,
                         env={"JAX_PLATFORMS": "cpu"})
    sup = threading.Thread(target=fleet.supervise, daemon=True)
    results = {}

    def drain(q):
        for uri, raw in q.db.all_results(pop=True).items():
            try:
                payload = json.loads(raw.decode())
            except ValueError:
                payload = {"error": "undecodable"}
            if uri in results:
                return fail(f"{uri} answered twice")
            results[uri] = payload
        return None

    try:
        fleet.start()
        sup.start()
        if not fleet.wait_healthy(timeout=90.0):
            return fail("workers never became healthy")
        routed = RoutedGenerateQueue(workdir, src=f"file:{stream_dir}")
        out_q = OutputQueue(backend=FileStreamQueue(stream_dir))

        # -- phase 1: warm a prefix, then assert affinity routing ------
        warm_key = prompt_key(np.asarray(WARM_PROMPT, np.int64))
        _rid, d0 = routed.enqueue_routed(
            {"uri": "warm-0",
             "generate": {"prompt": WARM_PROMPT, "max_new_tokens": 8}})
        if d0 is None:
            return fail("no routing decision despite fresh heartbeats")
        deadline = time.time() + 60.0
        holder = None
        while time.time() < deadline and holder is None:
            rc = drain(out_q)
            if rc is not None:
                return rc
            for wid, rep in load_reports(workdir).items():
                if rep.holds_prefix(warm_key):
                    holder = wid
            time.sleep(0.2)
        if holder is None:
            return fail("warm prefix never appeared in a heartbeat digest")
        _rid, d1 = routed.enqueue_routed(
            {"uri": "warm-1",
             "generate": {"prompt": WARM_PROMPT, "max_new_tokens": 8}})
        if d1 is None or not d1.affinity or d1.worker_id != holder:
            return fail(f"repeat prompt not affinity-routed to "
                        f"worker-{holder} (got {d1})")

        # -- phase 2: skewed burst, SIGKILL mid-burst, exactly-once ----
        rng = random.Random(0)
        expected = {}
        victim = 0
        h0 = read_health(workdir, victim)
        if not h0:
            return fail(f"no heartbeat for worker-{victim}")
        for i in range(records):
            prompt, steps = _prompt_for(i, rng)
            uri = f"mix-{i}"
            expected[uri] = prompt[0] + 1       # stub: token 1 = p[0]+1
            routed.enqueue_routed(
                {"uri": uri, "generate": {"prompt": list(prompt),
                                          "max_new_tokens": steps}})
            if i == records // 2:
                os.kill(int(h0["pid"]), signal.SIGKILL)
        expected["warm-0"] = WARM_PROMPT[0] + 1
        expected["warm-1"] = WARM_PROMPT[0] + 1
        deadline = time.time() + 120.0
        while len(results) < len(expected) and time.time() < deadline:
            rc = drain(out_q)
            if rc is not None:
                return rc
            missing = [u for u in expected if u not in results]
            if missing:
                # unclaimed substream records of the dead worker go
                # back to the shared stream; claimed-but-uncommitted
                # ones are re-driven under their original rid
                routed.sweep_worker(victim)
                routed.reenqueue_missing(missing)
                time.sleep(0.3)
        if len(results) < len(expected):
            missing = sorted(u for u in expected if u not in results)
            return fail(f"lost {len(missing)} result(s) after SIGKILL: "
                        f"{missing[:6]}")
        for uri, want in expected.items():
            payload = results[uri]
            toks = payload.get("tokens")
            if "error" in payload or not toks:
                return fail(f"{uri} errored: {payload}")
            if int(toks[0]) != want:
                return fail(f"{uri} first token {toks[0]} != {want} "
                            f"(cross-wired streams)")
        time.sleep(1.0)          # settle: late duplicates would land now
        late = out_q.db.all_results(pop=True)
        if late:
            return fail(f"duplicated results after settle: "
                        f"{sorted(late)[:6]}")
        if fleet.restarts.get(victim, 0) < 1:
            return fail(f"supervisor never restarted worker-{victim}")
        rstats = routed.stats()
        if rstats["router"]["affinity"] < 1:
            return fail("no affinity decision over a 30%-repeat mix")
        if rstats["routed"] < records // 2:
            return fail(f"only {rstats['routed']} routed placements "
                        f"over {records} records")

        # -- status rendering ------------------------------------------
        from . import cli

        scap = io.StringIO()
        with contextlib.redirect_stdout(scap):
            cli._print_fleet_generation(cli._read_stats_files(workdir))
            cli._print_routing_rows(workdir)
        status = scap.getvalue()
        if "route worker-" not in status:
            return fail(f"status is missing routing rows:\n{status}")
        out.write(f"ROUTE_SMOKE_OK records={len(expected)} "
                  f"routed={rstats['routed']} "
                  f"affinity={rstats['router']['affinity']} "
                  f"swept={rstats['swept']} "
                  f"reenqueued={rstats['reenqueued']} "
                  f"restarts={fleet.restarts.get(victim, 0)}\n")
        return 0
    finally:
        fleet.stop()
        fleet.shutdown()
        shutil.rmtree(workdir, ignore_errors=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="route-smoke")
    ap.add_argument("--records", type=int, default=24)
    ap.add_argument("--stub-ms", type=float, default=2.0)
    args = ap.parse_args(argv)
    return run_smoke(records=args.records, stub_ms=args.stub_ms)


if __name__ == "__main__":
    sys.exit(main())
