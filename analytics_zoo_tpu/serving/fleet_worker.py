"""One serving-fleet worker process (spawned by serving/fleet.py).

Runs the standard pipelined serve loop against the shared transport,
plus the fleet-specific plumbing (docs/serving-fleet.md):

- **heartbeat**: a daemon thread writes ``health/worker-N.json`` every
  ``params.health_interval`` seconds with pid, records served, and shed
  count — the fleet manager's liveness signal and `zoo-serving status`'s
  data source;
- **registry sharing**: worker 0 owns the file-RPC control plane (and
  manifest writes); workers >0 watch the manifest's mtime and
  ``recover(save=False)`` on change, so a deploy/promote through worker
  0 reaches every replica without cross-process RPC;
- **teardown**: SIGTERM/SIGINT set the serve loop's stop event — the
  pipeline drains in order (no in-flight record is lost) before exit.

Usage (normally via ServingFleet, runnable standalone for debugging)::

    python -m analytics_zoo_tpu.serving.fleet_worker \
        --config config.yaml --workdir /tmp/fleet --worker-id 0
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import sys
import threading
import time

from ..utils import telemetry
from .fleet import HEALTH_DIR, write_health

logger = logging.getLogger("analytics_zoo_tpu.serving.fleet_worker")


def _build_serving(cfg: str, workdir: str, worker_id: int):
    """Worker-side twin of cli._build_serving: per-worker stats path,
    control-plane ownership only on worker 0, manifest following on the
    rest."""
    from .cluster_serving import ClusterServing, ClusterServingHelper

    helper = ClusterServingHelper(config_path=cfg)
    helper.stats_path = os.path.join(workdir,
                                     f"stats-worker-{worker_id}.json")
    if not helper.request_log and (helper.telemetry or telemetry.enabled()):
        # committed timings per worker — `zoo-serving trace <id>` scans
        # every requests*.jsonl under the workdir for the waterfall
        helper.request_log = os.path.join(
            workdir, f"requests-worker-{worker_id}.jsonl")
    # file transports get routed-placement intake: drain our private
    # generate substream first, then the shared any-claim stream
    # (serving/routing.py; a fleet with no router sees an empty
    # substream and behaves exactly as before)
    backend = None
    root = None
    src = helper.src or ""
    if src.startswith("file:"):
        from .routing import WorkerIntakeQueue

        root = src[len("file:"):]
        backend = WorkerIntakeQueue(root, worker_id)
    if not helper.registry_root:
        return ClusterServing(helper=helper, backend=backend), None
    from .registry import ModelRegistry, RegistryControlServer
    from .router import RoutedClusterServing

    registry = ModelRegistry(
        root=helper.registry_root,
        default_model=helper.default_model,
        canary_error_threshold=helper.canary_error_threshold,
        canary_min_requests=helper.canary_min_requests)
    serving = RoutedClusterServing(registry, helper=helper,
                                   backend=backend)
    registry.recover(load=True, warmup=serving.registry_warmup(),
                     save=worker_id == 0)
    ctl = None
    if worker_id == 0:
        if helper.model_path and not registry.routed_versions():
            serving.deploy(path=helper.model_path)
        ctl = RegistryControlServer(registry, helper.registry_root,
                                    serving=serving).start()
    return serving, ctl


def _watch_manifest(serving, stop: threading.Event, interval: float = 1.0):
    """Followers poll the shared manifest's mtime; on change, re-recover
    (idempotent over loaded versions, never writes the manifest)."""
    registry = serving.registry
    uri = registry.manifest_uri
    last = None
    while not stop.wait(interval):
        try:
            mtime = os.path.getmtime(uri)
        except OSError:
            continue
        if last is not None and mtime != last:
            try:
                registry.recover(load=True,
                                 warmup=serving.registry_warmup(),
                                 save=False)
                logger.info("manifest change picked up")
            except Exception as e:  # noqa: BLE001 - keep serving
                logger.warning("manifest refresh failed: %s", e)
        last = mtime


def _heartbeat(serving, workdir: str, worker_id: int,
               stop: threading.Event, interval: float, restarts: int):
    started = time.time()
    while True:
        with serving._ctr_lock:
            served, shed = serving.results_out, serving.shed
        payload = {
            "pid": os.getpid(),
            "started_at": started,
            "records_served": served,
            "shed": shed,
            "restarts": restarts,
            # EWMA service estimates ride the heartbeat so the
            # supervisor's backlog autoscaler can predict queue wait
            # without RPC into the worker (docs/serving-network.md)
            "admission": serving.admission.stats(),
        }
        try:
            # routing load report (free slots, queued decode steps,
            # prefix-key digest) rides the same heartbeat — the fleet
            # router's only data source (serving/routing.py)
            report = serving.generate_load_report()
        except Exception:  # noqa: BLE001 - never kill the heartbeat
            report = None
        if report is not None:
            payload["routing"] = report
        dump = getattr(serving, "_flight_dump_path", None)
        if dump:
            payload["flight_dump"] = dump
        write_health(workdir, worker_id, payload)
        if stop.wait(interval):
            return


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="zoo-serving-fleet-worker")
    ap.add_argument("--config", required=True)
    ap.add_argument("--workdir", required=True)
    ap.add_argument("--worker-id", type=int, required=True)
    args = ap.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format=f"%(asctime)s worker-{args.worker_id} %(message)s")
    # honor JAX_PLATFORMS even when a TPU plugin is registered (the env
    # var alone is ignored then; the config update is authoritative)
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        try:
            import jax
            jax.config.update("jax_platforms", plat)
        except Exception:  # noqa: BLE001 - serving may not need jax yet
            pass
    workdir = os.path.abspath(args.workdir)
    os.makedirs(os.path.join(workdir, HEALTH_DIR), exist_ok=True)
    serving, _ctl = _build_serving(args.config, workdir, args.worker_id)
    if serving.helper.telemetry or telemetry.enabled():
        # per-worker metrics snapshots land next to the stats dumps so
        # the supervisor (worker 0's host) can merge a fleet view
        telemetry.configure(enabled=True,
                            trace_dir=serving.helper.trace_dir,
                            service=f"serving-worker-{args.worker_id}",
                            export_metrics=False)
        telemetry.start_metrics_exporter(os.path.join(
            workdir, f"metrics-worker-{args.worker_id}.json"))
    if serving.helper.warmup:
        serving.warmup()
    stop = threading.Event()
    restarts = int(os.environ.get("ZOO_SERVING_WORKER_RESTARTS", "0"))

    def _term(sig, _frm):
        telemetry.event("serving/drain", signal=sig,
                        worker=args.worker_id)
        dump = telemetry.dump_flight(
            f"serving worker {args.worker_id} draining on signal {sig}")
        if dump:
            # stamp the post-mortem path into the heartbeat file so
            # `zoo-serving status` can point an operator straight at it
            serving._flight_dump_path = dump
            with serving._ctr_lock:
                served, shed = serving.results_out, serving.shed
            write_health(workdir, args.worker_id, {
                "pid": os.getpid(), "records_served": served,
                "shed": shed, "restarts": restarts,
                "flight_dump": dump, "draining": True,
            })
        stop.set()
        serving._stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    hb = threading.Thread(
        target=_heartbeat,
        args=(serving, workdir, args.worker_id, stop,
              float(serving.helper.health_interval), restarts),
        daemon=True, name="fleet-heartbeat")
    hb.start()
    if args.worker_id > 0 and getattr(serving, "registry", None) is not None:
        threading.Thread(target=_watch_manifest, args=(serving, stop),
                         daemon=True, name="fleet-manifest-watch").start()
    logger.info("fleet worker %d serving (pid %d)", args.worker_id,
                os.getpid())
    try:
        serving.serve_forever()
    finally:
        stop.set()
    return 0


if __name__ == "__main__":
    sys.exit(main())
