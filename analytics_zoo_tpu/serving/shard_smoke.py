"""Sharded-fabric chaos smoke (``scripts/shard-smoke``; CI fast tier).

Brings up the fabric's full production shape — two real broker
*processes*, an in-process :class:`ClusterServing` consuming
``shard://`` with two SLO tenant classes, and a fabric producer — then
SIGKILLs one broker mid-burst and asserts the fabric contract
(docs/serving-network.md#sharding, docs/multi-tenancy.md):

- **exactly-once through broker death**: every uri ends with exactly
  one result carrying *its own* record's value; records (and unpopped
  results) the dead broker swallowed are re-driven from the producer's
  pending ledger with their original dedup tokens, so nothing is lost
  and nothing double-answers;
- **tenant classification**: each result's timing payload names the
  SLO class its (model, version) bound to, and the scheduler drained
  both classes;
- **status rows**: ``zoo-serving status`` transport section renders
  one row per shard, with the killed shard marked DOWN.

Exit 0 on success, 1 on any violated assertion.
"""

from __future__ import annotations

import argparse
import contextlib
import io
import json
import os
import shutil
import signal
import socket as socket_mod
import sys
import tempfile
import time

CONFIG_TMPL = """\
model:
  stub_ms_per_batch: {stub_ms}

data:
  src: {src}
  image_shape: 3, 4, 4

params:
  batch_size: 4
  top_n: 0
  stream_maxlen: 1000000

slo:
  classes:
    - name: premium
      model: m1
      weight: 3
      priority: 0
      objectives:
        - name: latency
          p99_ms: 60000
    - name: batch
      model: m2
      weight: 1
      priority: 1
      shed_wait_ms: 60000
"""


def _free_ports(n):
    socks = [socket_mod.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def run_smoke(records: int = 48, stub_ms: float = 2.0,
              stream=None) -> int:
    import numpy as np

    from . import cli
    from .cluster_serving import ClusterServing, ClusterServingHelper
    from .shard_fabric import (ShardedStreamQueue, spawn_broker_proc,
                               wait_broker_up)

    out = stream if stream is not None else sys.stdout
    workdir = tempfile.mkdtemp(prefix="zoo_shard_smoke_")
    ports = _free_ports(2)
    spec = "shard://" + ",".join(f"127.0.0.1:{p}" for p in ports)
    cfg = os.path.join(workdir, "config.yaml")
    with open(cfg, "w") as f:
        f.write(CONFIG_TMPL.format(stub_ms=stub_ms, src=spec))

    def fail(msg):
        out.write(f"SHARD_SMOKE_FAIL: {msg}\n")
        return 1

    procs = [spawn_broker_proc(p, claim_timeout_s=5.0) for p in ports]
    serving = None
    old_env = os.environ.get("ZOO_SERVING_TRANSPORT")
    try:
        for p in ports:
            wait_broker_up("127.0.0.1", p)
        serving = ClusterServing(
            helper=ClusterServingHelper(config_path=cfg)).start()
        q = ShardedStreamQueue([("127.0.0.1", p) for p in ports],
                               probe_interval_s=0.2)
        uris = [f"u-{i}" for i in range(records)]
        for i, uri in enumerate(uris):
            q.enqueue({
                "uri": uri, "model": "m1" if i % 2 else "m2",
                "tensors": {"t": {
                    "data": np.full((3, 4, 4), float(i),
                                    np.float32).tobytes(),
                    "shape": [3, 4, 4]}},
                "enqueue_ts_ms": time.time() * 1e3})

        # -- mid-burst: wait for first results, then SIGKILL shard 0 --
        results = {}
        deadline = time.time() + 30.0
        while len(results) < records // 4:
            if time.time() > deadline:
                return fail("burst never started draining")
            results.update(q.all_results(pop=True))
            time.sleep(0.02)
        os.kill(procs[0].pid, signal.SIGKILL)
        procs[0].wait(timeout=10)

        # -- recovery: popped results are ground truth; re-drive what
        # the dead broker swallowed via the producer's pending ledger -
        deadline = time.time() + 60.0
        while len(results) < records and time.time() < deadline:
            got = q.all_results(pop=True)
            results.update(got)
            if not got:
                q.reenqueue_missing(u for u in uris if u not in results)
                time.sleep(0.1)
        if len(results) != records:
            missing = [u for u in uris if u not in results][:8]
            return fail(f"only {len(results)}/{records} results after "
                        f"kill (missing {missing}...)")
        for i, uri in enumerate(uris):
            row = json.loads(results[uri])
            if abs(float(row["value"][0]) - i) > 1e-4:
                return fail(f"{uri} value {row['value'][0]} != {i} "
                            f"(cross-wired: not exactly-once)")
            want = "premium" if i % 2 else "batch"
            if row["timing"].get("tenant") != want:
                return fail(f"{uri} classified "
                            f"{row['timing'].get('tenant')} != {want}")
        if q.all_results(pop=True):
            return fail("duplicate results after recovery")
        if q.reenqueued < 1:
            return fail("broker death re-drove nothing (reenqueued=0)")
        st = serving.pipeline_stats()
        tn = st.get("tenants", {})
        if not (tn.get("premium", {}).get("drained", 0) > 0
                and tn.get("batch", {}).get("drained", 0) > 0):
            return fail(f"tenant scheduler drained nothing: {tn}")

        # -- status: one row per shard, dead shard marked DOWN --------
        os.environ["ZOO_SERVING_TRANSPORT"] = spec
        cap = io.StringIO()
        with contextlib.redirect_stdout(cap):
            cli._print_transport(workdir)
        status = cap.getvalue()
        if status.count("shard socket://") != 2:
            return fail(f"expected 2 shard rows in status:\n{status}")
        if "health=DOWN" not in status or "healthy=1/2" not in status:
            return fail(f"killed shard not marked DOWN:\n{status}")

        out.write(f"SHARD_SMOKE_OK records={records} "
                  f"reenqueued={q.reenqueued} failovers={q.failovers} "
                  f"premium_drained={tn['premium']['drained']} "
                  f"batch_drained={tn['batch']['drained']}\n")
        return 0
    finally:
        if old_env is None:
            os.environ.pop("ZOO_SERVING_TRANSPORT", None)
        else:
            os.environ["ZOO_SERVING_TRANSPORT"] = old_env
        if serving is not None:
            serving.stop()
        for p in procs:
            if p.poll() is None:
                p.kill()
            p.wait(timeout=10)
        shutil.rmtree(workdir, ignore_errors=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="shard-smoke")
    ap.add_argument("--records", type=int, default=48)
    ap.add_argument("--stub-ms", type=float, default=2.0)
    args = ap.parse_args(argv)
    return run_smoke(records=args.records, stub_ms=args.stub_ms)


if __name__ == "__main__":
    sys.exit(main())
