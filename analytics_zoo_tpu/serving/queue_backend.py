"""Stream-queue transports for Cluster Serving.

The reference's transport is a Redis stream (``image_stream`` XADD/XREAD,
ClusterServing.scala:105-116) plus a results hash.  The rebuild keeps that
wire model behind a small interface so the serving loop and clients are
transport-agnostic:

- :class:`InProcessStreamQueue` — threading-based, for tests and
  single-process serving;
- :class:`FileStreamQueue` — directory-backed, multi-process on one host
  (each record one msgpack file, atomic rename), no external service;
- :class:`RedisStreamQueue` — the reference transport, used when the
  ``redis`` client package is importable and a server address is given;
- :class:`~analytics_zoo_tpu.serving.socket_queue.SocketStreamQueue` —
  the stdlib network transport (``socket://host:port``): a TCP broker
  with server-side claims, redelivery, and result long-poll
  (docs/serving-network.md).

All three implement XADD-like ``enqueue``, XREAD-like ``read_batch``, a
results hash (``put_result``/``get_result``), and the memory-watermark trim
(ClusterServing.scala:130-136).

Latency decomposition (docs/serving-fleet.md): ``read_batch`` stamps
every delivered record with ``dequeue_ts_ms`` (epoch ms) so the serving
loop can split wire/transport time (``dequeue_ts_ms - enqueue_ts_ms``,
the client stamps the latter) from device time.

Fleet delivery contract: :class:`FileStreamQueue` claims records by
atomic rename, so N worker processes reading one stream directory never
double-serve a record; each consumer additionally tracks delivered
record ids (duplicate redelivery is detected and skipped) and
per-producer sequence gaps — see :meth:`FileStreamQueue.consumer_stats`.
"""

from __future__ import annotations

import itertools
import os
import tempfile
import threading
import time
import uuid
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

import msgpack

from ..utils import telemetry


class StreamQueue:
    """Interface: a named input stream + a results map."""

    def enqueue(self, record: dict) -> str:
        raise NotImplementedError

    def read_batch(self, max_items: int, timeout: float = 1.0
                   ) -> List[Tuple[str, dict]]:
        raise NotImplementedError

    def put_result(self, uri: str, value: bytes):
        raise NotImplementedError

    def put_results(self, results: Dict[str, bytes]):
        """Commit a batch of results (the serving writer stage drains a
        whole batch at once); transports may override to amortize their
        per-result cost."""
        for uri, value in results.items():
            self.put_result(uri, value)

    def get_result(self, uri: str, pop: bool = True) -> Optional[bytes]:
        raise NotImplementedError

    def all_results(self, pop: bool = True) -> Dict[str, bytes]:
        raise NotImplementedError

    def stream_len(self) -> int:
        raise NotImplementedError

    def trim(self, keep_last: int):
        """Watermark trim (xtrim parity)."""
        raise NotImplementedError

    @staticmethod
    def _stamp_dequeue(items: List[Tuple[str, dict]]
                       ) -> List[Tuple[str, dict]]:
        """Stamp delivery time (epoch ms) on every record so the server
        can report transport vs device latency per row; with telemetry
        on, each delivery is an instant event tagged with the record's
        trace id — the queue hop in the merged request tree."""
        ts = time.time() * 1e3
        traced = telemetry.enabled()
        for _rid, rec in items:
            if isinstance(rec, dict):
                rec.setdefault("dequeue_ts_ms", ts)
                if traced:
                    tid = rec.get("trace_id") or rec.get(b"trace_id")
                    if tid:
                        if isinstance(tid, (bytes, bytearray)):
                            tid = tid.decode()
                        telemetry.event("queue/deliver", trace_id=tid,
                                        uri=rec.get("uri"))
        return items


class DeliveryLedger:
    """Bounded consumer-side delivery ledger (shared by the file and
    socket transports): duplicate-redelivery detection over a sliding
    rid window plus per-producer sequence-gap accounting.

    Both memories are **bounded**: delivered rids beyond ``window`` are
    evicted oldest-first (duplicate counters stay exact within the
    window — older redeliveries are indistinguishable from fresh rids,
    the documented trade), and the per-producer last-seen-seq map is an
    LRU capped at ``producer_cap`` so a long-lived consumer fed by an
    endless churn of short-lived producers (every client restart mints a
    new producer id) cannot leak — the slow growth the PR 13 soak leg
    exposed."""

    def __init__(self, window: int = 65536, producer_cap: int = 4096):
        self.window = int(window)
        self.producer_cap = int(producer_cap)
        self._delivered: set = set()
        self._ring: deque = deque()
        self._producer_seq: "OrderedDict[str, int]" = OrderedDict()
        self.duplicates = 0
        self.seq_gaps = 0

    def note(self, rid: str) -> bool:
        """Record one delivery; False when ``rid`` was already served
        within the window (duplicate redelivery — skip it)."""
        if rid in self._delivered:
            self.duplicates += 1
            return False
        self._delivered.add(rid)
        self._ring.append(rid)
        while len(self._ring) > self.window:
            self._delivered.discard(self._ring.popleft())
        # per-producer sequence continuity (advisory: a gap means a
        # record this consumer never saw — lost, trimmed, or claimed by
        # another fleet worker; per-worker gaps are expected in a fleet,
        # a gap with ONE consumer means loss)
        parts = rid.rsplit("-", 2)
        if len(parts) == 3:
            try:
                seq = int(parts[2])
            except ValueError:
                return True
            producer = parts[1]
            last = self._producer_seq.get(producer)
            if last is not None and seq > last + 1:
                self.seq_gaps += seq - last - 1
            if last is None or seq > last:
                self._producer_seq[producer] = seq
            self._producer_seq.move_to_end(producer)
            while len(self._producer_seq) > self.producer_cap:
                self._producer_seq.popitem(last=False)
        return True

    def stats(self) -> dict:
        return {"duplicates": self.duplicates,
                "seq_gaps": self.seq_gaps,
                "producers_seen": len(self._producer_seq)}


class InProcessStreamQueue(StreamQueue):
    def __init__(self, name: str = "image_stream"):
        self.name = name
        self._stream: "OrderedDict[str, dict]" = OrderedDict()
        self._results: Dict[str, bytes] = {}
        self._cv = threading.Condition()

    def enqueue(self, record: dict) -> str:
        rid = uuid.uuid4().hex
        with self._cv:
            self._stream[rid] = record
            self._cv.notify_all()
        return rid

    def read_batch(self, max_items, timeout=1.0):
        deadline = time.time() + timeout
        with self._cv:
            while not self._stream and time.time() < deadline:
                self._cv.wait(timeout=max(deadline - time.time(), 0.01))
            out = []
            while self._stream and len(out) < max_items:
                rid, rec = self._stream.popitem(last=False)
                out.append((rid, rec))
            return self._stamp_dequeue(out)

    def put_result(self, uri, value):
        with self._cv:
            self._results[uri] = value

    def put_results(self, results):
        with self._cv:   # one lock acquisition per served batch
            self._results.update(results)

    def get_result(self, uri, pop=True):
        with self._cv:
            return self._results.pop(uri, None) if pop else \
                self._results.get(uri)

    def all_results(self, pop=True):
        with self._cv:
            out = dict(self._results)
            if pop:
                self._results.clear()
            return out

    def stream_len(self):
        with self._cv:
            return len(self._stream)

    def trim(self, keep_last):
        with self._cv:
            while len(self._stream) > keep_last:
                self._stream.popitem(last=False)


class FileStreamQueue(StreamQueue):
    """Directory-backed stream: producers write ``<ts>-<id>.msgpack`` into
    ``<root>/stream`` atomically; the consumer claims files by rename.
    Results land in ``<root>/results/<safe-uri>``.  Good enough for
    multi-process single-host serving without Redis."""

    #: delivered-rid memory per consumer (duplicate detection window)
    DELIVERED_WINDOW = 65536
    #: LRU cap on the per-producer last-seen-seq map (DeliveryLedger)
    PRODUCER_CAP = 4096

    def __init__(self, root: str, name: str = "image_stream",
                 orphan_tmp_age: float = 60.0,
                 delivered_window: Optional[int] = None,
                 producer_cap: Optional[int] = None):
        self.root = root
        self.stream_dir = os.path.join(root, name)
        self.results_dir = os.path.join(root, "results")
        os.makedirs(self.stream_dir, exist_ok=True)
        os.makedirs(self.results_dir, exist_ok=True)
        # per-producer monotonic sequence: timestamp collisions (same
        # time_ns on fast enqueues, coarse clocks) still sort FIFO
        self._seq = itertools.count()
        # producer identity baked into every rid so a consumer can track
        # per-producer sequence continuity under concurrent writers
        self._producer = uuid.uuid4().hex[:8]
        self.orphan_tmp_age = orphan_tmp_age
        self._last_gc = 0.0
        # consumer-side delivery ledger: bounded rid window + LRU-capped
        # per-producer seq map + the counters consumer_stats() reports
        self._ledger = DeliveryLedger(
            window=(self.DELIVERED_WINDOW if delivered_window is None
                    else int(delivered_window)),
            producer_cap=(self.PRODUCER_CAP if producer_cap is None
                          else int(producer_cap)))

    def enqueue(self, record):
        rid = (f"{time.time_ns():020d}-{self._producer}"
               f"-{next(self._seq):08d}")
        payload = msgpack.packb(record, use_bin_type=True)
        fd, tmp = tempfile.mkstemp(dir=self.stream_dir, suffix=".tmp")
        with os.fdopen(fd, "wb") as f:
            f.write(payload)
        os.rename(tmp, os.path.join(self.stream_dir, rid + ".msgpack"))
        return rid

    def _gc_orphans(self):
        """Recover droppings of crashed processes: aged ``.tmp`` files
        (enqueuer/writer died mid-write, never renamed) are deleted;
        aged ``.claimed`` files (consumer died between claim and unlink)
        are renamed back into the stream — re-serving is harmless since
        the results map is idempotent per uri."""
        now = time.time()
        if now - self._last_gc < self.orphan_tmp_age / 2:
            return
        self._last_gc = now
        for d in (self.stream_dir, self.results_dir):
            for n in os.listdir(d):
                path = os.path.join(d, n)
                try:
                    age = now - os.path.getmtime(path)
                except OSError:
                    continue
                if age < self.orphan_tmp_age:
                    continue
                try:
                    if n.endswith(".tmp"):
                        os.unlink(path)
                    elif n.endswith(".msgpack.claimed"):
                        os.rename(path, path[:-len(".claimed")])
                except OSError:
                    pass

    def _note_delivery(self, rid: str) -> bool:
        """Record one delivery; False when ``rid`` was already served by
        this consumer (duplicate redelivery — e.g. an operator restoring
        ``.claimed`` orphans a second time) and must be skipped."""
        return self._ledger.note(rid)

    def consumer_stats(self) -> dict:
        """Delivery-integrity counters for THIS consumer instance."""
        return self._ledger.stats()

    def read_batch(self, max_items, timeout=1.0):
        self._gc_orphans()
        deadline = time.time() + timeout
        while True:
            names = sorted(n for n in os.listdir(self.stream_dir)
                           if n.endswith(".msgpack"))[:max_items]
            out = []
            for n in names:
                path = os.path.join(self.stream_dir, n)
                claimed = path + ".claimed"
                try:
                    os.rename(path, claimed)  # atomic claim
                except OSError:
                    continue    # another fleet worker won the claim
                with open(claimed, "rb") as f:
                    rec = msgpack.unpackb(f.read(), raw=False)
                os.unlink(claimed)
                rid = n[:-len(".msgpack")]
                if not self._note_delivery(rid):
                    continue    # duplicate redelivery: drop, don't serve
                out.append((rid, rec))
            if out or time.time() >= deadline:
                return self._stamp_dequeue(out)
            time.sleep(0.02)

    @staticmethod
    def _safe(uri: str) -> str:
        # ASCII [A-Za-z0-9._-] exactly as documented
        # (docs/inference-serving.md): non-ASCII alphanumerics must NOT
        # survive, or second-language clients (bytewise mapping) poll a
        # different result filename than the server writes
        return "".join(c if (c.isascii() and c.isalnum()) or c in "._-"
                       else "_" for c in uri)

    def put_result(self, uri, value):
        fd, tmp = tempfile.mkstemp(dir=self.results_dir, suffix=".tmp")
        with os.fdopen(fd, "wb") as f:
            f.write(value)
        os.rename(tmp, os.path.join(self.results_dir, self._safe(uri)))

    def get_result(self, uri, pop=True):
        path = os.path.join(self.results_dir, self._safe(uri))
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            data = f.read()
        if pop:
            os.unlink(path)
        return data

    def all_results(self, pop=True):
        out = {}
        for n in os.listdir(self.results_dir):
            if n.endswith(".tmp"):
                continue
            data = self.get_result(n, pop=pop)
            if data is not None:
                out[n] = data
        return out

    def stream_len(self):
        return sum(1 for n in os.listdir(self.stream_dir)
                   if n.endswith(".msgpack"))

    def trim(self, keep_last):
        names = sorted(n for n in os.listdir(self.stream_dir)
                       if n.endswith(".msgpack"))
        for n in names[:-keep_last] if keep_last else names:
            try:
                os.unlink(os.path.join(self.stream_dir, n))
            except OSError:
                pass


class RedisStreamQueue(StreamQueue):  # pragma: no cover - needs a server
    """The reference transport (Redis stream + hash), used when redis-py
    and a server are available."""

    def __init__(self, host="localhost", port=6379, name="image_stream"):
        import redis

        self.r = redis.Redis(host=host, port=port)
        self.name = name
        self._last_id = "0"

    def enqueue(self, record):
        return self.r.xadd(self.name, {
            k: v if isinstance(v, (bytes, str, int, float)) else
            msgpack.packb(v, use_bin_type=True)
            for k, v in record.items()}).decode()

    def read_batch(self, max_items, timeout=1.0):
        resp = self.r.xread({self.name: self._last_id}, count=max_items,
                            block=int(timeout * 1000))
        out = []
        for _stream, entries in resp or []:
            for rid, fields in entries:
                self._last_id = rid
                rec = {k.decode(): v for k, v in fields.items()}
                out.append((rid.decode(), rec))
        return self._stamp_dequeue(out)

    def put_result(self, uri, value):
        self.r.hset("result:" + uri, "value", value)

    def put_results(self, results):
        pipe = self.r.pipeline()
        for uri, value in results.items():
            pipe.hset("result:" + uri, "value", value)
        pipe.execute()

    def get_result(self, uri, pop=True):
        v = self.r.hget("result:" + uri, "value")
        if pop and v is not None:
            self.r.delete("result:" + uri)
        return v

    def all_results(self, pop=True):
        # one pipelined round trip for the reads (and one for the
        # deletes) instead of 2N — the result-poll path is the client
        # hot loop, N round trips per poll is what wait_all pays
        keys = self.r.keys("result:*")
        if not keys:
            return {}
        pipe = self.r.pipeline()
        for key in keys:
            pipe.hget(key, "value")
        values = pipe.execute()
        out = {}
        hit = []
        for key, v in zip(keys, values):
            if v is None:
                continue
            out[key.decode()[len("result:"):]] = v
            hit.append(key)
        if pop and hit:
            self.r.delete(*hit)
        return out

    def stream_len(self):
        return self.r.xlen(self.name)

    def trim(self, keep_last):
        self.r.xtrim(self.name, maxlen=keep_last)


def get_queue_backend(spec: Optional[str] = None) -> StreamQueue:
    """``None``/'inproc' -> InProcessStreamQueue (also registered as the
    process-wide default so clients and server share it); 'file:<dir>' ->
    FileStreamQueue; 'socket://host:port' -> SocketStreamQueue (network
    broker, serving/socket_queue.py); 'shard://host:p1,host:p2,...' ->
    ShardedStreamQueue (HRW-sharded broker fabric, serving/
    shard_fabric.py); 'host:port' -> RedisStreamQueue."""
    global _DEFAULT_INPROC
    if spec is None or spec == "inproc":
        if _DEFAULT_INPROC is None:
            _DEFAULT_INPROC = InProcessStreamQueue()
        return _DEFAULT_INPROC
    if spec.startswith("file:"):
        return FileStreamQueue(spec[len("file:"):])
    if spec.startswith("socket://"):
        from .socket_queue import SocketStreamQueue, parse_socket_spec

        host, port = parse_socket_spec(spec)
        return SocketStreamQueue(host, port)
    if spec.startswith("shard://"):
        from .shard_fabric import ShardedStreamQueue, parse_shard_spec

        return ShardedStreamQueue(parse_shard_spec(spec))
    host, _, port = spec.partition(":")
    return RedisStreamQueue(host, int(port or 6379))


_DEFAULT_INPROC: Optional[InProcessStreamQueue] = None
