"""ServingFleet: N supervised serving worker processes over one queue.

The reference scales Cluster Serving by running multiple Flink task
replicas behind Redis pub/sub; here the fleet manager composes the
pieces this repo already has (docs/serving-fleet.md):

- **supervision** comes from the launcher seam
  (:mod:`analytics_zoo_tpu.launcher.supervisor`): each worker is a
  subprocess with env propagation, ``[fleet-N]``-tagged log fan-in into
  one stream, and SIGTERM→SIGKILL teardown;
- **work partitioning** is the queue backend's delivery contract: the
  file transport's atomic rename *claim* hands each record to exactly
  one worker process (queue_backend.py), so no record is double-served
  — workers share ``data.src`` and nothing else on the hot path;
- **control plane**: all workers recover the same registry manifest;
  worker 0 owns the file-RPC :class:`RegistryControlServer` (and the
  manifest writes), workers >0 follow the manifest by mtime
  (fleet_worker.py);
- **health**: every worker heartbeats an atomic JSON file under
  ``<workdir>/health/`` (pid, records served, shed count).  The
  supervise loop restarts a worker whose process died *or* whose
  heartbeat went stale past ``health_timeout`` (after a startup grace
  for interpreter + jax import).

``zoo-serving status`` renders :func:`fleet_status` rows from the same
health files, so fleet observability needs no RPC into the workers.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
from typing import Dict, List, Optional

from ..launcher.supervisor import (SupervisedProc, inject_pythonpath,
                                   spawn_supervised, terminate_all)
from ..utils import file_io, telemetry

logger = logging.getLogger("analytics_zoo_tpu.serving.fleet")

HEALTH_DIR = "health"
SUPERVISOR_FILE = "supervisor.json"
AUTOSCALE_FILE = "autoscale.json"
BACKOFF_CAP_S = 30.0


def health_path(workdir: str, worker_id: int) -> str:
    return os.path.join(workdir, HEALTH_DIR, f"worker-{worker_id}.json")


def supervisor_path(workdir: str) -> str:
    return os.path.join(workdir, HEALTH_DIR, SUPERVISOR_FILE)


def read_supervisor_state(workdir: str) -> Dict[str, dict]:
    """Per-worker restart bookkeeping the supervise loop persists
    (restarts, backoff_until, crash_looped) — keyed by worker id string."""
    try:
        with open(supervisor_path(workdir)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def autoscale_path(workdir: str) -> str:
    return os.path.join(workdir, HEALTH_DIR, AUTOSCALE_FILE)


def read_autoscale_trace(workdir: str) -> List[dict]:
    """The supervisor's autoscale event trace (scale_up / scale_down
    rows with backlog, predicted wait, and worker ids) — bench legs and
    `zoo-serving status` read this."""
    try:
        with open(autoscale_path(workdir)) as f:
            return json.load(f).get("events", [])
    except (OSError, ValueError):
        return []


def write_health(workdir: str, worker_id: int, payload: dict):
    """Atomic heartbeat write (rename) — readers never see a torn file."""
    payload = dict(payload, worker_id=worker_id, ts=time.time())
    file_io.write_bytes_atomic(health_path(workdir, worker_id),
                               json.dumps(payload).encode())


def read_health(workdir: str, worker_id: int) -> Optional[dict]:
    try:
        with open(health_path(workdir, worker_id)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


#: heartbeat / stats-file age past which a *live* worker is flagged
#: stale by :func:`fleet_status` (the supervisor may not have acted yet
#: — e.g. it is down, or the worker wedged inside its health_timeout)
STALE_AFTER_S = 10.0


def _file_age_s(path: str, now: float) -> Optional[float]:
    try:
        return round(now - os.path.getmtime(path), 2)
    except OSError:
        return None


def fleet_status(workdir: str,
                 stale_after_s: float = STALE_AFTER_S) -> List[dict]:
    """Per-worker status rows from the health files: worker id, pid,
    heartbeat age, liveness (signal-0 probe), records served, shed count.
    Works from any process — `zoo-serving status` renders these.

    A row is flagged ``stale`` when the worker looks alive but its
    heartbeat or stats dump has not been refreshed within
    ``stale_after_s`` — the wedged-but-not-dead case the supervisor's
    own health_timeout may not have caught yet."""
    hdir = os.path.join(workdir, HEALTH_DIR)
    rows = []
    try:
        names = sorted(n for n in os.listdir(hdir)
                       if n.startswith("worker-") and n.endswith(".json"))
    except FileNotFoundError:
        return rows
    sup = read_supervisor_state(workdir)
    now = time.time()
    seen = set()
    for name in names:
        try:
            with open(os.path.join(hdir, name)) as f:
                h = json.load(f)
        except (OSError, ValueError):
            continue
        pid = h.get("pid")
        alive = False
        if pid:
            try:
                os.kill(int(pid), 0)
                alive = True
            except (OSError, ValueError):
                alive = False
        wid = h.get("worker_id")
        seen.add(str(wid))
        s = sup.get(str(wid), {})
        health_age = round(now - h.get("ts", 0.0), 2)
        stats_age = _file_age_s(
            os.path.join(workdir, f"stats-worker-{wid}.json"), now)
        stale = alive and (
            health_age > stale_after_s or
            (stats_age is not None and stats_age > stale_after_s))
        rows.append({
            "worker_id": wid,
            "pid": pid,
            "alive": alive,
            "health_age_s": health_age,
            "stats_age_s": stats_age,
            "stale": stale,
            "records_served": h.get("records_served", 0),
            "shed": h.get("shed", 0),
            "restarts": s.get("restarts", h.get("restarts", 0)),
            "backoff_until": s.get("backoff_until", 0.0),
            "crash_looped": s.get("crash_looped", False),
            "flight_dump": h.get("flight_dump") or s.get("flight_dump"),
        })
    # workers the supervisor is tracking that never (re)wrote a
    # heartbeat — dead in backoff, or crash-looped before first beat
    for wid, s in sorted(sup.items(), key=lambda kv: kv[0]):
        if wid in seen:
            continue
        rows.append({
            "worker_id": int(wid), "pid": None, "alive": False,
            "health_age_s": None, "stats_age_s": None, "stale": False,
            "records_served": 0, "shed": 0,
            "restarts": s.get("restarts", 0),
            "backoff_until": s.get("backoff_until", 0.0),
            "crash_looped": s.get("crash_looped", False),
            "flight_dump": s.get("flight_dump"),
        })
    rows.sort(key=lambda r: (r["worker_id"] is None, r["worker_id"]))
    return rows


def fleet_metrics(workdir: str) -> dict:
    """Merge per-worker telemetry snapshots (``metrics-worker-N.json``,
    written by each worker's metrics exporter when telemetry is on) into
    one fleet view: counters and gauges are summed by (name, labels) —
    fleet totals — while each worker's full snapshot rides along with
    its age. ``zoo-serving status`` renders this next to the health rows;
    missing/unreadable files are skipped (telemetry may be off)."""
    now = time.time()
    workers: List[dict] = []
    merged: Dict[tuple, float] = {}
    try:
        names = sorted(n for n in os.listdir(workdir)
                       if n.startswith("metrics-worker-")
                       and n.endswith(".json"))
    except FileNotFoundError:
        names = []
    for name in names:
        try:
            with open(os.path.join(workdir, name)) as f:
                snap = json.load(f)
        except (OSError, ValueError):
            continue
        wid = name[len("metrics-worker-"):-len(".json")]
        metrics = snap.get("metrics", [])
        workers.append({"worker_id": wid,
                        "service": snap.get("service", ""),
                        "age_s": round(now - snap.get("ts", 0.0), 2),
                        "metrics": metrics})
        for m in metrics:
            if m.get("type") not in ("counter", "gauge"):
                continue
            key = (m.get("name"),
                   tuple(sorted((m.get("labels") or {}).items())))
            merged[key] = merged.get(key, 0.0) + float(m.get("value", 0.0))
    return {"workers": workers,
            "merged": [{"name": k[0], "labels": dict(k[1]), "value": v}
                       for k, v in sorted(merged.items())]}


class ServingFleet:
    """Spawn, heartbeat-watch, and restart N serving workers.

    ``config_path`` is the standard serving ``config.yaml`` (all workers
    share it; ``data.src`` must be a cross-process transport —
    ``file:<dir>`` or redis).  Worker count and health knobs default to
    the config's ``params.workers`` / ``params.health_*``.
    """

    def __init__(self, config_path: str, workdir: str,
                 workers: Optional[int] = None,
                 health_interval: Optional[float] = None,
                 health_timeout: Optional[float] = None,
                 grace_s: float = 5.0, startup_grace_s: float = 60.0,
                 max_restarts: Optional[int] = None,
                 restart_backoff_s: Optional[float] = None,
                 healthy_reset_s: float = 60.0,
                 stream=None, env: Optional[Dict[str, str]] = None,
                 python: Optional[str] = None,
                 min_workers: Optional[int] = None,
                 max_workers: Optional[int] = None,
                 autoscale_interval: Optional[float] = None):
        from .admission import BacklogAutoscaler
        from .cluster_serving import ClusterServingHelper

        self.config_path = os.path.abspath(config_path)
        self.workdir = os.path.abspath(workdir)
        helper = ClusterServingHelper(config_path=self.config_path)
        self.helper = helper
        self.workers = int(workers if workers is not None
                           else helper.workers)
        if self.workers < 1:
            raise ValueError(f"need >= 1 worker, got {self.workers}")
        # backlog-driven autoscaling band (docs/serving-network.md):
        # active when min < max; the initial worker count is clamped
        # into the band and then floats with load
        self.min_workers = int(min_workers if min_workers is not None
                               else helper.min_workers)
        self.max_workers = int(max_workers if max_workers is not None
                               else helper.max_workers)
        self.max_workers = max(self.max_workers, self.min_workers)
        self.workers = min(max(self.workers, self.min_workers),
                           self.max_workers)
        self.autoscale_interval = float(
            autoscale_interval if autoscale_interval is not None
            else helper.autoscale_interval)
        self.autoscaler = None
        if self.max_workers > self.min_workers:
            self.autoscaler = BacklogAutoscaler(
                self.min_workers, self.max_workers,
                target_ms=helper.autoscale_target_ms,
                scale_up_fraction=helper.scale_up_fraction,
                idle_s=helper.scale_down_idle_s,
                cooldown_s=helper.autoscale_cooldown_s)
        self._backlog_q = None       # lazy supervisor-side queue handle
        self._next_autoscale = 0.0
        self._draining: Dict[int, float] = {}   # wid -> SIGTERM ts
        self.autoscale_events: List[dict] = []
        self.health_interval = float(
            health_interval if health_interval is not None
            else helper.health_interval)
        self.health_timeout = float(
            health_timeout if health_timeout is not None
            else helper.health_timeout)
        self.grace_s = float(grace_s)
        self.startup_grace_s = float(startup_grace_s)
        # crash-loop protection: give up on a worker after max_restarts
        # consecutive restarts (counter resets after healthy_reset_s of
        # uptime); each restart waits restart_backoff_s * 2^(n-1), capped
        # at BACKOFF_CAP_S, so a fast-dying worker cannot spin the host
        self.max_restarts = int(
            max_restarts if max_restarts is not None
            else helper.max_restarts)
        self.restart_backoff_s = float(
            restart_backoff_s if restart_backoff_s is not None
            else helper.restart_backoff_s)
        self.healthy_reset_s = float(healthy_reset_s)
        self.stream = stream if stream is not None else sys.stdout
        self.env = dict(env or {})
        self.python = python or sys.executable
        self._lock = threading.Lock()
        self._procs: Dict[int, SupervisedProc] = {}
        self._spawned_at: Dict[int, float] = {}
        self._active: set = set(range(self.workers))   # wids desired now
        self.restarts: Dict[int, int] = {}
        self.backoff_until: Dict[int, float] = {}
        self.crash_looped: set = set()
        self.flight_dumps: Dict[int, str] = {}
        self._stop = threading.Event()
        os.makedirs(os.path.join(self.workdir, HEALTH_DIR), exist_ok=True)

    # -- lifecycle ------------------------------------------------------
    def _worker_env(self, worker_id: int) -> dict:
        env = inject_pythonpath(dict(os.environ))
        env.update(self.env)
        env["ZOO_SERVING_WORKER_ID"] = str(worker_id)
        env["ZOO_SERVING_FLEET_SIZE"] = str(self.workers)
        env["ZOO_SERVING_WORKER_RESTARTS"] = str(
            self.restarts.get(worker_id, 0))
        return env

    def _spawn(self, worker_id: int):
        # drop the previous heartbeat so a freshly restarted worker is
        # not judged by its predecessor's stale file
        try:
            os.remove(health_path(self.workdir, worker_id))
        except OSError:
            pass
        cmd = [self.python, "-m", "analytics_zoo_tpu.serving.fleet_worker",
               "--config", self.config_path, "--workdir", self.workdir,
               "--worker-id", str(worker_id)]
        sp = spawn_supervised(cmd, env=self._worker_env(worker_id),
                              tag=f"fleet-{worker_id}", stream=self.stream,
                              lock=self._lock, prefix=True)
        self._procs[worker_id] = sp
        self._spawned_at[worker_id] = time.time()
        logger.info("fleet: worker-%d spawned (pid %d)", worker_id,
                    sp.proc.pid)

    def start(self) -> "ServingFleet":
        self._stop.clear()
        self._active = set(range(self.workers))
        for wid in sorted(self._active):
            self._spawn(wid)
        return self

    def _write_supervisor_state(self):
        state = {}
        for wid in set(self.restarts) | set(self.backoff_until) | \
                self.crash_looped:
            state[str(wid)] = {
                "restarts": self.restarts.get(wid, 0),
                "backoff_until": self.backoff_until.get(wid, 0.0),
                "crash_looped": wid in self.crash_looped,
            }
            if wid in self.flight_dumps:
                state[str(wid)]["flight_dump"] = self.flight_dumps[wid]
        file_io.write_bytes_atomic(supervisor_path(self.workdir),
                                   json.dumps(state).encode())

    def poll_once(self) -> List[int]:
        """One supervision pass: restart workers whose process exited or
        whose heartbeat is stale — with per-worker exponential backoff
        and a crash-loop cap.  Returns the worker ids respawned."""
        restarted = []
        now = time.time()
        # reap scaled-down workers: SIGTERM'd workers drain their
        # pipeline and exit — their death is the *goal*, not a crash
        for wid, since in list(self._draining.items()):
            sp = self._procs.get(wid)
            if sp is None:
                self._draining.pop(wid, None)
                continue
            if sp.proc.poll() is not None:
                del self._procs[wid]
                self._draining.pop(wid, None)
                self._forget_worker(wid)
                with self._lock:
                    self.stream.write(
                        f"[fleet] worker-{wid} drained and stopped "
                        f"(scale down)\n")
                    self.stream.flush()
            elif now - since > max(self.grace_s, 10.0):
                terminate_all([sp.proc], grace_s=0.0)   # drain overdue
        # phase 2 of a restart: respawn workers whose backoff elapsed
        for wid, until in list(self.backoff_until.items()):
            if self._stop.is_set() or wid in self._procs or \
                    wid not in self._active:
                continue
            if now >= until:
                del self.backoff_until[wid]
                self._spawn(wid)
                restarted.append(wid)
        if restarted:
            self._write_supervisor_state()
        for wid, sp in list(self._procs.items()):
            if wid in self._draining:
                continue
            rc = sp.proc.poll()
            stale = False
            if rc is None:
                h = read_health(self.workdir, wid)
                age = now - h["ts"] if h else now - self._spawned_at[wid]
                grace = (self.startup_grace_s if h is None
                         else self.health_timeout)
                stale = age > max(grace, self.health_timeout)
            if rc is None and not stale:
                continue
            if self._stop.is_set():
                continue
            reason = (f"exited rc={rc}" if rc is not None
                      else "heartbeat stale")
            if now - self._spawned_at.get(wid, now) >= self.healthy_reset_s:
                # a long-healthy worker dying is not a crash loop
                self.restarts[wid] = 0
            self.restarts[wid] = self.restarts.get(wid, 0) + 1
            if rc is None:
                terminate_all([sp.proc], self.grace_s)
            del self._procs[wid]
            if self.restarts[wid] > self.max_restarts:
                self.crash_looped.add(wid)
                # post-mortem: dump the supervisor's own flight recorder
                # (it saw every restart event) and stamp the path into
                # supervisor.json so `zoo-serving status` can point at it
                telemetry.event("fleet/crash_loop", worker_id=wid,
                                restarts=self.restarts[wid], reason=reason)
                dump = telemetry.dump_flight(
                    f"fleet worker-{wid} crash loop ({reason})")
                if dump:
                    self.flight_dumps[wid] = dump
                with self._lock:
                    self.stream.write(
                        f"[fleet] worker-{wid} {reason}; crash loop "
                        f"(> {self.max_restarts} restarts), giving up"
                        + (f" (flight recorder: {dump})" if dump else "")
                        + "\n")
                    self.stream.flush()
                self._write_supervisor_state()
                continue
            delay = min(BACKOFF_CAP_S,
                        self.restart_backoff_s *
                        (2 ** (self.restarts[wid] - 1)))
            self.backoff_until[wid] = now + delay
            with self._lock:
                self.stream.write(
                    f"[fleet] worker-{wid} {reason}; restarting "
                    f"(restart #{self.restarts[wid]}) in {delay:.1f}s\n")
                self.stream.flush()
            self._write_supervisor_state()
        return restarted

    # -- backlog-driven autoscaling (docs/serving-network.md) -----------
    def _forget_worker(self, wid: int):
        """Scale-down bookkeeping: drop every trace of a retired worker
        so status/supervisor state don't show ghost rows."""
        self.restarts.pop(wid, None)
        self.backoff_until.pop(wid, None)
        self.crash_looped.discard(wid)
        self.flight_dumps.pop(wid, None)
        try:
            os.remove(health_path(self.workdir, wid))
        except OSError:
            pass
        # routed records parked on the retired worker's private generate
        # substream go back to the shared any-claim stream — placement
        # must never strand work (serving/routing.py)
        src = self.helper.src or ""
        if src.startswith("file:"):
            from .routing import sweep_substream

            try:
                n = sweep_substream(src[len("file:"):], wid)
                if n:
                    with self._lock:
                        self.stream.write(
                            f"[fleet] worker-{wid} substream swept: "
                            f"{n} routed record(s) back on the shared "
                            f"stream\n")
                        self.stream.flush()
            except OSError:
                pass
        self._write_supervisor_state()

    def _queue_backlog(self) -> Optional[int]:
        """stream_len() through a supervisor-side handle on the shared
        transport; None when the transport is unreadable from here
        (inproc/redis src, or the broker is down this tick)."""
        if self._backlog_q is None:
            src = self.helper.src or ""
            # shard:// sums stream_len across every healthy shard
            # (ShardedStreamQueue.stream_len), so scale-up sizing sees
            # the whole fabric's backlog, not one broker's
            if not (src.startswith("file:") or src.startswith("socket://")
                    or src.startswith("shard://")):
                return None
            from .queue_backend import get_queue_backend

            self._backlog_q = get_queue_backend(src)
        try:
            return int(self._backlog_q.stream_len())
        except Exception:  # noqa: BLE001 - broker briefly unreachable
            return None

    def _ewma_estimates(self) -> tuple:
        """(record_ms, batch_ms): mean of the positive EWMA service
        estimates the workers publish in their heartbeats."""
        rec, bat = [], []
        for wid in list(self._active):
            adm = (read_health(self.workdir, wid) or {}).get(
                "admission") or {}
            r = float(adm.get("est_record_ms") or 0.0)
            b = float(adm.get("est_batch_ms") or 0.0)
            if r > 0:
                rec.append(r)
            if b > 0:
                bat.append(b)
        return (sum(rec) / len(rec) if rec else 0.0,
                sum(bat) / len(bat) if bat else 0.0)

    def _generate_load(self) -> tuple:
        """(gen_steps, token_ms): queued decode-step backlog summed over
        the workers' heartbeat routing reports, and the mean positive
        EWMA per-token cost — the generate-aware inputs the autoscaler
        weighs so one queued 512-token essay no longer sizes like one
        predict record (docs/serving-generate.md#fleet-routing)."""
        steps = 0.0
        toks = []
        for wid in list(self._active):
            h = read_health(self.workdir, wid) or {}
            routing = h.get("routing") or {}
            steps += float(routing.get("queued_steps") or 0.0)
            t = float((h.get("admission") or {}).get(
                "est_token_ms") or 0.0)
            if t > 0:
                toks.append(t)
        return steps, (sum(toks) / len(toks) if toks else 0.0)

    def _routed_backlog(self) -> int:
        """Unclaimed records parked on per-worker generate substreams —
        invisible to the shared stream's ``stream_len`` but real
        backlog for scale-up sizing."""
        src = self.helper.src or ""
        if not src.startswith("file:"):
            return 0
        from .routing import substream_backlog

        return substream_backlog(src[len("file:"):])

    def _note_autoscale(self, action: str, wids: List[int], reason: str,
                        backlog: int, wait_ms: float):
        event = {"ts": time.time(), "action": action, "workers": wids,
                 "active": len(self._active), "backlog": backlog,
                 "predicted_wait_ms": round(wait_ms, 1), "reason": reason}
        self.autoscale_events.append(event)
        # literal names only (scripts/lint-telemetry): the action rides
        # as an arg, not in the event name
        telemetry.event("fleet/autoscale", **{k: v for k, v in
                                              event.items() if k != "ts"})
        telemetry.gauge("zoo_fleet_workers").set(len(self._active))
        file_io.write_bytes_atomic(
            autoscale_path(self.workdir),
            json.dumps({"min_workers": self.min_workers,
                        "max_workers": self.max_workers,
                        "active": len(self._active),
                        "events": self.autoscale_events}).encode())
        with self._lock:
            self.stream.write(
                f"[fleet] {action} -> {len(self._active)} workers "
                f"({'+' if action == 'scale_up' else '-'}"
                f"{wids}): {reason}\n")
            self.stream.flush()

    def autoscale_once(self, now: Optional[float] = None) -> bool:
        """One autoscale decision tick (no-op unless min < max): poll
        the shared stream's backlog + the workers' EWMA estimates, and
        grow/shrink toward the policy's desired count.  Scale-down is
        drain-before-kill: the retiring worker gets SIGTERM, finishes
        its in-flight records, and only then is reaped (poll_once).
        Returns True when the fleet changed size."""
        if self.autoscaler is None or self._stop.is_set():
            return False
        now = time.time() if now is None else now
        if now < self._next_autoscale:
            return False
        self._next_autoscale = now + self.autoscale_interval
        backlog = self._queue_backlog()
        if backlog is None:
            return False
        backlog += self._routed_backlog()
        record_ms, batch_ms = self._ewma_estimates()
        gen_steps, token_ms = self._generate_load()
        current = len(self._active)
        desired, reason = self.autoscaler.desired(
            backlog, record_ms, batch_ms, current, now,
            gen_steps=gen_steps, token_ms=token_ms)
        if reason is None or desired == current:
            return False
        wait_ms = self.autoscaler.predicted_wait_ms(
            backlog, record_ms, batch_ms, current,
            gen_steps=gen_steps, token_ms=token_ms)
        if desired > current:
            added = []
            for wid in range(self.max_workers):
                if len(self._active) >= desired:
                    break
                if wid in self._active or wid in self._draining:
                    continue
                self._active.add(wid)
                self.restarts.pop(wid, None)
                self.backoff_until.pop(wid, None)
                self.crash_looped.discard(wid)
                self._spawn(wid)
                added.append(wid)
            if added:
                self._note_autoscale("scale_up", added, reason,
                                     backlog, wait_ms)
            return bool(added)
        removed = []
        for wid in sorted(self._active, reverse=True):
            if len(self._active) <= desired:
                break
            self._active.discard(wid)
            sp = self._procs.get(wid)
            if sp is not None and sp.proc.poll() is None:
                self._draining[wid] = now
                try:
                    sp.proc.terminate()   # SIGTERM: drain, then exit
                except OSError:
                    pass
            else:
                # dead / in backoff: nothing in flight to drain
                if sp is not None:
                    self._procs.pop(wid, None)
                self._forget_worker(wid)
            removed.append(wid)
        if removed:
            self._note_autoscale("scale_down", removed, reason,
                                 backlog, wait_ms)
        return bool(removed)

    def supervise(self, poll_s: float = 0.25):
        """Block supervising until :meth:`stop` (or KeyboardInterrupt)."""
        try:
            while not self._stop.is_set():
                self.poll_once()
                self.autoscale_once()
                if self._stop.wait(poll_s):
                    break
        finally:
            self.shutdown()

    def wait_healthy(self, timeout: float = 60.0) -> bool:
        """Block until every worker has written a heartbeat (i.e. its
        serve loop is up), or ``timeout`` elapses."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            if all(read_health(self.workdir, w) is not None
                   for w in sorted(self._active)):
                return True
            time.sleep(0.05)
        return False

    def stop(self):
        self._stop.set()

    def shutdown(self):
        """SIGTERM every worker (they drain their pipelines), SIGKILL
        stragglers after the grace period."""
        self._stop.set()
        terminate_all([sp.proc for sp in self._procs.values()],
                      self.grace_s)
        for sp in self._procs.values():
            sp.pump.join(timeout=5.0)

    # -- observability --------------------------------------------------
    def status(self) -> List[dict]:
        return fleet_status(self.workdir)

    def metrics(self) -> dict:
        return fleet_metrics(self.workdir)

    def worker_stats(self) -> List[dict]:
        """Per-worker pipeline_stats() snapshots (from each worker's
        stats-worker-N.json dump); missing/unreadable files are skipped."""
        out = []
        for wid in range(max(self.workers, self.max_workers)):
            path = os.path.join(self.workdir, f"stats-worker-{wid}.json")
            try:
                with open(path) as f:
                    out.append(dict(json.load(f), worker_id=wid))
            except (OSError, ValueError):
                continue
        return out
