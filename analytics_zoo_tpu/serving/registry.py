"""ModelRegistry: versioned multi-model control plane for Cluster Serving.

The reference binds one serving process to one ``InferenceModel``
(ClusterServing.scala:44-392) — updating a model means restarting the
service.  This module is the control plane above the pipelined engine
(docs/serving-pipeline.md): named models, each with immutable numbered
versions wrapping an :class:`InferenceModel` loaded through the existing
multi-backend loaders, a routing pointer per model that can be swapped
atomically while traffic flows, and a canary mode that splits traffic by
a deterministic hash of the record uri.

Lifecycle (docs/model-registry.md):

- :meth:`ModelRegistry.deploy` — load + AOT-warm the new version *off*
  the serve path, then atomically swap the routing pointer and drain
  in-flight batches on the old version; a failed warmup/compile rolls
  back automatically (the pointer never moves).
- :meth:`ModelRegistry.set_canary` — route ``weight`` of a model's
  default traffic to a candidate version, keyed by ``crc32(uri)`` so a
  given uri always lands on the same side; the canary auto-rolls-back
  when its error rate exceeds ``error_threshold`` after
  ``min_requests`` observations.
- :meth:`ModelRegistry.promote` / :meth:`ModelRegistry.undeploy` —
  graduate a canary (or any ready version) to active / retire versions.

The deployed set persists as a JSON manifest written atomically through
``utils.file_io`` (:func:`~analytics_zoo_tpu.utils.file_io.
write_bytes_atomic`), so a restarted server :meth:`recover`\\ s its
models, active pointers, and canary state.

``RegistryControlServer`` + :func:`control_request` are the file-RPC
bridge the ``zoo-serving deploy``/``undeploy``/``promote`` CLI verbs use
to drive a *running* server: requests are JSON files atomically renamed
into ``<root>/control/``, answered in place by the server's poll thread.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import uuid
import zlib
from collections import deque
from typing import Callable, Dict, List, Optional

from ..pipeline.inference import InferenceModel
from ..pipeline.inference.inference_summary import InferenceSummary
from ..utils import file_io

logger = logging.getLogger("analytics_zoo_tpu.serving.registry")

DEFAULT_MODEL = "default"


class RegistryError(RuntimeError):
    """Base class for registry control-plane failures."""


class UnknownModelError(RegistryError):
    """Routing asked for a model/version the registry does not hold."""


class DeployError(RegistryError):
    """Deploy failed (load/warmup/compile); the routing pointer was not
    moved — the previous version keeps serving."""


def _is_int8(model: Optional[InferenceModel]) -> bool:
    """Whether a pre-loaded InferenceModel carries an int8 backend."""
    from ..pipeline.inference.inference_model import QuantizedModel

    return model is not None and \
        isinstance(getattr(model, "model", None), QuantizedModel)


class ModelVersion:
    """One immutable numbered version of a named model.

    Holds the loaded :class:`InferenceModel` (or just a ``path`` while
    cold), its own :class:`InferenceSummary`, request/error counters,
    and an in-flight refcount used to drain dispatched batches before a
    retired version is released.
    """

    def __init__(self, name: str, version: int,
                 model: Optional[InferenceModel] = None,
                 path: Optional[str] = None, dtype: str = "f32",
                 calibration: Optional[str] = None):
        self.name = name
        self.version = int(version)
        self.model = model
        self.path = path
        #: compute dtype of this version ("f32" | "int8") — part of the
        #: dispatch key so an int8 canary never shares a batch with its
        #: f32 baseline
        self.dtype = dtype or "f32"
        #: exported calibration-scales path for int8 versions (enables
        #: requantization-chain planning at (re)load time)
        self.calibration = calibration
        #: registered -> warming -> ready -> retired | failed | cold
        self.state = "registered"
        self.created = time.time()
        self.summary = InferenceSummary()
        self.requests = 0
        self.errors = 0
        self._inflight = 0
        self._cv = threading.Condition()

    @property
    def key(self) -> str:
        return f"{self.name}:v{self.version}"

    # -- in-flight batch refcount (hot-swap drain) ---------------------
    def acquire(self):
        with self._cv:
            self._inflight += 1

    def release(self):
        with self._cv:
            self._inflight = max(self._inflight - 1, 0)
            self._cv.notify_all()

    @property
    def inflight(self) -> int:
        with self._cv:
            return self._inflight

    def drain(self, timeout: float = 10.0) -> bool:
        """Block until every dispatched batch on this version has been
        written (or ``timeout``); returns True when fully drained."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(timeout=remaining)
        return True

    def stats(self) -> dict:
        return {"state": self.state,
                "path": self.path,
                "dtype": self.dtype,
                "created": self.created,
                "requests": self.requests,
                "errors": self.errors,
                "inflight": self.inflight,
                "stages": self.summary.snapshot()["stages"]}


class CanaryState:
    """Traffic split for one model: ``weight`` of default-routed records
    go to ``version``; counters feed the auto-rollback check."""

    def __init__(self, version: int, weight: float,
                 error_threshold: float = 0.5, min_requests: int = 20):
        self.version = int(version)
        self.weight = min(max(float(weight), 0.0), 1.0)
        self.error_threshold = float(error_threshold)
        self.min_requests = int(min_requests)
        self.requests = 0
        self.errors = 0

    def stats(self) -> dict:
        return {"version": self.version, "weight": self.weight,
                "error_threshold": self.error_threshold,
                "min_requests": self.min_requests,
                "requests": self.requests, "errors": self.errors}


class ModelRegistry:
    """Named models, immutable numbered versions, atomic routing swaps.

    ``root``: directory (URI) for the persisted manifest; ``None`` keeps
    the registry in-memory only.  ``loader``: ``path -> InferenceModel``
    (defaults to :meth:`InferenceModel.load`, which accepts native zoo
    model directories; any of the multi-backend ``load_*`` loaders can
    be closed over instead).
    """

    MANIFEST = "manifest.json"

    def __init__(self, root: Optional[str] = None,
                 default_model: str = DEFAULT_MODEL,
                 loader: Optional[Callable[[str], InferenceModel]] = None,
                 canary_error_threshold: float = 0.5,
                 canary_min_requests: int = 20):
        self.root = root
        self.default_model = default_model
        self._loader = loader or self._default_loader
        self.canary_error_threshold = float(canary_error_threshold)
        self.canary_min_requests = int(canary_min_requests)
        self._lock = threading.RLock()
        self._models: Dict[str, Dict[int, ModelVersion]] = {}
        self._active: Dict[str, int] = {}
        self._canary: Dict[str, CanaryState] = {}
        self.events: deque = deque(maxlen=64)
        if root:
            file_io.makedirs(root)

    @staticmethod
    def _default_loader(path: str) -> InferenceModel:
        return InferenceModel().load(path)

    @property
    def manifest_uri(self) -> Optional[str]:
        if not self.root:
            return None
        return self.root.rstrip("/") + "/" + self.MANIFEST

    def _event(self, msg: str):
        logger.info("registry: %s", msg)
        self.events.append({"t": time.time(), "msg": msg})

    # ------------------------------------------------------------------
    # deploy / promote / undeploy / canary
    # ------------------------------------------------------------------
    def deploy(self, name: Optional[str] = None,
               model: Optional[InferenceModel] = None,
               path: Optional[str] = None,
               warmup: Optional[Callable[[InferenceModel], object]] = None,
               activate: bool = True, load: bool = True,
               drain_timeout: float = 10.0, quantize: bool = False,
               calibration: Optional[str] = None) -> ModelVersion:
        """Register the next version of ``name`` and (optionally) swap
        traffic onto it.

        The model is loaded (``path`` through ``loader``) and warmed
        (``warmup(model)`` — typically AOT-compiling every padding
        bucket) entirely off the serve path; only then does the routing
        pointer swap, after which the old version's in-flight batches
        drain.  Any load/warmup failure raises :class:`DeployError` and
        leaves routing untouched.  ``load=False`` records the version in
        the manifest without loading (offline deploy; the next
        :meth:`recover` loads it).

        ``quantize`` deploys the version as int8: loaded through
        :meth:`InferenceModel.load_quantized` with ``calibration``
        (exported scales JSON; defaults to a ``calibration.json`` inside
        the model directory) so requantization chains are planned at
        load time. The version carries ``dtype="int8"`` — its own
        dispatch keys, AOT warmup, and compile-cache entries — so an
        int8 build can canary side-by-side against its f32 baseline.
        """
        name = name or self.default_model
        if model is None and path is None:
            raise ValueError("deploy needs a loaded model or a path")
        dtype = "int8" if quantize or _is_int8(model) else "f32"
        with self._lock:
            versions = self._models.setdefault(name, {})
            version = max(versions, default=0) + 1
            mv = ModelVersion(name, version, model=model, path=path,
                              dtype=dtype, calibration=calibration)
            versions[version] = mv
        if not load:
            if activate:
                with self._lock:
                    self._active[name] = version
            self._event(f"registered {mv.key} [{mv.dtype}] (path={path}; "
                        f"loads on next start)")
            self._save()
            return mv
        phase = "load"
        try:
            if mv.model is None:
                mv.model = self._load_version(mv)
            mv.state = "warming"
            phase = "warmup"
            if warmup is not None:
                warmup(mv.model)
        except Exception as e:
            with self._lock:
                mv.state = "failed"
                mv.model = None
            self._event(f"deploy of {mv.key} failed ({e}); routing "
                        f"pointer unchanged")
            self._save()
            raise DeployError(
                f"deploy of {mv.key} failed during {phase}: {e}") from e
        mv.state = "ready"
        if activate:
            self.promote(name, version, drain_timeout=drain_timeout)
        else:
            self._event(f"deployed {mv.key} (not routed)")
            self._save()
        return mv

    def _load_version(self, mv: ModelVersion) -> InferenceModel:
        """Load a version with its recorded dtype: int8 versions go
        through the quantized loader (+ calibration scales when
        exported), f32 through the configured loader."""
        if mv.dtype == "int8":
            return InferenceModel().load_quantized(
                mv.path, calibration_path=mv.calibration)
        return self._loader(mv.path)

    def _ensure_loaded(self, mv: ModelVersion,
                       warmup: Optional[Callable] = None):
        if mv.model is not None:
            return
        if not mv.path:
            raise RegistryError(
                f"{mv.key} has no loaded model and no path to load from")
        mv.state = "warming"
        try:
            mv.model = self._load_version(mv)
            if warmup is not None:
                warmup(mv.model)
        except Exception as e:
            mv.state = "failed"
            mv.model = None
            raise DeployError(f"loading {mv.key} failed: {e}") from e

    def promote(self, name: str, version: int,
                warmup: Optional[Callable] = None, load: bool = True,
                drain_timeout: float = 10.0) -> ModelVersion:
        """Atomically point ``name``'s routing at ``version`` (loading a
        cold version first, off the serve path), clear any canary on it,
        and drain in-flight batches on the previously active version."""
        with self._lock:
            versions = self._models.get(name)
            mv = versions.get(int(version)) if versions else None
            if mv is None:
                raise UnknownModelError(
                    f"unknown version {name}:v{version}")
        if load:
            self._ensure_loaded(mv, warmup=warmup)
        with self._lock:
            old_v = self._active.get(name)
            self._active[name] = mv.version
            if load:
                mv.state = "ready"
            can = self._canary.get(name)
            if can is not None and can.version == mv.version:
                del self._canary[name]
            old = None
            if old_v is not None and old_v != mv.version:
                old = versions.get(old_v)
        if old is not None:
            drained = old.drain(drain_timeout)
            old.state = "retired"
            self._event(f"{name}: v{old_v} -> v{mv.version} "
                        f"(old drained={drained})")
        else:
            self._event(f"{name}: active -> v{mv.version}")
        self._save()
        return mv

    def rollback(self, name: str, drain_timeout: float = 10.0
                 ) -> ModelVersion:
        """Point routing back at the newest loaded non-active version."""
        with self._lock:
            versions = self._models.get(name)
            if not versions:
                raise UnknownModelError(f"unknown model {name!r}")
            active = self._active.get(name)
            candidates = [v for v in sorted(versions, reverse=True)
                          if v != active and
                          versions[v].model is not None and
                          versions[v].state != "failed"]
            if not candidates:
                raise RegistryError(
                    f"no loaded version of {name!r} to roll back to")
        return self.promote(name, candidates[0],
                            drain_timeout=drain_timeout)

    def undeploy(self, name: str, version: Optional[int] = None,
                 drain_timeout: float = 10.0) -> List[int]:
        """Remove one version (refusing the active one while siblings
        remain) or, with ``version=None``, the whole model.  Removed
        versions drain their in-flight batches before release."""
        with self._lock:
            versions = self._models.get(name)
            if not versions:
                raise UnknownModelError(f"unknown model {name!r}")
            if version is None:
                targets = list(versions.values())
                del self._models[name]
                self._active.pop(name, None)
                self._canary.pop(name, None)
            else:
                v = int(version)
                mv = versions.get(v)
                if mv is None:
                    raise UnknownModelError(
                        f"unknown version {name}:v{version}")
                if self._active.get(name) == v and len(versions) > 1:
                    raise RegistryError(
                        f"{mv.key} is the active version; promote "
                        f"another version first")
                targets = [mv]
                del versions[v]
                if self._active.get(name) == v:
                    del self._active[name]
                can = self._canary.get(name)
                if can is not None and can.version == v:
                    del self._canary[name]
        removed = []
        for mv in targets:
            mv.drain(drain_timeout)
            if mv.model is not None:
                mv.model.release()
                mv.model = None
            mv.state = "retired"
            removed.append(mv.version)
        self._event(f"undeployed {name} versions {removed}")
        self._save()
        return removed

    def set_canary(self, name: str, version: int, weight: float,
                   error_threshold: Optional[float] = None,
                   min_requests: Optional[int] = None) -> CanaryState:
        """Split ``weight`` of ``name``'s default traffic onto
        ``version`` (which must exist; callers load cold versions via
        deploy/promote first)."""
        with self._lock:
            versions = self._models.get(name)
            mv = versions.get(int(version)) if versions else None
            if mv is None:
                raise UnknownModelError(
                    f"unknown version {name}:v{version}")
            can = CanaryState(
                version, weight,
                self.canary_error_threshold if error_threshold is None
                else error_threshold,
                self.canary_min_requests if min_requests is None
                else min_requests)
            self._canary[name] = can
        self._event(f"canary: {mv.key} at weight {can.weight}")
        self._save()
        return can

    def clear_canary(self, name: str):
        with self._lock:
            self._canary.pop(name, None)
        self._event(f"canary cleared for {name!r}")
        self._save()

    # ------------------------------------------------------------------
    # routing
    # ------------------------------------------------------------------
    @staticmethod
    def _canary_fraction(uri: str) -> float:
        """Deterministic uri -> [0, 1): the same record uri always lands
        on the same side of the split, across processes and restarts."""
        return (zlib.crc32(str(uri).encode("utf-8")) % 10_000) / 10_000.0

    def route(self, name: Optional[str] = None,
              version: Optional[int] = None, uri: str = "") -> ModelVersion:
        """Resolve a record to a loaded :class:`ModelVersion`: explicit
        ``version`` pins; otherwise the canary (when the uri hashes
        under its weight) or the active version."""
        name = name or self.default_model
        with self._lock:
            versions = self._models.get(name)
            if not versions:
                raise UnknownModelError(f"unknown model {name!r}")
            if version is not None:
                mv = versions.get(int(version))
                if mv is None or mv.model is None:
                    raise UnknownModelError(
                        f"unknown or unloaded version {name}:v{version}")
                return mv
            can = self._canary.get(name)
            if can is not None and self._canary_fraction(uri) < can.weight:
                mv = versions.get(can.version)
                if mv is not None and mv.model is not None:
                    return mv
            active = self._active.get(name)
            mv = versions.get(active) if active is not None else None
            if mv is None or mv.model is None:
                raise UnknownModelError(
                    f"model {name!r} has no active loaded version")
            return mv

    def record_result(self, mv: ModelVersion, error: bool = False,
                      n: int = 1) -> bool:
        """Account ``n`` served (or failed) records against ``mv``; when
        ``mv`` is the canary and its error rate crosses the threshold,
        auto-roll the canary back.  Returns True iff a rollback fired."""
        can = None
        with self._lock:
            mv.requests += n
            if error:
                mv.errors += n
            c = self._canary.get(mv.name)
            if c is not None and c.version == mv.version:
                c.requests += n
                if error:
                    c.errors += n
                if (c.requests >= c.min_requests and
                        c.errors > c.error_threshold * c.requests):
                    del self._canary[mv.name]
                    mv.state = "failed"
                    can = c
        if can is not None:
            self._event(
                f"canary {mv.key} rolled back: error rate "
                f"{can.errors}/{can.requests} exceeds "
                f"{can.error_threshold:.2f}")
            self._save()
            return True
        return False

    def routed_versions(self) -> List[ModelVersion]:
        """Every loaded version traffic can currently reach (active +
        canary per model) — the warmup/bench surface."""
        out = []
        with self._lock:
            for name, versions in self._models.items():
                wanted = {self._active.get(name)}
                can = self._canary.get(name)
                if can is not None:
                    wanted.add(can.version)
                for v in wanted:
                    mv = versions.get(v) if v is not None else None
                    if mv is not None and mv.model is not None:
                        out.append(mv)
        return out

    # ------------------------------------------------------------------
    # persistence
    # ------------------------------------------------------------------
    def _save(self):
        uri = self.manifest_uri
        if uri is None:
            return
        with self._lock:
            data = {"default_model": self.default_model, "models": {}}
            for name, versions in self._models.items():
                can = self._canary.get(name)
                data["models"][name] = {
                    "active": self._active.get(name),
                    "canary": can.stats() if can is not None else None,
                    "versions": [
                        {"version": mv.version, "path": mv.path,
                         "state": mv.state, "created": mv.created,
                         "dtype": mv.dtype,
                         "calibration": mv.calibration}
                        for mv in sorted(versions.values(),
                                         key=lambda m: m.version)]}
        file_io.write_bytes_atomic(
            uri, json.dumps(data, indent=2).encode())

    def recover(self, load: bool = True,
                warmup: Optional[Callable] = None,
                save: bool = True) -> "ModelRegistry":
        """Rebuild the deployed set from the manifest.  With ``load``,
        the active (and canary) version of each model is re-loaded from
        its path and warmed; other versions stay ``cold`` (re-loadable
        via promote).  Load failures are logged and leave the version
        ``failed`` — the server still starts and dead-letters traffic
        for that model rather than crashing.

        Idempotent over loaded state: a version whose in-memory object
        already holds a loaded model is kept, not replaced with a cold
        shell — fleet workers call recover() on every manifest change
        (docs/serving-fleet.md) and must not drop live models mid-serve."""
        uri = self.manifest_uri
        if uri is None or not file_io.exists(uri):
            return self
        data = json.loads(file_io.read_bytes(uri).decode())
        with self._lock:
            self.default_model = data.get("default_model",
                                          self.default_model)
            for name, m in (data.get("models") or {}).items():
                versions = self._models.setdefault(name, {})
                for vd in m.get("versions", []):
                    v = int(vd["version"])
                    prior = versions.get(v)
                    if prior is not None and prior.model is not None:
                        continue   # already live in this process
                    mv = ModelVersion(name, v, path=vd.get("path"),
                                      dtype=vd.get("dtype", "f32"),
                                      calibration=vd.get("calibration"))
                    mv.created = vd.get("created", mv.created)
                    mv.state = "cold"
                    versions[v] = mv
                if m.get("active") is not None:
                    self._active[name] = int(m["active"])
                can = m.get("canary")
                if can:
                    self._canary[name] = CanaryState(
                        can["version"], can["weight"],
                        can.get("error_threshold",
                                self.canary_error_threshold),
                        can.get("min_requests", self.canary_min_requests))
        if load:
            for mv in self._cold_routed():
                try:
                    self._ensure_loaded(mv, warmup=warmup)
                    mv.state = "ready"
                    self._event(f"recovered {mv.key} from {mv.path}")
                except Exception as e:  # noqa: BLE001 - keep serving rest
                    logger.warning("recover: %s failed to load: %s",
                                   mv.key, e)
            if save:
                # follower workers refresh with save=False: only the
                # control-plane owner may rewrite the shared manifest
                self._save()
        return self

    def _cold_routed(self) -> List[ModelVersion]:
        out = []
        with self._lock:
            for name, versions in self._models.items():
                wanted = {self._active.get(name)}
                can = self._canary.get(name)
                if can is not None:
                    wanted.add(can.version)
                for v in wanted:
                    mv = versions.get(v) if v is not None else None
                    if mv is not None and mv.model is None and mv.path:
                        out.append(mv)
        return out

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------
    def stats(self) -> dict:
        """Per-model/per-version control-plane + InferenceSummary stats
        (the ``models`` payload in ``pipeline_stats()`` and the
        ``zoo-serving status`` output)."""
        with self._lock:
            names = {name: (dict(versions), self._active.get(name),
                            self._canary.get(name))
                     for name, versions in self._models.items()}
            events = list(self.events)
        out = {}
        for name, (versions, active, can) in names.items():
            out[name] = {
                "active": active,
                "canary": can.stats() if can is not None else None,
                "versions": {v: mv.stats()
                             for v, mv in sorted(versions.items())}}
        return {"models": out, "events": events}


# ---------------------------------------------------------------------------
# file-RPC control plane (zoo-serving deploy/undeploy/promote/status)
# ---------------------------------------------------------------------------

def _control_dir(root: str) -> str:
    scheme, path = file_io.split_scheme(root)
    if scheme != "file":
        raise RegistryError(
            "the control plane is file-RPC on the serving host; "
            f"registry root {root!r} is not a local path")
    return os.path.join(path, "control")


def control_request(root: str, op: str, timeout: float = 180.0,
                    poll: float = 0.05, **kw) -> dict:
    """Send one control op to the serving process and wait for its
    response (exponential backoff up to 0.5s between polls)."""
    ctl = _control_dir(root)
    os.makedirs(ctl, exist_ok=True)
    rid = uuid.uuid4().hex[:12]
    req = os.path.join(ctl, f"{rid}.req.json")
    res = os.path.join(ctl, f"{rid}.res.json")
    tmp = req + ".tmp"
    with open(tmp, "w") as f:
        json.dump(dict(kw, op=op, id=rid), f)
    os.replace(tmp, req)  # atomic: the server never reads a partial file
    deadline = time.monotonic() + timeout
    interval = poll
    while time.monotonic() < deadline:
        if os.path.exists(res):
            with open(res) as f:
                data = json.load(f)
            os.unlink(res)
            return data
        time.sleep(interval)
        interval = min(interval * 2, 0.5)
    try:
        os.unlink(req)  # withdraw so a late server doesn't act on it
    except OSError:
        pass
    raise TimeoutError(
        f"no response to {op!r} within {timeout}s — is the serving "
        f"process running in registry mode?")


class RegistryControlServer:
    """Server half of the control plane: a daemon thread that applies
    ``deploy``/``undeploy``/``promote``/``canary``/``stats`` requests
    dropped into ``<root>/control`` and writes responses in place.
    Deploys run on this thread — warmup compiles never block the serve
    loop."""

    def __init__(self, registry: ModelRegistry, root: str, serving=None,
                 poll_interval: float = 0.2):
        self.registry = registry
        self.serving = serving  # RoutedClusterServing (warmup + stats)
        self.dir = _control_dir(root)
        os.makedirs(self.dir, exist_ok=True)
        self.poll_interval = poll_interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "RegistryControlServer":
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="serving-registry-ctl")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self):
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001 - control must survive
                logger.warning("control poll failed: %s", e)
            self._stop.wait(self.poll_interval)

    def poll_once(self) -> int:
        """Handle every pending request file; returns how many."""
        try:
            names = sorted(n for n in os.listdir(self.dir)
                           if n.endswith(".req.json"))
        except FileNotFoundError:
            return 0
        handled = 0
        for name in names:
            path = os.path.join(self.dir, name)
            try:
                with open(path) as f:
                    req = json.load(f)
                os.unlink(path)
            except (OSError, ValueError):
                continue
            resp = self._handle(req)
            res = os.path.join(self.dir,
                               name[:-len(".req.json")] + ".res.json")
            tmp = res + ".tmp"
            with open(tmp, "w") as f:
                json.dump(resp, f)
            os.replace(tmp, res)
            handled += 1
        return handled

    def _warmup_fn(self):
        if self.serving is not None:
            return self.serving.registry_warmup()
        return None

    def _handle(self, req: dict) -> dict:
        op = req.get("op")
        try:
            if op == "deploy":
                activate = bool(req.get("activate", True))
                weight = req.get("canary_weight")
                mv = self.registry.deploy(
                    req.get("model"), path=req["path"],
                    warmup=self._warmup_fn(),
                    activate=activate and weight is None,
                    quantize=bool(req.get("quantize", False)),
                    calibration=req.get("calibration"))
                if weight is not None:
                    self.registry.set_canary(mv.name, mv.version,
                                             float(weight))
                return {"ok": True, "model": mv.name,
                        "version": mv.version, "state": mv.state,
                        "dtype": mv.dtype}
            if op == "promote":
                mv = self.registry.promote(
                    req["model"], int(req["version"]),
                    warmup=self._warmup_fn())
                return {"ok": True, "model": mv.name,
                        "version": mv.version}
            if op == "undeploy":
                version = req.get("version")
                removed = self.registry.undeploy(
                    req["model"],
                    int(version) if version is not None else None)
                return {"ok": True, "model": req["model"],
                        "removed": removed}
            if op == "canary":
                mv_name = req["model"]
                with self.registry._lock:
                    versions = self.registry._models.get(mv_name) or {}
                    mv = versions.get(int(req["version"]))
                if mv is not None and mv.model is None:
                    self.registry._ensure_loaded(mv, self._warmup_fn())
                can = self.registry.set_canary(
                    mv_name, int(req["version"]), float(req["weight"]))
                return {"ok": True, "model": mv_name,
                        "canary": can.stats()}
            if op == "stats":
                if self.serving is not None:
                    return {"ok": True,
                            "stats": self.serving.pipeline_stats()}
                return {"ok": True, "stats": self.registry.stats()}
            return {"ok": False, "error": f"unknown op {op!r}"}
        except Exception as e:  # noqa: BLE001 - report, don't crash
            return {"ok": False, "error": str(e) or repr(e)}
