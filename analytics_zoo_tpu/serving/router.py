"""RoutedClusterServing: the multi-model data plane over ModelRegistry.

Extends the PR-1 pipelined engine (docs/serving-pipeline.md) so one
serving process carries many named models: wire records gain optional
``model``/``version`` fields (absent fields route to the registry's
default model, so single-model clients keep working unchanged), the
compute stage resolves each record through :meth:`ModelRegistry.route`
at dispatch time — a hot-swap therefore takes effect for every record
not yet dispatched, even ones already decoded — and groups dispatch by
``(model, version, bucket)``.  Records that resolve to an unknown model
or whose batch fails are **dead-lettered**: an error payload lands in
the results map under the record uri (``{"error": ..., "model": ...,
"version": ...}``), so clients see a structured failure instead of a
silent timeout (:meth:`OutputQueue.wait_all` surfaces these as
:class:`~analytics_zoo_tpu.serving.client.ServingError`).

Each dispatched batch holds an in-flight ref on its
:class:`ModelVersion` until the writer commits its results, which is
what :meth:`ModelRegistry.promote`'s drain waits on — the old version
is not released while any of its batches is still in the pipe.
"""

from __future__ import annotations

import json
import logging
import queue
import time
from typing import Optional

import numpy as np

from .admission import SHED_EXPIRED, now_ms
from .cluster_serving import (ClusterServing, ClusterServingHelper,
                              _SENTINEL, pick_bucket)
from .registry import ModelRegistry

logger = logging.getLogger("analytics_zoo_tpu.serving.router")


def _as_text(v):
    return v.decode() if isinstance(v, (bytes, bytearray)) else v


def _as_version(v) -> Optional[int]:
    if v is None or v == "" or v == b"":
        return None
    return int(_as_text(v))


class RoutedClusterServing(ClusterServing):
    """Pipelined serving with per-record model/version routing."""

    def __init__(self, registry: ModelRegistry,
                 helper: Optional[ClusterServingHelper] = None,
                 backend=None, config_path: Optional[str] = None,
                 summary=None, preprocessing=None):
        self.registry = registry
        super().__init__(model=None, helper=helper, backend=backend,
                         config_path=config_path, summary=summary,
                         preprocessing=preprocessing)
        if not self.pipelined:
            logger.warning("registry routing requires the pipelined "
                           "engine; ignoring params.pipelined=false")
        self.pipelined = True

    def _default_model(self):
        # models live in the registry, not on the serving instance
        return None

    # -- decode stage: carry the routing fields ------------------------
    def _ready_item(self, meta, rec, arr):
        # Redis transports hand back bytes keys *and* values; normalize
        # here so routing compares strings/ints everywhere downstream
        model = _as_text(rec.get("model") or rec.get(b"model"))
        try:
            version = _as_version(rec.get("version") or rec.get(b"version"))
        except (TypeError, ValueError):
            version = None
        return (meta, arr, (model, version))

    def _on_decode_error(self, rid, rec, exc):
        uri = rec.get("uri", rid)
        model = _as_text(rec.get("model") or rec.get(b"model"))
        self._dead_letter([(uri, f"decode failed: {exc}", model, None)])

    # -- compute stage: resolve routes, group, dispatch per version ----
    def _dispatch_batch(self, batch_items, write_q: queue.Queue):
        # shed deadline-expired records before routing (same policy as
        # the base engine's dispatch shed point)
        at = now_ms()
        live, expired = [], []
        for it in batch_items:
            if self.admission.expired(it[0].deadline_at_ms, at):
                expired.append(it[0])
            else:
                live.append(it)
        self._shed(expired, SHED_EXPIRED)
        groups, dead = {}, []
        for meta, arr, (model, version) in live:
            try:
                mv = self.registry.route(model, version, uri=meta.uri)
            except Exception as e:  # unknown model/version -> dead-letter
                dead.append((meta.uri, str(e) or repr(e), model, version))
                continue
            # (model, version, dtype) + the bucket picked per group is
            # the full dispatch key: an int8 canary version never shares
            # a batch (or a compile-cache entry) with its f32 baseline
            groups.setdefault((mv.name, mv.version, mv.dtype),
                              (mv, []))[1].append((meta, arr))
        if dead:
            self._dead_letter(dead)
        for mv, items in groups.values():
            self._dispatch_to_version(mv, items, write_q)

    def _dispatch_to_version(self, mv, items, write_q: queue.Queue):
        metas = [it[0] for it in items]
        arrays = [it[1] for it in items]
        n = len(arrays)
        bucket = pick_bucket(n, self.buckets)
        mv.acquire()  # held until the writer commits (promote drains it)
        try:
            batch = np.stack(arrays)
            if n < bucket:
                pad = np.repeat(batch[-1:], bucket - n, axis=0)
                batch = np.concatenate([batch, pad])
            disp_ts_ms = now_ms()
            t0 = time.perf_counter()
            out = mv.model.predict_async(batch)
        except Exception as e:
            mv.release()
            self.registry.record_result(mv, error=True, n=n)
            self._dead_letter([(m.uri, f"dispatch failed: {e}",
                                mv.name, mv.version) for m in metas])
            return
        self.summary.record_stage("dispatch", time.perf_counter() - t0)
        self._count(batches=1)
        with self._ctr_lock:
            self.bucket_counts[f"{mv.key}:{bucket}:{mv.dtype}"] += 1
        write_q.put((metas, n, t0, disp_ts_ms, out, mv))

    # -- write stage: per-version accounting + refcount release --------
    def _writer_loop(self, write_q: queue.Queue):
        while True:
            item = write_q.get()
            if item is _SENTINEL:
                return
            metas, n, t_disp, disp_ts_ms, out, mv = item
            try:
                preds = np.asarray(out)[:n]  # host transfer = sync point
            except Exception as e:
                self.registry.record_result(mv, error=True, n=n)
                mv.release()
                self._dead_letter([(m.uri, f"predict failed: {e}",
                                    mv.name, mv.version) for m in metas])
                continue
            dt = time.perf_counter() - t_disp
            self.summary.record_batch(n, dt)
            self.summary.record_stage("compute", dt, batch_size=n)
            self.admission.observe_batch(n, dt)
            mv.summary.record_batch(n, dt)
            done_ms = now_ms()
            t0 = time.perf_counter()
            results = {}
            for meta, p in zip(metas, preds):
                obj = self._format_result(p)
                obj["timing"] = self._timing_payload(
                    meta, disp_ts_ms, dt * 1e3, done_ms)
                self._record_row_timing(obj["timing"])
                results[meta.uri] = json.dumps(obj).encode()
            self.db.put_results(results)
            now = time.perf_counter()
            self.summary.record_stage("write", now - t0, batch_size=n)
            for meta in metas:
                self.summary.record_stage("e2e", now - meta.t_in)
                mv.summary.record_stage("e2e", now - meta.t_in)
            self._count(results_out=n)
            self.registry.record_result(mv, error=False, n=n)
            mv.release()

    # -- dead letters: error payloads in the results map ---------------
    def _dead_letter(self, entries):
        """entries: [(uri, message, model, version)] — committed to the
        results map so clients get a structured error, never a silent
        drop."""
        results = {}
        for uri, msg, model, version in entries:
            results[uri] = json.dumps(
                {"error": msg, "model": model, "version": version}).encode()
        try:
            self.db.put_results(results)
        except Exception as e:  # noqa: BLE001 - keep the stage alive
            logger.warning("dead-letter write failed for %d records: %s",
                           len(entries), e)
        self._count(dead_letters=len(entries))

    # -- registry-aware warmup + stats ---------------------------------
    def registry_warmup(self):
        """``warmup(model)`` callable for registry deploys: AOT-compile
        every padding bucket off the serve path; raises on failure so
        deploy rolls back rather than swapping onto a broken version."""
        shape, buckets = tuple(self.helper.image_shape), list(self.buckets)
        return lambda inf: inf.warm(shape, buckets)

    def deploy(self, name: Optional[str] = None, model=None,
               path: Optional[str] = None, activate: bool = True,
               canary_weight: Optional[float] = None, warmup: bool = True,
               quantize: bool = False,
               calibration: Optional[str] = None):
        """Deploy into this server's registry with its bucket warmup;
        ``canary_weight`` deploys as a canary instead of activating;
        ``quantize`` deploys an int8 version (with optional exported
        ``calibration`` scales) for side-by-side comparison against the
        f32 baseline."""
        mv = self.registry.deploy(
            name, model=model, path=path,
            warmup=self.registry_warmup() if warmup else None,
            activate=activate and canary_weight is None,
            drain_timeout=self.helper.drain_timeout,
            quantize=quantize, calibration=calibration)
        if canary_weight is not None:
            self.registry.set_canary(mv.name, mv.version,
                                     float(canary_weight))
        return mv

    def warmup(self, shape=None) -> dict:
        """Best-effort warm of every currently routed version (the
        deploy path warms strictly; this covers recovered sets)."""
        shape = tuple(shape if shape is not None else
                      self.helper.image_shape)
        times = {}
        for mv in self.registry.routed_versions():
            for b in self.buckets:
                try:
                    t = mv.model.warm(shape, [b])
                except Exception as e:  # noqa: BLE001 - best-effort
                    logger.warning("warmup: %s bucket %d failed: %s",
                                   mv.key, b, e)
                    continue
                times[f"{mv.key}:{b}:{mv.dtype}"] = t[b]
        return times

    def pipeline_stats(self) -> dict:
        out = super().pipeline_stats()
        out["models"] = self.registry.stats()["models"]
        return out
