"""Cluster Serving: always-on streaming inference service.

Parity: ``zoo/.../serving/ClusterServing.scala`` + client
``pyzoo/zoo/serving/client.py``; the model registry / router layer
(versioned hot-swap, canary rollout) is TPU-rebuild-native
(docs/model-registry.md), as are the serving fleet + deadline-aware
admission control (docs/serving-fleet.md).
"""

from .admission import (AdaptiveBatcher, AdmissionController,
                        BacklogAutoscaler, SHED_CAPACITY, SHED_DEADLINE,
                        SHED_EXPIRED, TenantScheduler)
from .client import (API, GenerationResult, InputQueue, OutputQueue,
                     ServingError, ServingRejected, ServingResult,
                     ServingTimeout)
from .cluster_serving import (ClusterServing, ClusterServingHelper,
                              EchoStubModel, RecordMeta, pick_bucket,
                              power_of_two_buckets)
from .fleet import ServingFleet, fleet_status, read_autoscale_trace
from .generation import (ContinuousBatchScheduler, GenRequest,
                         StubDecodeEngine, TransformerDecodeEngine)
from .queue_backend import (DeliveryLedger, FileStreamQueue,
                            InProcessStreamQueue, StreamQueue,
                            get_queue_backend)
from .shard_fabric import (LocalShardFabric, ShardedStreamQueue,
                           parse_shard_spec)
from .routing import (GenerateRouter, RouteDecision, RoutedGenerateQueue,
                      WorkerIntakeQueue, WorkerReport)
from .socket_queue import SocketStreamQueue, StreamQueueBroker
from .registry import (CanaryState, DeployError, ModelRegistry,
                       ModelVersion, RegistryControlServer, RegistryError,
                       UnknownModelError, control_request)
from .router import RoutedClusterServing

__all__ = ["InputQueue", "OutputQueue", "API", "ServingError",
           "ServingRejected", "ServingResult", "ServingTimeout",
           "ClusterServing", "ClusterServingHelper", "EchoStubModel",
           "RecordMeta", "StreamQueue",
           "InProcessStreamQueue", "FileStreamQueue", "get_queue_backend",
           "pick_bucket", "power_of_two_buckets", "ModelRegistry",
           "ModelVersion", "CanaryState", "RegistryError",
           "UnknownModelError", "DeployError", "RegistryControlServer",
           "control_request", "RoutedClusterServing",
           "AdmissionController", "AdaptiveBatcher", "BacklogAutoscaler",
           "SHED_DEADLINE", "SHED_EXPIRED", "SHED_CAPACITY",
           "TenantScheduler", "ServingFleet", "fleet_status",
           "read_autoscale_trace", "DeliveryLedger", "SocketStreamQueue",
           "StreamQueueBroker", "ShardedStreamQueue", "LocalShardFabric",
           "parse_shard_spec",
           "GenerationResult", "ContinuousBatchScheduler", "GenRequest",
           "StubDecodeEngine", "TransformerDecodeEngine",
           "GenerateRouter", "RouteDecision", "RoutedGenerateQueue",
           "WorkerIntakeQueue", "WorkerReport"]
