"""Cluster Serving: always-on streaming inference service.

Parity: ``zoo/.../serving/ClusterServing.scala`` + client
``pyzoo/zoo/serving/client.py``; the model registry / router layer
(versioned hot-swap, canary rollout) is TPU-rebuild-native
(docs/model-registry.md).
"""

from .client import API, InputQueue, OutputQueue, ServingError
from .cluster_serving import (ClusterServing, ClusterServingHelper,
                              pick_bucket, power_of_two_buckets)
from .queue_backend import (FileStreamQueue, InProcessStreamQueue,
                            StreamQueue, get_queue_backend)
from .registry import (CanaryState, DeployError, ModelRegistry,
                       ModelVersion, RegistryControlServer, RegistryError,
                       UnknownModelError, control_request)
from .router import RoutedClusterServing

__all__ = ["InputQueue", "OutputQueue", "API", "ServingError",
           "ClusterServing", "ClusterServingHelper", "StreamQueue",
           "InProcessStreamQueue", "FileStreamQueue", "get_queue_backend",
           "pick_bucket", "power_of_two_buckets", "ModelRegistry",
           "ModelVersion", "CanaryState", "RegistryError",
           "UnknownModelError", "DeployError", "RegistryControlServer",
           "control_request", "RoutedClusterServing"]
