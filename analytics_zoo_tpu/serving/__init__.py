"""Cluster Serving: always-on streaming inference service.

Parity: ``zoo/.../serving/ClusterServing.scala`` + client
``pyzoo/zoo/serving/client.py``.
"""

from .client import API, InputQueue, OutputQueue
from .cluster_serving import (ClusterServing, ClusterServingHelper,
                              pick_bucket, power_of_two_buckets)
from .queue_backend import (FileStreamQueue, InProcessStreamQueue,
                            StreamQueue, get_queue_backend)

__all__ = ["InputQueue", "OutputQueue", "API", "ClusterServing",
           "ClusterServingHelper", "StreamQueue", "InProcessStreamQueue",
           "FileStreamQueue", "get_queue_backend", "pick_bucket",
           "power_of_two_buckets"]
