"""Telemetry end-to-end smoke (``scripts/trace-smoke``; CI fast tier).

Proves the observability spine on the CPU backend with the production
code paths — real launcher, real process-infeed workers, real kill:

1. **Trace leg** — a 3-step :mod:`launcher.trace_train` run under
   ``zoo-launch ... --trace-dir`` with the process infeed backend must
   leave a Chrome-trace JSON that (a) parses and passes a schema check,
   (b) contains ``train/step``, ``train/dispatch``,
   ``train/device_sync`` and ``ckpt/write`` spans, (c) shows an
   ``infeed/wait`` span *nested inside* a ``train/step`` span on the
   same pid/tid, and (d) carries ``infeed/transform`` timelines from
   the worker *processes* (foreign pids, ``zoo-infeed-*`` process-name
   metadata) plus a ``metrics-<pid>.json`` snapshot.
2. **Flight leg** — the same job with ``ZOO_TPU_FAULT=step:kill@2``
   armed dies mid-run and must leave ``debug/flight-*.json`` whose
   tail records the ``fault/step`` event for the killed step, with a
   metrics snapshot attached.

Exit 0 and ``TRACE_SMOKE_OK`` on success; 1 with the captured worker
logs on any violated assertion.
"""

from __future__ import annotations

import argparse
import glob
import io
import json
import os
import shutil
import sys
import tempfile

from ..utils.faults import ENV_SPEC, ENV_STATE

_SCRIPT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "trace_train.py")


def _run_train(ckpt_dir: str, trace_dir: str, steps: int,
               extra_env=None, **launch_kw):
    """One trace_train job under ``zoo-launch --trace-dir``; returns
    ``(rc, merged_output)``. The process infeed backend is forced so the
    trace must show per-worker timelines, not thread rows."""
    from .launch import launch

    env = {"JAX_PLATFORMS": "cpu", ENV_SPEC: "", ENV_STATE: "",
           "ZOO_TPU_INFEED_BACKEND": "process",
           "ZOO_TPU_TRANSFORM_WORKERS": "2"}
    env.update(extra_env or {})
    cap = io.StringIO()
    rc = launch([_SCRIPT, ckpt_dir, str(steps)], num_hosts=1, env=env,
                stream=cap, trace_dir=trace_dir, **launch_kw)
    return rc, cap.getvalue()


def _load_traces(trace_dir: str):
    """Parse every ``trace-*.json`` in the dir; schema-check as we go.
    Returns ``[(path, payload)]`` or raises AssertionError."""
    out = []
    for path in sorted(glob.glob(os.path.join(trace_dir, "trace-*.json"))):
        with open(path) as f:
            payload = json.load(f)
        assert isinstance(payload.get("traceEvents"), list), \
            f"{path}: traceEvents missing/not a list"
        for ev in payload["traceEvents"]:
            assert isinstance(ev, dict), f"{path}: non-dict event"
            assert ev.get("ph") in ("B", "E", "i", "M"), \
                f"{path}: bad ph {ev.get('ph')!r}"
            assert "name" in ev and "pid" in ev, \
                f"{path}: event missing name/pid: {ev}"
            if ev["ph"] != "M":
                assert isinstance(ev.get("ts"), int), \
                    f"{path}: non-M event without integer ts: {ev}"
                assert "tid" in ev, f"{path}: event without tid: {ev}"
        out.append((path, payload))
    return out


def _intervals(events, name):
    """B/E pairs for ``name`` as ``[(pid, tid, t0, t1)]`` (per pid/tid
    stack pairing, tolerant of nesting of the same name)."""
    stacks, pairs = {}, []
    for ev in events:
        if ev.get("name") != name or ev["ph"] not in ("B", "E"):
            continue
        key = (ev["pid"], ev["tid"])
        if ev["ph"] == "B":
            stacks.setdefault(key, []).append(ev["ts"])
        elif stacks.get(key):
            pairs.append((key[0], key[1], stacks[key].pop(), ev["ts"]))
    return pairs


def run_smoke(steps: int = 3, stream=None) -> int:
    out = stream if stream is not None else sys.stdout
    work = tempfile.mkdtemp(prefix="zoo_trace_smoke_")

    def fail(msg, log=""):
        if log:
            out.write(log)
        out.write(f"TRACE_SMOKE_FAIL: {msg}\n")
        return 1

    try:
        # -- leg 1: traced 3-step run ----------------------------------
        td = os.path.join(work, "traces")
        rc, log = _run_train(os.path.join(work, "ckpt"), td, steps)
        if rc != 0:
            return fail(f"traced run failed rc={rc}", log)
        try:
            traces = _load_traces(td)
        except AssertionError as e:
            return fail(f"trace schema violation: {e}", log)
        if not traces:
            return fail(f"no trace-*.json written under {td}", log)
        # the worker's trace is the one that trained
        trainer = [(p, t) for p, t in traces
                   if any(e.get("name") == "train/step"
                          for e in t["traceEvents"])]
        if not trainer:
            return fail("no trace file contains train/step spans", log)
        path, trace = trainer[0]
        evs = trace["traceEvents"]
        names = {e["name"] for e in evs if e["ph"] != "M"}
        for want in ("train/step", "train/dispatch", "train/device_sync",
                     "ckpt/write", "infeed/wait", "infeed/transform"):
            if want not in names:
                return fail(f"{path}: span {want!r} missing "
                            f"(have {sorted(names)})", log)
        # nesting: some infeed/wait interval inside a train/step interval
        # on the same pid/tid (the consumer thread)
        steps_iv = _intervals(evs, "train/step")
        waits_iv = _intervals(evs, "infeed/wait")
        nested = any(sp == wp and st == wt and s0 <= w0 and w1 <= s1
                     for (sp, st, s0, s1) in steps_iv
                     for (wp, wt, w0, w1) in waits_iv)
        if not nested:
            return fail(f"{path}: no infeed/wait span nests inside a "
                        f"train/step span on the same pid/tid", log)
        # per-process worker timelines: infeed/transform events must come
        # from pids other than the trainer's, under zoo-infeed-* rows
        own_pid = trace.get("otherData", {}).get("pid")
        foreign = [e for e in evs if e["name"] == "infeed/transform"
                   and e["pid"] != own_pid]
        if not foreign:
            return fail(f"{path}: no infeed/transform events from worker "
                        f"processes (process backend timelines missing)",
                        log)
        rows = {e["args"]["name"] for e in evs if e["ph"] == "M"
                and e["name"] == "process_name"}
        if not any(r.startswith("zoo-infeed-") for r in rows):
            return fail(f"{path}: no zoo-infeed-* process_name metadata "
                        f"(rows: {sorted(rows)})", log)
        if not glob.glob(os.path.join(td, "metrics-*.json")):
            return fail(f"no metrics-*.json exported under {td}", log)
        out.write(f"TRACE_LEG_OK spans={len(names)} "
                  f"workers={len({e['pid'] for e in foreign})}\n")

        # -- leg 2: kill@2 leaves a flight dump ------------------------
        td2 = os.path.join(work, "traces-fault")
        state = os.path.join(work, "fault-state")
        os.makedirs(state)
        rc, log = _run_train(
            os.path.join(work, "ckpt-fault"), td2, steps,
            extra_env={ENV_SPEC: "step:kill@2", ENV_STATE: state})
        if rc == 0:
            return fail("step:kill@2 never fired (rc=0)", log)
        dumps = sorted(glob.glob(os.path.join(td2, "debug",
                                              "flight-*.json")))
        if not dumps:
            return fail(f"no debug/flight-*.json under {td2}", log)
        with open(dumps[-1]) as f:
            flight = json.load(f)
        spans = flight.get("spans") or []
        # the fault event is recorded immediately before the dump — it
        # must sit at the tail of the ring (a couple of infeed-thread
        # events may race in behind it)
        tail = spans[-5:]
        hit = [e for e in tail if e.get("name") == "fault/step"]
        if not hit or hit[-1].get("args", {}).get("step") != 2:
            return fail(
                f"{dumps[-1]}: ring tail does not record fault/step@2 "
                f"(tail: {[e.get('name') for e in tail]})", log)
        if not isinstance(flight.get("metrics"), dict):
            return fail(f"{dumps[-1]}: no metrics snapshot in flight "
                        f"dump", log)
        if "ZOO_TPU_FAULT" not in (flight.get("reason") or ""):
            return fail(f"{dumps[-1]}: reason does not name the fault "
                        f"({flight.get('reason')!r})", log)
        out.write(f"FLIGHT_LEG_OK dump={os.path.basename(dumps[-1])} "
                  f"ring={len(spans)}\n")

        out.write(f"TRACE_SMOKE_OK steps={steps}\n")
        return 0
    finally:
        shutil.rmtree(work, ignore_errors=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="trace-smoke")
    ap.add_argument("--steps", type=int, default=3)
    args = ap.parse_args(argv)
    return run_smoke(steps=args.steps)


if __name__ == "__main__":
    sys.exit(main())
