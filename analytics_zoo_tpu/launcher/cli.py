"""``zoo-launch`` CLI — the init_spark_on_yarn analogue.

Usage::

    zoo-launch --hosts 2 train.py --epochs 3
    zoo-launch --hosts 4 --on-failure report --env ZOO_TPU_SEED=7 train.py
    zoo-launch --hosts-file hosts.txt train.py   # localhost rows today

Everything after the script path is passed to the script verbatim.
Exits with the first nonzero worker exit code (0 on success).
"""

from __future__ import annotations

import argparse
import logging
import sys

from .launch import LaunchError, launch


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="zoo-launch",
        description="Launch a training script as an N-process job: "
                    "coordinator bootstrap, ZOO_TPU_* env propagation, "
                    "prefixed log fan-in, child health supervision.")
    ap.add_argument("--hosts", "-n", type=int, default=None, metavar="N",
                    help="number of worker processes (default: 1, or the "
                         "hosts-file slot total)")
    ap.add_argument("--hosts-file", default=None, metavar="FILE",
                    help="MPI-style 'host [slots]' file; only localhost "
                         "rows are launchable today")
    ap.add_argument("--env", action="append", default=[], metavar="K=V",
                    help="extra env var for every worker (repeatable); "
                         "e.g. --env ZOO_TPU_DATA_PARALLEL=4")
    ap.add_argument("--on-failure",
                    choices=("kill-all", "report", "restart"),
                    default="kill-all",
                    help="kill-all: first nonzero exit terminates the "
                         "rest (default); report: let survivors finish "
                         "and report at the end; restart: tear down the "
                         "gang and relaunch it (workers auto-resume from "
                         "the latest checkpoint)")
    ap.add_argument("--max-restarts", type=int, default=3, metavar="N",
                    help="with --on-failure restart: give up after N "
                         "gang relaunches (default: 3)")
    ap.add_argument("--restart-backoff-s", type=float, default=1.0,
                    metavar="S",
                    help="with --on-failure restart: initial delay before "
                         "relaunching, doubled each attempt (default: 1.0)")
    ap.add_argument("--coordinator-port", type=int, default=None,
                    help="fixed coordination-service port (default: an "
                         "OS-assigned free port)")
    ap.add_argument("--trace-dir", default=None, metavar="DIR",
                    help="enable telemetry: every worker writes "
                         "trace-<pid>.json + metrics-<pid>.json here, "
                         "and the launcher records gang lifecycle events "
                         "(see docs/observability.md)")
    ap.add_argument("--no-prefix", action="store_true",
                    help="disable the [worker-N] log line prefixes")
    ap.add_argument("script", help="training script to run on every host")
    ap.add_argument("script_args", nargs=argparse.REMAINDER,
                    help="arguments passed through to the script")
    return ap


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO,
                        format="%(levelname)s %(name)s: %(message)s")
    args = build_parser().parse_args(argv)
    extra_env = {}
    for kv in args.env:
        if "=" not in kv:
            print(f"zoo-launch: --env expects K=V, got {kv!r}",
                  file=sys.stderr)
            return 2
        k, v = kv.split("=", 1)
        extra_env[k] = v
    try:
        return launch([args.script, *args.script_args],
                      num_hosts=args.hosts, hosts_file=args.hosts_file,
                      env=extra_env, on_failure=args.on_failure,
                      coordinator_port=args.coordinator_port,
                      prefix=not args.no_prefix,
                      max_restarts=args.max_restarts,
                      restart_backoff_s=args.restart_backoff_s,
                      trace_dir=args.trace_dir)
    except LaunchError as e:
        print(f"zoo-launch: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
