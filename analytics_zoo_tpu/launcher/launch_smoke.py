"""Launcher end-to-end smoke (``scripts/launch-smoke``; CI fast tier).

Generates an 8-shard partitioned parquet dataset, runs ``zoo-launch
--hosts 2`` over :mod:`launcher.smoke_train` on the CPU backend, and
asserts the distributed-platform contract:

- both workers printed ``SHARDS`` lines whose shard sets are disjoint,
  non-empty, and together cover all 8 shards;
- both workers completed ``NNEstimator.fit(dataset_uri)`` with params
  that actually moved from init (``FIT_DONE ... trained=1``);
- the job exit code is 0 — with **no hand-set ZOO_TPU_* env** anywhere.

Exit 0 on success, 1 on any violated assertion (printing the captured
worker log for diagnosis).
"""

from __future__ import annotations

import argparse
import io
import os
import re
import shutil
import sys
import tempfile


def run_smoke(hosts: int = 2, shards: int = 8, rows: int = 128,
              batch: int = 8, stream=None) -> int:
    import numpy as np

    from ..feature.dataset import write_parquet_shards
    from .launch import launch

    out = stream if stream is not None else sys.stdout
    dataset = tempfile.mkdtemp(prefix="zoo_launch_smoke_")
    try:
        rng = np.random.default_rng(0)
        x = rng.standard_normal((rows, 4)).astype(np.float32)
        y = (x[:, :1].sum(axis=1) > 0).astype(np.float32)
        write_parquet_shards(dataset, x, y, num_shards=shards)

        script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "smoke_train.py")
        cap = io.StringIO()
        env = {"JAX_PLATFORMS": "cpu"}
        rc = launch([script, dataset, str(batch)], num_hosts=hosts,
                    env=env, on_failure="kill-all", stream=cap)
        log = cap.getvalue()
        out.write(log)

        def fail(msg):
            out.write(f"LAUNCH_SMOKE_FAIL: {msg}\n")
            return 1

        if rc != 0:
            return fail(f"zoo-launch exited rc={rc}")
        shard_sets = {}
        for m in re.finditer(r"SHARDS pid=(\d+) (\S+)", log):
            shard_sets[int(m.group(1))] = set(m.group(2).split(","))
        if sorted(shard_sets) != list(range(hosts)):
            return fail(f"expected SHARDS lines from {hosts} workers, "
                        f"got pids {sorted(shard_sets)}")
        union = set()
        for pid, s in sorted(shard_sets.items()):
            if not s:
                return fail(f"worker {pid} got no shards")
            overlap = union & s
            if overlap:
                return fail(f"shard sets overlap: {sorted(overlap)}")
            union |= s
        expected = {f"part-{i:05d}.parquet" for i in range(shards)}
        if union != expected:
            return fail(f"coverage gap: missing {sorted(expected - union)}")
        done = {int(m.group(1)): int(m.group(2)) for m in
                re.finditer(r"FIT_DONE pid=(\d+) trained=(\d)", log)}
        if set(done) != set(range(hosts)):
            return fail(f"FIT_DONE missing for workers "
                        f"{sorted(set(range(hosts)) - set(done))}")
        untrained = sorted(p for p, t in done.items() if not t)
        if untrained:
            return fail(f"fit completed but params never moved on "
                        f"workers {untrained}")
        out.write(f"LAUNCH_SMOKE_OK hosts={hosts} shards={shards} "
                  f"rows={rows} disjoint=1 covered=1\n")
        return 0
    finally:
        shutil.rmtree(dataset, ignore_errors=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="launch-smoke")
    ap.add_argument("--hosts", type=int, default=2)
    ap.add_argument("--shards", type=int, default=8)
    ap.add_argument("--rows", type=int, default=128)
    ap.add_argument("--batch", type=int, default=8)
    args = ap.parse_args(argv)
    return run_smoke(hosts=args.hosts, shards=args.shards, rows=args.rows,
                     batch=args.batch)


if __name__ == "__main__":
    sys.exit(main())
