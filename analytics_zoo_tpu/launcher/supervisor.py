"""Process-supervision primitives shared by zoo-launch and the serving
fleet.

PR 6's launcher grew the supervision machinery (spawn with env
propagation, per-worker log fan-in, SIGTERM→SIGKILL teardown) inline in
:func:`~analytics_zoo_tpu.launcher.launch.launch`; the serving fleet
(docs/serving-fleet.md) needs exactly the same mechanics with a
different lifecycle (long-running workers that get *restarted* rather
than a batch job that runs to completion).  This module is the common
seam both build on:

- :func:`inject_pythonpath` — child processes import the same package
  tree the supervisor runs from, regardless of cwd or pip state;
- :func:`spawn_supervised` — Popen with merged stdout/stderr and a
  daemon pump thread fanning lines into one stream under a shared lock,
  each line prefixed ``[tag]`` so interleaved workers stay readable;
- :func:`terminate_all` — SIGTERM everything still alive (children run
  their teardown handlers), escalate to SIGKILL after a grace period.
"""

from __future__ import annotations

import os
import signal
import subprocess
import threading
import time
from typing import Dict, IO, List, NamedTuple, Optional, Sequence


def inject_pythonpath(env: Dict[str, str]) -> Dict[str, str]:
    """Prepend the package root to ``env``'s PYTHONPATH (deduplicated,
    order-preserving) so spawned workers resolve ``analytics_zoo_tpu``
    identically to the supervisor."""
    pkg_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    parts = [pkg_root] + [p for p in
                          env.get("PYTHONPATH", "").split(os.pathsep) if p]
    env["PYTHONPATH"] = os.pathsep.join(dict.fromkeys(parts))
    return env


def pump_lines(tag: str, pipe: IO[str], stream, lock: threading.Lock,
               prefix: bool = True):
    """Fan one child's merged stdout/stderr into ``stream``, one line at
    a time under ``lock`` so workers never interleave mid-line."""
    head = f"[{tag}] "
    for line in iter(pipe.readline, ""):
        with lock:
            stream.write((head if prefix else "") + line)
            stream.flush()
    pipe.close()


class SupervisedProc(NamedTuple):
    """One supervised child: the Popen handle plus its log pump."""

    proc: subprocess.Popen
    pump: threading.Thread
    tag: str


def spawn_supervised(cmd: Sequence[str], env: Dict[str, str], tag: str,
                     stream, lock: threading.Lock,
                     prefix: bool = True,
                     cwd: Optional[str] = None) -> SupervisedProc:
    """Start ``cmd`` with merged stdout/stderr pumped into ``stream``
    line-by-line under ``lock``, each line tagged ``[tag]``."""
    p = subprocess.Popen(list(cmd), env=env, cwd=cwd,
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True, bufsize=1)
    t = threading.Thread(target=pump_lines,
                         args=(tag, p.stdout, stream, lock, prefix),
                         daemon=True, name=f"pump-{tag}")
    t.start()
    return SupervisedProc(p, t, tag)


class Respawner:
    """Bounded respawn-on-death policy.

    The supervision loops that restart dead children (the serving fleet,
    and the process-based infeed pool in
    :mod:`~analytics_zoo_tpu.feature.host_pipeline`) all need the same
    decision: *is one more restart of this child allowed, or has it died
    often enough that the failure is structural and should surface?*
    This class is only that decision — it spawns nothing itself, so it
    works for ``subprocess.Popen`` fleets and ``multiprocessing``
    workers alike.

    A restart budget is per-child (``tag``), with an optional global
    cap across all children. Exceeding either raises ``RuntimeError``
    with the death history, which is exactly the prompt-error-surfacing
    contract the infeed iterators follow.
    """

    def __init__(self, max_per_child: int = 3,
                 max_total: Optional[int] = None):
        self.max_per_child = max_per_child
        self.max_total = max_total
        self._per_child: Dict[str, int] = {}
        self._total = 0
        self._lock = threading.Lock()

    @property
    def total_respawns(self) -> int:
        return self._total

    def note_death(self, tag: str, detail: str = "") -> None:
        """Record a child death and authorise one respawn of it, or
        raise ``RuntimeError`` when the budget is exhausted."""
        with self._lock:
            n = self._per_child.get(tag, 0) + 1
            self._per_child[tag] = n
            self._total += 1
            if n > self.max_per_child:
                raise RuntimeError(
                    f"worker {tag!r} died {n} times "
                    f"(> {self.max_per_child} respawns allowed)"
                    + (f": {detail}" if detail else ""))
            if self.max_total is not None and self._total > self.max_total:
                raise RuntimeError(
                    f"{self._total} worker deaths across the pool "
                    f"(> {self.max_total} total respawns allowed)"
                    + (f": {detail}" if detail else ""))


def terminate_all(procs: Sequence[subprocess.Popen], grace_s: float):
    """SIGTERM everything still alive (workers run their teardown
    handlers), escalate to SIGKILL after ``grace_s``."""
    live = [p for p in procs if p.poll() is None]
    for p in live:
        try:
            p.send_signal(signal.SIGTERM)
        except OSError:
            pass
    deadline = time.time() + grace_s
    for p in live:
        try:
            p.wait(timeout=max(0.0, deadline - time.time()))
        except subprocess.TimeoutExpired:
            try:
                p.kill()
                p.wait(timeout=5.0)
            except OSError:
                pass


__all__: List[str] = ["inject_pythonpath", "pump_lines", "spawn_supervised",
                      "SupervisedProc", "Respawner", "terminate_all"]
