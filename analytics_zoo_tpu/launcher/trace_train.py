"""Tiny traced trainer behind ``scripts/trace-smoke``.

Same skeleton as :mod:`launcher.chaos_train` but tuned so every span
family the telemetry spine promises actually fires in a 3-step run:

- ``log_every_n_steps=1`` — ``train/device_sync`` + ``train/metric_fetch``
  run every step instead of only at the log boundary;
- ``checkpoint_trigger=SeveralIteration(1)`` — a ``ckpt/write`` span per
  step;
- the dataset goes through ``LambdaPreprocessing(cpu_bound_transform,
  cpu_bound=True)`` so ``ZOO_TPU_INFEED_BACKEND=process`` spawns real
  transform worker processes whose ``infeed/transform`` spans are
  shipped back over the result queue and land in the parent's trace as
  per-worker timelines.

argv: ``<checkpoint_dir> [total_steps]``. Prints
``TRACE_TRAIN_DONE step=<N>`` on success.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> int:
    ckpt_dir = sys.argv[1]
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from analytics_zoo_tpu.common.nncontext import (ZooConfig,
                                                    init_nncontext)
    from analytics_zoo_tpu.common.zoo_trigger import (MaxIteration,
                                                      SeveralIteration)
    from analytics_zoo_tpu.feature.common import LambdaPreprocessing
    # module-level + importable by reference: spawned infeed workers
    # unpickle the chain by qualified name
    from analytics_zoo_tpu.feature.data_smoke import cpu_bound_transform
    from analytics_zoo_tpu.feature.feature_set import ArrayFeatureSet
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
    from analytics_zoo_tpu.pipeline.api.keras.models import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.optimizers import Adam
    from analytics_zoo_tpu.pipeline.estimator.estimator import Estimator

    init_nncontext(ZooConfig(log_every_n_steps=1))

    rng = np.random.default_rng(0)
    x = rng.standard_normal((64, 4)).astype(np.float32)
    y = (x.sum(axis=1, keepdims=True) > 0).astype(np.float32)
    fs = ArrayFeatureSet(x, y).transform(
        LambdaPreprocessing(cpu_bound_transform, cpu_bound=True))

    model = Sequential()
    model.add(Dense(8, activation="relu", input_shape=(4,)))
    model.add(Dense(1))
    est = Estimator(model, Adam(lr=1e-2), model_dir=ckpt_dir)
    est.train(fs, "mse", end_trigger=MaxIteration(steps),
              checkpoint_trigger=SeveralIteration(1), batch_size=8)
    est.trainer.wait_for_checkpoint()
    print(f"TRACE_TRAIN_DONE step={est.trainer.step}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
