"""Chaos end-to-end smoke (``scripts/chaos-smoke``; CI fast tier).

Proves the preemption-safety contract on the CPU backend with the
production code paths — no test doubles, real SIGKILLs:

1. **Reference leg** — an uninterrupted :mod:`launcher.chaos_train` run
   prints its final param+optimizer digest.
2. **Gang-restart leg** — the same job under ``zoo-launch --hosts 1
   --on-failure restart`` with ``ZOO_TPU_FAULT=step:kill@K`` (K random
   mid-run) and a ``ZOO_TPU_FAULT_STATE`` dir so the kill fires exactly
   once: the worker is SIGKILLed mid-training, the launcher relaunches
   the gang, the relaunched worker auto-resumes from ``latest``, and
   the final digest is **bit-exact** vs. the reference.
3. **Partial-write leg** — ``ZOO_TPU_FAULT=ckpt-write:kill@2`` kills
   the job mid-write of the second checkpoint: the smoke asserts the
   truncated ``ckpt-2`` has no manifest (never committed), ``latest``
   still points at ``ckpt-1``, and a plain auto-resume re-run skips the
   partial dir and still reproduces the reference digest.

Exit 0 and ``CHAOS_SMOKE_OK`` on success; 1 with captured worker logs
on any violated assertion.
"""

from __future__ import annotations

import argparse
import io
import os
import random
import re
import shutil
import sys
import tempfile

from ..utils.faults import ENV_SPEC, ENV_STATE

_SCRIPT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "chaos_train.py")


def _run_train(ckpt_dir: str, steps: int, extra_env=None, **launch_kw):
    """One chaos_train job under ``zoo-launch`` (every leg goes through
    the launcher: jax compiles slightly different — still deterministic
    — programs with the distributed runtime up, so digests only compare
    within one environment); returns ``(rc, merged_output)``."""
    from .launch import launch

    # a leg must never inherit the caller's fault arming; auto-resume is
    # set per leg (the restart policy injects its own "1" when unset)
    env = {"JAX_PLATFORMS": "cpu", ENV_SPEC: "", ENV_STATE: ""}
    env.update(extra_env or {})
    cap = io.StringIO()
    rc = launch([_SCRIPT, ckpt_dir, str(steps)], num_hosts=1, env=env,
                stream=cap, **launch_kw)
    return rc, cap.getvalue()


def _digest(log: str):
    m = re.search(r"FINAL step=(\d+) digest=([0-9a-f]{64})", log)
    return (int(m.group(1)), m.group(2)) if m else (None, None)


def run_smoke(steps: int = 12, kill_step: int = 0, stream=None) -> int:
    out = stream if stream is not None else sys.stdout
    work = tempfile.mkdtemp(prefix="zoo_chaos_smoke_")
    kill_step = kill_step or random.randint(3, steps - 2)

    def fail(msg, log=""):
        if log:
            out.write(log)
        out.write(f"CHAOS_SMOKE_FAIL: {msg}\n")
        return 1

    try:
        # -- leg 1: uninterrupted reference ----------------------------
        rc, log = _run_train(os.path.join(work, "ref"), steps,
                             extra_env={"ZOO_TPU_AUTO_RESUME": "0"})
        ref_step, ref_digest = _digest(log)
        if rc != 0 or ref_digest is None:
            return fail(f"reference run failed rc={rc}", log)
        out.write(f"CHAOS_REF_OK step={ref_step} digest={ref_digest}\n")

        # -- leg 2: SIGKILL mid-run under gang restart -----------------
        ckpt_b = os.path.join(work, "restart")
        state = os.path.join(work, "fault-state")
        os.makedirs(state)
        rc, log = _run_train(
            ckpt_b, steps,
            extra_env={ENV_SPEC: f"step:kill@{kill_step}",
                       ENV_STATE: state},
            on_failure="restart", max_restarts=2, restart_backoff_s=0.1)
        if rc != 0:
            return fail(f"restart leg exited rc={rc}", log)
        if "restarting gang" not in log:
            return fail("worker survived the injected kill "
                        f"(step:kill@{kill_step} never fired?)", log)
        got_step, got_digest = _digest(log)
        if got_step != ref_step or got_digest != ref_digest:
            return fail(
                f"resume after kill@{kill_step} is not bit-exact: "
                f"step={got_step} digest={got_digest} vs reference "
                f"step={ref_step} digest={ref_digest}", log)
        out.write(f"CHAOS_RESTART_OK kill_step={kill_step} bitexact=1\n")

        # -- leg 3: crash mid-checkpoint-write, then resume ------------
        ckpt_c = os.path.join(work, "partial")
        rc, log = _run_train(ckpt_c, steps,
                             extra_env={ENV_SPEC: "ckpt-write:kill@2",
                                        "ZOO_TPU_AUTO_RESUME": "0"})
        if rc == 0:
            return fail("ckpt-write:kill@2 never fired", log)
        partial = os.path.join(ckpt_c, "ckpt-2")
        if not os.path.isdir(partial):
            return fail("no partial ckpt-2 dir left behind", log)
        if os.path.exists(os.path.join(partial, "manifest.json")):
            return fail("crashed-mid-write checkpoint has a manifest "
                        "(partial write became visible)", log)
        with open(os.path.join(ckpt_c, "latest"), "rb") as f:
            latest = f.read().decode()
        if latest != "ckpt-1":
            return fail(f"latest moved to {latest!r} despite the crash "
                        "(expected ckpt-1)", log)
        rc, log = _run_train(ckpt_c, steps,
                             extra_env={"ZOO_TPU_AUTO_RESUME": "1"})
        got_step, got_digest = _digest(log)
        if rc != 0 or got_step != ref_step or got_digest != ref_digest:
            return fail(
                f"resume past partial checkpoint not bit-exact: rc={rc} "
                f"step={got_step} digest={got_digest} vs reference "
                f"step={ref_step} digest={ref_digest}", log)
        out.write("CHAOS_PARTIAL_OK skipped=ckpt-2 bitexact=1\n")

        out.write(f"CHAOS_SMOKE_OK steps={steps} kill_step={kill_step}\n")
        return 0
    finally:
        shutil.rmtree(work, ignore_errors=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="chaos-smoke")
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--kill-step", type=int, default=0,
                    help="step at which to SIGKILL the restart leg "
                         "(default: random in [3, steps-2])")
    args = ap.parse_args(argv)
    return run_smoke(steps=args.steps, kill_step=args.kill_step)


if __name__ == "__main__":
    sys.exit(main())
