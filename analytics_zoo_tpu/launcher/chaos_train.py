"""Deterministic tiny trainer behind ``scripts/chaos-smoke``.

Trains a 2-layer MLP on a fixed synthetic dataset (64 rows, batch 8 —
so 8 steps/epoch; the default 12 total steps cross an epoch boundary,
exercising the mid-epoch dataset cursor) with a checkpoint every step,
then prints a machine-checkable marker::

    FINAL step=<N> digest=<sha256 over all param + optimizer leaves>

Everything is seeded, so two uninterrupted runs — or one uninterrupted
run vs. a killed-and-resumed run — must print the *same* digest. The
chaos smoke (:mod:`launcher.chaos_smoke`) asserts exactly that under
``ZOO_TPU_FAULT`` kill injection and gang restart.

argv: ``<checkpoint_dir> [total_steps]``.
"""

import hashlib
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def state_digest(trainer) -> str:
    import jax
    import numpy as np

    h = hashlib.sha256()
    for leaf in (jax.tree_util.tree_leaves(trainer.params) +
                 jax.tree_util.tree_leaves(trainer.opt_state)):
        a = np.asarray(leaf)
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def main() -> int:
    ckpt_dir = sys.argv[1]
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 12
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from analytics_zoo_tpu.common.nncontext import (ZooConfig,
                                                    init_nncontext)
    from analytics_zoo_tpu.common.zoo_trigger import (MaxIteration,
                                                      SeveralIteration)
    from analytics_zoo_tpu.feature.feature_set import ArrayFeatureSet
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
    from analytics_zoo_tpu.pipeline.api.keras.models import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.optimizers import Adam
    from analytics_zoo_tpu.pipeline.estimator.estimator import Estimator

    init_nncontext(ZooConfig(log_every_n_steps=1000))

    rng = np.random.default_rng(0)
    x = rng.standard_normal((64, 4)).astype(np.float32)
    y = (x.sum(axis=1, keepdims=True) > 0).astype(np.float32)
    fs = ArrayFeatureSet(x, y)

    model = Sequential()
    model.add(Dense(8, activation="relu", input_shape=(4,)))
    model.add(Dense(1))
    est = Estimator(model, Adam(lr=1e-2), model_dir=ckpt_dir)
    est.train(fs, "mse", end_trigger=MaxIteration(steps),
              checkpoint_trigger=SeveralIteration(1), batch_size=8)
    est.trainer.wait_for_checkpoint()
    print(f"FINAL step={est.trainer.step} "
          f"digest={state_digest(est.trainer)}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
