"""Per-worker bootstrap: signal-safe teardown around the user's script.

``zoo-launch`` runs every worker as ``python -m
analytics_zoo_tpu.launcher.worker <script> [args...]`` so that:

1. a supervisor-driven SIGTERM first tries a graceful drain: if a
   trainer is mid-loop (``pipeline.engine.active_trainer_count() > 0``)
   the handler requests preemption, the training loop checkpoints at the
   next step boundary and raises ``TrainingPreempted``, and the worker
   exits 143 having saved its state. A watchdog hard-exits after
   ``ZOO_TPU_PREEMPTION_GRACE_S`` (default 30) seconds in case the loop
   never reaches a step boundary;
2. when no trainer is active (or on SIGINT) teardown is immediate:
   every live infeed stage closes (``feature.shutdown_all_pipelines``)
   before exiting — otherwise concurrent.futures' atexit hook joins
   still-busy non-daemon transform-pool threads and a "killed" worker
   hangs instead of dying;
3. the script sees a clean ``sys.argv`` (its own name + args), exactly
   as if launched directly.

Deliberately import-light: jax and the package's heavy modules load only
if (and when) the user script imports them.
"""

from __future__ import annotations

import os
import runpy
import signal
import sys
import threading


def _grace_s() -> float:
    try:
        return float(os.environ.get("ZOO_TPU_PREEMPTION_GRACE_S", "30"))
    except ValueError:
        return 30.0


def _flight(reason: str, **args):
    """Flight-recorder dump via sys.modules (not an import: this worker
    shim stays light, and a process that never loaded telemetry has
    nothing worth dumping anyway)."""
    tel = sys.modules.get("analytics_zoo_tpu.utils.telemetry")
    if tel is None:
        return
    try:
        tel.event("launch/worker_signal", **args)
        tel.dump_flight(reason)
    except Exception:  # noqa: BLE001 - teardown must proceed
        pass


def _hard_exit(signum: int):
    rank = os.environ.get("ZOO_TPU_PROCESS_ID", "?")
    _flight(f"worker {rank} hard exit on signal {signum}",
            rank=rank, signal=signum, drain=False)
    try:
        from analytics_zoo_tpu.feature.feature_set import \
            shutdown_all_pipelines

        closed = shutdown_all_pipelines()
        if closed:
            print(f"[launcher.worker {rank}] closed {closed} pipeline "
                  f"stage(s) on signal {signum}", file=sys.stderr,
                  flush=True)
    finally:
        # 128+signum, the shell convention the supervisor reports
        os._exit(128 + signum)


def _shutdown_handler(signum, frame):  # noqa: ARG001 - signal signature
    rank = os.environ.get("ZOO_TPU_PROCESS_ID", "?")
    # sys.modules lookup, not an import: the handler must stay cheap and
    # must not pull jax into a worker that never trained
    engine = sys.modules.get("analytics_zoo_tpu.pipeline.engine")
    if signum == signal.SIGTERM and engine is not None \
            and engine.active_trainer_count() > 0:
        print(f"[launcher.worker {rank}] SIGTERM: draining — checkpoint "
              f"at next step boundary (grace {_grace_s():.0f}s)",
              file=sys.stderr, flush=True)
        tel = sys.modules.get("analytics_zoo_tpu.utils.telemetry")
        if tel is not None:
            try:
                tel.event("launch/drain_requested", rank=rank,
                          signal=signum)
            except Exception:  # noqa: BLE001
                pass
        engine.request_preemption()
        t = threading.Timer(_grace_s(), _hard_exit, args=(signum,))
        t.daemon = True
        t.start()
        return
    _hard_exit(signum)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        print("usage: python -m analytics_zoo_tpu.launcher.worker "
              "<script.py> [args...]", file=sys.stderr)
        return 2
    signal.signal(signal.SIGTERM, _shutdown_handler)
    signal.signal(signal.SIGINT, _shutdown_handler)
    script, sys.argv = argv[0], argv
    # scripts resolve siblings relative to themselves, like `python x.py`
    sys.path.insert(0, os.path.dirname(os.path.abspath(script)) or ".")
    try:
        runpy.run_path(script, run_name="__main__")
    except Exception as e:
        engine = sys.modules.get("analytics_zoo_tpu.pipeline.engine")
        if engine is not None and isinstance(
                e, getattr(engine, "TrainingPreempted", ())):
            rank = os.environ.get("ZOO_TPU_PROCESS_ID", "?")
            print(f"[launcher.worker {rank}] drained: checkpoint saved, "
                  f"exiting 143", file=sys.stderr, flush=True)
            return 143
        raise
    return 0


if __name__ == "__main__":
    sys.exit(main())
