"""Per-worker bootstrap: signal-safe teardown around the user's script.

``zoo-launch`` runs every worker as ``python -m
analytics_zoo_tpu.launcher.worker <script> [args...]`` so that:

1. a supervisor-driven SIGTERM (kill-all failure policy, operator ^C)
   closes every live infeed stage (``feature.shutdown_all_pipelines``)
   before exiting — otherwise concurrent.futures' atexit hook joins
   still-busy non-daemon transform-pool threads and a "killed" worker
   hangs instead of dying;
2. the script sees a clean ``sys.argv`` (its own name + args), exactly
   as if launched directly.

Deliberately import-light: jax and the package's heavy modules load only
if (and when) the user script imports them.
"""

from __future__ import annotations

import os
import runpy
import signal
import sys


def _shutdown_handler(signum, frame):  # noqa: ARG001 - signal signature
    rank = os.environ.get("ZOO_TPU_PROCESS_ID", "?")
    try:
        from analytics_zoo_tpu.feature.feature_set import \
            shutdown_all_pipelines

        closed = shutdown_all_pipelines()
        if closed:
            print(f"[launcher.worker {rank}] closed {closed} pipeline "
                  f"stage(s) on signal {signum}", file=sys.stderr,
                  flush=True)
    finally:
        # 128+signum, the shell convention the supervisor reports
        os._exit(128 + signum)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        print("usage: python -m analytics_zoo_tpu.launcher.worker "
              "<script.py> [args...]", file=sys.stderr)
        return 2
    signal.signal(signal.SIGTERM, _shutdown_handler)
    signal.signal(signal.SIGINT, _shutdown_handler)
    script, sys.argv = argv[0], argv
    # scripts resolve siblings relative to themselves, like `python x.py`
    sys.path.insert(0, os.path.dirname(os.path.abspath(script)) or ".")
    runpy.run_path(script, run_name="__main__")
    return 0


if __name__ == "__main__":
    sys.exit(main())
