"""The per-worker train script behind ``scripts/launch-smoke``.

Run by ``zoo-launch`` on every host: joins the distributed runtime via
``init_nncontext`` (no hand-set env — the launcher propagated the
contract), trains ``NNEstimator.fit(dataset_uri)`` over the partitioned
parquet directory given as argv[1], and prints machine-checkable markers:

- ``SHARDS pid=<rank> <comma-separated shard basenames>`` — the smoke
  asserts per-host disjointness and full coverage;
- ``FIT_DONE pid=<rank> trained=<0|1>`` — fit completed; ``trained=1``
  means the synced-back model params actually moved from their init
  values (the optimizer stepped).
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> int:
    uri = sys.argv[1]
    batch_size = int(sys.argv[2]) if len(sys.argv) > 2 else 8
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from analytics_zoo_tpu.common.nncontext import ZooConfig, init_nncontext

    init_nncontext(ZooConfig(log_every_n_steps=1000))
    pid = jax.process_index()

    from analytics_zoo_tpu.feature.feature_set import FeatureSet

    fs = FeatureSet.from_dataset(uri, label_col="label")
    print(f"SHARDS pid={pid} {','.join(fs.local_shards)}", flush=True)

    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
    from analytics_zoo_tpu.pipeline.api.keras.models import Sequential
    from analytics_zoo_tpu.pipeline.nnframes import NNEstimator

    model = Sequential()
    model.add(Dense(8, activation="relu", input_shape=(4,)))
    model.add(Dense(1))
    est = (NNEstimator(model, "mse")
           .setBatchSize(batch_size)
           .setMaxEpoch(1)
           .setLabelCol("label"))
    import numpy as np

    init_weights = model.get_weights()
    nn_model = est.fit(uri)
    assert nn_model is not None
    trained = [np.asarray(l) for l in
               jax.tree_util.tree_leaves(model._built_params[0])]
    moved = any(not np.array_equal(a, b)
                for a, b in zip(init_weights, trained))
    print(f"FIT_DONE pid={pid} trained={int(moved)}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
