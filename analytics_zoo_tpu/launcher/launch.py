"""Multi-process job launcher: spawn, propagate env, fan in logs, supervise.

Local multi-process today (one worker per ``--hosts`` slot on this
machine — the CPU-backend test topology and the single-TPU-host
multi-process layout); the ``--hosts-file`` surface is already parsed so
ssh/pod-slice placement can slot in without changing the contract.

Env contract handed to every worker (consumed by
``common/nncontext._maybe_init_distributed``):

- ``ZOO_TPU_COORDINATOR``   host:port of process 0's coordination service
- ``ZOO_TPU_NUM_PROCESSES`` world size
- ``ZOO_TPU_PROCESS_ID``    this worker's rank

Failure policy (``on_failure``):

- ``kill-all`` (default): first nonzero exit terminates the remaining
  workers (SIGTERM, then SIGKILL after ``grace_s``) — fail fast, the
  collective is dead anyway once one member is gone;
- ``report``: let the surviving workers run to completion and report the
  failure at the end.
- ``restart``: SPMD is all-or-nothing — any worker death tears down the
  whole gang (as kill-all) and relaunches it, up to ``max_restarts``
  times with exponential backoff starting at ``restart_backoff_s``.
  Restarted gangs get ``ZOO_TPU_AUTO_RESUME=1`` so training resumes from
  the ``latest`` checkpoint (see docs/fault-tolerance.md); each attempt
  picks a fresh coordinator port (the dead gang's port may linger in
  TIME_WAIT).

Either way :func:`launch` returns the **first nonzero exit code** (0 when
every worker succeeded, possibly after restarts).
"""

from __future__ import annotations

import logging
import os
import shlex
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, IO, List, NamedTuple, Optional, Sequence

from ..utils import telemetry
from .supervisor import (inject_pythonpath, pump_lines, spawn_supervised,
                         terminate_all)

logger = logging.getLogger("analytics_zoo_tpu.launcher")


class LaunchError(RuntimeError):
    """Launcher-level misconfiguration (bad hosts file, no workers...)."""


class HostSpec(NamedTuple):
    """One placement row: hostname + number of worker slots on it."""

    host: str
    slots: int


_LOCAL_HOSTS = ("localhost", "127.0.0.1", "::1")


def parse_hosts_file(path: str) -> List[HostSpec]:
    """Parse an MPI-style hosts file: ``host [slots]`` per line, ``#``
    comments. Only localhost rows are launchable today; remote rows
    parse fine but :func:`launch` rejects them with a clear error so the
    file format is already the forward-compatible surface."""
    specs: List[HostSpec] = []
    with open(path) as f:
        for lineno, raw in enumerate(f, 1):
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            if len(parts) > 2:
                raise LaunchError(
                    f"{path}:{lineno}: expected 'host [slots]', got "
                    f"{raw.strip()!r}")
            slots = 1
            if len(parts) == 2:
                try:
                    slots = int(parts[1])
                except ValueError as e:
                    raise LaunchError(
                        f"{path}:{lineno}: bad slot count "
                        f"{parts[1]!r}") from e
                if slots < 1:
                    raise LaunchError(
                        f"{path}:{lineno}: slots must be >= 1")
            specs.append(HostSpec(parts[0], slots))
    if not specs:
        raise LaunchError(f"hosts file {path} has no host entries")
    return specs


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _pump(pid: int, pipe: IO[str], stream, lock: threading.Lock,
          prefix: bool):
    """Fan one worker's merged output into ``stream`` (supervisor seam)."""
    pump_lines(f"worker-{pid}", pipe, stream, lock, prefix)


def _worker_env(base: Dict[str, str], coordinator: str, num_processes: int,
                process_id: int, extra: Optional[Dict[str, str]]) -> dict:
    # workers must import the same package tree the supervisor runs from,
    # regardless of their cwd (the repo may not be pip-installed)
    env = inject_pythonpath(dict(base))
    if extra:
        env.update({str(k): str(v) for k, v in extra.items()})
    env["ZOO_TPU_COORDINATOR"] = coordinator
    env["ZOO_TPU_NUM_PROCESSES"] = str(num_processes)
    env["ZOO_TPU_PROCESS_ID"] = str(process_id)
    return env


def launch(script_argv: Sequence[str], num_hosts: Optional[int] = None,
           hosts_file: Optional[str] = None,
           env: Optional[Dict[str, str]] = None,
           on_failure: str = "kill-all",
           coordinator_port: Optional[int] = None,
           grace_s: float = 10.0, stream=None, prefix: bool = True,
           python: Optional[str] = None, max_restarts: int = 3,
           restart_backoff_s: float = 1.0,
           trace_dir: Optional[str] = None) -> int:
    """Run ``script_argv`` (a train script + its args) as a multi-process
    job. See module docstring for the env contract and failure policy.
    Returns the first nonzero worker exit code, or 0.

    ``trace_dir`` turns on telemetry for the launcher *and* (via the
    exported ``ZOO_TPU_TELEMETRY`` / ``ZOO_TPU_TRACE_DIR`` env) every
    worker: each process writes its own ``trace-<pid>.json`` +
    ``metrics-<pid>.json`` there, and the launcher records gang
    lifecycle events (spawn, exit, restart, drain)."""
    if trace_dir is not None:
        telemetry.configure(enabled=True, trace_dir=trace_dir,
                            service="launcher")
    if on_failure not in ("kill-all", "report", "restart"):
        raise LaunchError(
            f"on_failure must be 'kill-all', 'report' or 'restart', got "
            f"{on_failure!r}")
    if not script_argv:
        raise LaunchError("no train script given")
    if hosts_file is not None:
        specs = parse_hosts_file(hosts_file)
        remote = [s.host for s in specs if s.host not in _LOCAL_HOSTS]
        if remote:
            raise LaunchError(
                f"remote hosts not supported yet (only localhost rows "
                f"launch; got {remote}); run zoo-launch on each host with "
                f"ZOO_TPU_COORDINATOR pointing at host 0, or use "
                f"--hosts N for local multi-process")
        world = sum(s.slots for s in specs)
        if num_hosts is not None and num_hosts != world:
            raise LaunchError(
                f"--hosts {num_hosts} disagrees with hosts file "
                f"({world} slots)")
    else:
        world = num_hosts if num_hosts is not None else 1
    if world < 1:
        raise LaunchError(f"need >= 1 worker, got {world}")
    stream = stream if stream is not None else sys.stdout
    python = python or sys.executable
    base_env = dict(os.environ)

    cmd_tail = [os.fspath(a) for a in script_argv]
    lock = threading.Lock()
    attempt = 0
    while True:
        port = coordinator_port or _free_port()
        coordinator = f"127.0.0.1:{port}"
        extra_env = dict(env or {})
        if on_failure == "restart":
            # every attempt (the first included) resumes from `latest`
            # when one exists: under the restart policy the launcher —
            # not the script — owns the job's lifecycle, so a relaunch
            # of the whole zoo-launch process must also pick up where
            # the checkpoint left off. Explicit user env wins.
            extra_env.setdefault("ZOO_TPU_AUTO_RESUME", "1")
        logger.info("zoo-launch: %d worker(s), coordinator %s, "
                    "on-failure=%s%s: %s", world, coordinator, on_failure,
                    f" (attempt {attempt + 1})" if attempt else "",
                    " ".join(shlex.quote(c) for c in cmd_tail))
        telemetry.event("launch/gang_start", world=world,
                        attempt=attempt + 1, coordinator=coordinator)
        first_rc, failed_pid = _run_gang(
            cmd_tail, world, coordinator, base_env, extra_env, on_failure,
            grace_s, stream, lock, prefix, python)
        if first_rc == 0:
            telemetry.event("launch/job_complete", world=world,
                            attempts=attempt + 1)
            with lock:
                stream.write(f"[zoo-launch] job complete: {world} "
                             f"worker(s) exited 0\n")
                stream.flush()
            return 0
        if on_failure != "restart" or attempt >= max_restarts:
            telemetry.event("launch/job_failed", rc=first_rc,
                            failed_worker=failed_pid,
                            attempts=attempt + 1)
            if on_failure == "restart":
                with lock:
                    stream.write(
                        f"[zoo-launch] restarts exhausted "
                        f"({max_restarts}): giving up with rc="
                        f"{first_rc}\n")
                    stream.flush()
            return first_rc
        attempt += 1
        delay = restart_backoff_s * (2 ** (attempt - 1))
        telemetry.event("launch/gang_restart", rc=first_rc,
                        failed_worker=failed_pid, attempt=attempt,
                        delay_s=delay)
        with lock:
            stream.write(
                f"[zoo-launch] worker-{failed_pid} rc={first_rc}: "
                f"restarting gang (attempt {attempt}/{max_restarts}) "
                f"in {delay:.1f}s\n")
            stream.flush()
        time.sleep(delay)


def _run_gang(cmd_tail: List[str], world: int, coordinator: str,
              base_env: Dict[str, str], env: Optional[Dict[str, str]],
              on_failure: str, grace_s: float, stream, lock, prefix: bool,
              python: str):
    """Spawn one gang of ``world`` workers and supervise it to completion.
    Returns ``(first_rc, failed_pid)``. Under kill-all AND restart, the
    first death terminates the survivors (SPMD: the collective is dead
    once one member is gone)."""
    procs: List[subprocess.Popen] = []
    pumps: List[threading.Thread] = []
    try:
        for pid in range(world):
            sp = spawn_supervised(
                [python, "-m", "analytics_zoo_tpu.launcher.worker",
                 *cmd_tail],
                env=_worker_env(base_env, coordinator, world, pid, env),
                tag=f"worker-{pid}", stream=stream, lock=lock,
                prefix=prefix)
            procs.append(sp.proc)
            pumps.append(sp.pump)
            telemetry.event("launch/worker_spawn", worker=pid,
                            os_pid=sp.proc.pid)
    except BaseException:
        _terminate_all(procs, grace_s)
        raise

    first_rc = 0
    failed_pid: Optional[int] = None
    killed = False
    pending = set(range(world))
    while pending:
        for pid in sorted(pending):
            rc = procs[pid].poll()
            if rc is None:
                continue
            pending.discard(pid)
            telemetry.event("launch/worker_exit", worker=pid, rc=rc)
            if rc != 0:
                with lock:
                    stream.write(
                        f"[zoo-launch] worker-{pid} exited rc={rc}\n")
                    stream.flush()
                if first_rc == 0:
                    first_rc, failed_pid = rc, pid
                if on_failure in ("kill-all", "restart") and not killed \
                        and pending:
                    telemetry.event("launch/terminate_survivors",
                                    n=len(pending), failed_worker=pid)
                    with lock:
                        stream.write(
                            f"[zoo-launch] on-failure={on_failure}: "
                            f"terminating {len(pending)} remaining "
                            f"worker(s)\n")
                        stream.flush()
                    _terminate_all([procs[q] for q in pending], grace_s)
                    killed = True
        if pending:
            time.sleep(0.05)
    for t in pumps:
        t.join(timeout=5.0)
    rcs = [p.returncode for p in procs]
    if first_rc != 0:
        with lock:
            stream.write(
                f"[zoo-launch] job FAILED: first failure worker-"
                f"{failed_pid} rc={first_rc}; exit codes {rcs}\n")
            stream.flush()
    return first_rc, failed_pid


def _terminate_all(procs: Sequence[subprocess.Popen], grace_s: float):
    """SIGTERM then SIGKILL after ``grace_s`` (supervisor seam)."""
    terminate_all(procs, grace_s)
