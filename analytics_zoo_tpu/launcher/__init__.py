"""zoo-launch: the ``init_spark_on_yarn`` analogue for TPU-host jobs.

The reference brings a cluster up with one call — ``init_spark_on_yarn``
submits executors, propagates conf/env and wires the driver (pyzoo
``zoo/common/nncontext.py``). This package does the same for the
multi-controller JAX runtime: ``zoo-launch --hosts N train.py`` spawns N
host processes, picks a coordinator address, propagates the
``ZOO_TPU_COORDINATOR/NUM_PROCESSES/PROCESS_ID`` env contract that
``init_nncontext`` consumes, fans worker logs into prefixed streams and
supervises child health — replacing the hand-set env dance.

Kept import-light on purpose: the supervisor never imports jax, so the
CLI starts instantly and survives on hosts where the accelerator runtime
is broken (the workers are the ones that need it).
"""

from .launch import HostSpec, LaunchError, launch, parse_hosts_file

__all__ = ["HostSpec", "LaunchError", "launch", "parse_hosts_file"]
