"""ZeRO stage-1 smoke: parity + memory + collective contract in one
subprocess (CI hook, ``scripts/zero-smoke``; the bench ``zero`` leg runs
the same module with ``--bench --json``).

Checks, all on a forced 4-device CPU host (re-exec via
``common.hostdev`` when the topology is short — the attn_smoke
pattern):

* ``parity_dp2`` / ``parity_dp4`` — zero=1 loss curve matches zero=0
  within ``PARITY_TOL`` after ``STEPS`` Adam steps.
* ``opt_memory`` — per-device optimizer moment bytes at zero=1 are
  <= ``RATIO_MAX`` x the replicated baseline at dp=4, measured BOTH from
  the live arrays (``parallel.zero.per_device_bytes``) and from the
  AOT-compiled step's ``memory_analysis()`` breakdown
  (``utils.memory.program_breakdown``) — the compiled-argument view is
  the one silicon pays.
* ``collectives`` — the step jaxpr contains reduce-scatter + all-gather
  and NO full-gradient-sized all-reduce/psum
  (``parallel.zero.assert_zero_collectives``).

``--bench`` additionally times the hot step for both stages (the bench
gate: zero=1 step time <= 1.05x replicated on the stub).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

PARITY_TOL = 1e-6
RATIO_MAX = 0.30
STEPS = 20
BENCH_WARMUP = 3
BENCH_ITERS = 10

_N, _IN, _HID = 64, 32, 64
# the timing comparison needs real per-step work: at toy sizes the
# fixed dispatch overhead of the shard_map step dominates and the
# ratio is meaningless (measured: 64-wide 1.13x, 256-wide 0.73x,
# 1024-wide 0.39x — the 1/dp optimizer math wins as soon as the update
# is non-trivial)
_BENCH_N, _BENCH_IN, _BENCH_HID = 128, 128, 256


def _data(n: int = _N, nin: int = _IN):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((n, nin)).astype(np.float32)
    y = (x[:, :1] * x[:, 1:2] > 0).astype(np.float32)
    return x, y


def _mk_trainer(dp: int, zero_stage: int, hid: int = _HID,
                nin: int = _IN):
    import jax

    from ..common.nncontext import ZooConfig, ZooContext, set_nncontext
    from .api.keras.layers import Dense
    from .api.keras.models import Sequential

    set_nncontext(None)
    set_nncontext(ZooContext(
        ZooConfig(data_parallel=dp, zero_stage=zero_stage),
        devices=jax.devices()[:dp]))
    tag = f"zsmoke_dp{dp}_z{zero_stage}_h{hid}"
    model = Sequential()
    model.add(Dense(hid, activation="relu", input_shape=(nin,),
                    name=f"{tag}_d0"))
    model.add(Dense(1, activation="sigmoid", name=f"{tag}_d1"))
    model.compile(optimizer="adam", loss="binary_crossentropy")
    trainer = model._ensure_trainer()
    trainer.ensure_initialized()
    return trainer


def _run_steps(trainer, steps=STEPS):
    from ..feature.feature_set import MiniBatch
    x, y = _data()
    fn = trainer.build_train_step()
    losses = []
    for i in range(steps):
        batch = trainer._put_batch(MiniBatch([x], y, None))
        trainer.params, trainer.opt_state, trainer.net_state, logs = fn(
            trainer.params, trainer.opt_state, trainer.net_state, batch, i)
        losses.append(float(logs["loss"]))
    return losses


def _moment_bytes(trainer):
    """Per-device bytes of the param-mirroring moment leaves only
    (schedule counts are noise at this model size)."""
    import jax
    from ..parallel import zero
    flat = jax.tree_util.tree_flatten_with_path(trainer.opt_state)[0]
    if trainer._zero_opt_paths:
        leaves = [leaf for path, leaf in flat
                  if tuple(path) in trainer._zero_opt_paths]
    else:
        leaves = [leaf for _, leaf in flat
                  if getattr(leaf, "ndim", 0) >= 1]
    return zero.per_device_bytes(leaves)


def _compiled_breakdown(trainer):
    from ..feature.feature_set import MiniBatch
    from ..utils import memory
    x, y = _data()
    batch = trainer._put_batch(MiniBatch([x], y, None))
    fn = trainer.build_train_step()
    compiled = fn.lower(*trainer._abstractify(
        (trainer.params, trainer.opt_state, trainer.net_state, batch,
         0))).compile()
    return memory.program_breakdown(compiled, params=trainer.params,
                                    opt_state=trainer.opt_state)


def _time_step(trainer):
    from ..feature.feature_set import MiniBatch
    import jax
    x, y = _data(_BENCH_N, _BENCH_IN)
    fn = trainer.build_train_step()
    p, o, s = trainer.params, trainer.opt_state, trainer.net_state
    for i in range(BENCH_WARMUP):
        batch = trainer._put_batch(MiniBatch([x], y, None))
        p, o, s, logs = fn(p, o, s, batch, i)
    jax.block_until_ready(logs["loss"])
    times = []
    for i in range(BENCH_ITERS):
        batch = trainer._put_batch(MiniBatch([x], y, None))
        t0 = time.perf_counter()
        p, o, s, logs = fn(p, o, s, batch, BENCH_WARMUP + i)
        jax.block_until_ready(logs["loss"])
        times.append((time.perf_counter() - t0) * 1000.0)
    trainer.params, trainer.opt_state, trainer.net_state = p, o, s
    return float(np.median(times))


def _check_parity(out, dp):
    l0 = _run_steps(_mk_trainer(dp, 0))
    l1 = _run_steps(_mk_trainer(dp, 1))
    err = max(abs(a - b) for a, b in zip(l0, l1))
    out[f"parity_dp{dp}_max_err"] = err
    out[f"parity_dp{dp}_steps"] = STEPS
    return err <= PARITY_TOL


def _check_memory(out, bench=False):
    t0 = _mk_trainer(4, 0)
    t1 = _mk_trainer(4, 1)
    b0, b1 = _moment_bytes(t0), _moment_bytes(t1)
    out["opt_moment_bytes_replicated"] = int(b0)
    out["opt_moment_bytes_zero1"] = int(b1)
    ratio = b1 / max(b0, 1)
    out["opt_state_bytes_ratio"] = round(ratio, 6)
    ok = ratio <= RATIO_MAX
    bd0, bd1 = _compiled_breakdown(t0), _compiled_breakdown(t1)
    if bd0 is not None and bd1 is not None:
        out["compiled_opt_per_device_repl"] = \
            bd0["opt_state_per_device_bytes"]
        out["compiled_opt_per_device_zero1"] = \
            bd1["opt_state_per_device_bytes"]
        cratio = bd1["opt_state_per_device_bytes"] / \
            max(bd0["opt_state_per_device_bytes"], 1)
        out["compiled_opt_state_ratio"] = round(cratio, 6)
        ok = ok and cratio <= RATIO_MAX
        # the compiled program's own input-buffer accounting must agree:
        # zero=1 feeds strictly fewer argument bytes per device
        out["compiled_argument_bytes_repl"] = bd0["argument_bytes"]
        out["compiled_argument_bytes_zero1"] = bd1["argument_bytes"]
        ok = ok and bd1["argument_bytes"] < bd0["argument_bytes"]
    if bench:
        out["step_time_replicated_ms"] = _time_step(
            _mk_trainer(4, 0, hid=_BENCH_HID, nin=_BENCH_IN))
        out["step_time_zero1_ms"] = _time_step(
            _mk_trainer(4, 1, hid=_BENCH_HID, nin=_BENCH_IN))
        out["step_time_ratio"] = round(
            out["step_time_zero1_ms"] /
            max(out["step_time_replicated_ms"], 1e-9), 4)
    return ok


def _check_collectives(out):
    import jax
    from ..feature.feature_set import MiniBatch
    from ..parallel import zero
    trainer = _mk_trainer(4, 1)
    x, y = _data()
    batch = trainer._put_batch(MiniBatch([x], y, None))
    report = zero.collective_report(
        lambda p, o, s, b: trainer._step_body(p, o, s, b, 0),
        trainer.params, trainer.opt_state, trainer.net_state, batch)
    out["reduce_scatter_ops"] = len(report["reduce_scatter"])
    out["all_gather_ops"] = len(report["all_gather"])
    out["psum_sizes"] = report["psum"][:8]
    floor = sum(int(np.prod(p.shape, dtype=np.int64))
                for p in jax.tree.leaves(trainer.params))
    out["grad_numel_floor"] = floor
    zero.assert_zero_collectives(report, floor)
    return True


def run_smoke(stream=None, bench=False):
    """Run every check; returns (rc, payload dict)."""
    out = {}
    checks = {}
    for name, fn in (("parity_dp2", lambda o: _check_parity(o, 2)),
                     ("parity_dp4", lambda o: _check_parity(o, 4)),
                     ("opt_memory",
                      lambda o: _check_memory(o, bench=bench)),
                     ("collectives", _check_collectives)):
        try:
            checks[name] = bool(fn(out))
        except Exception as e:  # noqa: BLE001 — smoke must report, not die
            checks[name] = False
            out[f"{name}_error"] = (str(e).splitlines()[0][:200]
                                    if str(e) else repr(e)[:200])
        if stream is not None:
            stream.write(f"{'ok' if checks[name] else 'FAIL'}  {name}\n")
    payload = {
        "checks": checks,
        "parity_ok": checks["parity_dp2"] and checks["parity_dp4"],
        "opt_state_bytes_ratio": out.get("opt_state_bytes_ratio"),
        **out,
    }
    return (0 if all(checks.values()) else 1), payload


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="zero-smoke")
    ap.add_argument("--json", action="store_true",
                    help="print one JSON payload line on stdout")
    ap.add_argument("--bench", action="store_true",
                    help="also time the hot step for both stages")
    args = ap.parse_args(argv)
    # needs a 4-device host; re-exec once with the forced CPU topology
    # when short (shared helper, common/hostdev.py)
    from ..common.hostdev import reexec_module
    rc = reexec_module("analytics_zoo_tpu.pipeline.zero_smoke", 4, argv)
    if rc is not None:
        return rc
    rc, payload = run_smoke(stream=sys.stderr if args.json
                            else sys.stdout, bench=args.bench)
    if args.json:
        print(json.dumps(payload))
    else:
        print(("ZERO_SMOKE_OK" if rc == 0 else "ZERO_SMOKE_FAIL") +
              " " + " ".join(f"{k}={v}" for k, v in
                             payload["checks"].items()))
    return rc


if __name__ == "__main__":
    sys.exit(main())
