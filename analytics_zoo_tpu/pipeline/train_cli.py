"""``zoo-train`` — live training observability CLI.

The training-side sibling of ``zoo-serving top`` (serving/cli.py): a
terminal view of a run's TrainSummary event files plus the telemetry
exporter's ``metrics-<pid>.json``, refreshed in place.

::

    zoo-train top --logdir runs/logs/myapp [--trace-dir runs/trace]
    zoo-train summary --logdir runs/logs/myapp

Data sources (both optional — the view renders whatever exists):

* ``--logdir``: a TrainSummary directory (``<log_dir>/<app>/train`` or
  any directory holding ``events.out.tfevents.*``) — loss, learning
  rate, throughput, step time, infeed-bound fraction, grad norm, the
  HBM breakdown scalars and the latched health state.
* ``--trace-dir``: the telemetry trace dir — the freshest
  ``metrics-<pid>.json`` supplies live ``zoo_hbm_*`` watermark gauges
  and ``zoo_train_health_state`` even before the next summary flush.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

from ..utils import tensorboard

# TrainSummary tags the view reads (engine._epoch_loop writes them)
_TAGS = ["Loss", "LearningRate", "Throughput", "StepTimeMs",
         "InfeedWaitMs", "InputBoundFraction", "GradNorm", "MFU",
         "HealthState", "HBMTotalMB", "HBMParamsMB", "HBMOptStateMB",
         "HBMActivationsMB", "HBMTransfersMB"]

_HEALTH_NAMES = {0: "OK", 1: "WARN (spike latched)",
                 2: "FAULT (non-finite latched)", 3: "HALTED"}


def _summary_dir(logdir: str) -> Optional[str]:
    """Accept either the app log root (``<log_dir>/<app>``) or the train
    subdir / any dir holding event files directly."""
    if not logdir or not os.path.isdir(logdir):
        return None
    for cand in (logdir, os.path.join(logdir, "train")):
        if glob.glob(os.path.join(cand, "events.out.tfevents.*")):
            return cand
    return None


def read_latest_scalars(logdir: str) -> Dict[str, Tuple[int, float]]:
    """Last (step, value) per tag from the TrainSummary event files."""
    d = _summary_dir(logdir)
    out: Dict[str, Tuple[int, float]] = {}
    if d is None:
        return out
    try:
        events = tensorboard.read_scalars(d)
    except Exception:  # noqa: BLE001 - partial/in-flight writes
        return out
    for step, _wall, tag, value in events:
        if tag in _TAGS:
            prev = out.get(tag)
            if prev is None or step >= prev[0]:
                out[tag] = (int(step), float(value))
    return out


def read_live_gauges(trace_dir: str) -> Dict[str, float]:
    """Flatten the freshest ``metrics-*.json`` exporter snapshot in
    ``trace_dir`` into ``{name{labels}: value}`` for gauges/counters."""
    out: Dict[str, float] = {}
    if not trace_dir:
        return out
    paths = sorted(glob.glob(os.path.join(trace_dir, "metrics-*.json")),
                   key=lambda p: os.path.getmtime(p), reverse=True)
    if not paths:
        return out
    try:
        with open(paths[0]) as f:
            snap = json.load(f)
    except (OSError, ValueError):
        return out
    for m in snap.get("metrics", []):
        if "value" not in m:
            continue
        labels = m.get("labels") or {}
        key = m["name"]
        if labels:
            key += "{" + ",".join(f"{k}={v}" for k, v in
                                  sorted(labels.items())) + "}"
        out[key] = m["value"]
    return out


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024
    return f"{n:.1f} TiB"


def _val(scalars, tag):
    pair = scalars.get(tag)
    return pair[1] if pair else None


def render_status(logdir: str, trace_dir: Optional[str],
                  prev: Optional[dict] = None) -> List[str]:
    """One frame of the live view as printable lines. ``prev`` carries
    the last frame's (step, ts) so step/s can be derived between
    refreshes even when the run logs Throughput sparsely."""
    scalars = read_latest_scalars(logdir)
    gauges = read_live_gauges(trace_dir) if trace_dir else {}
    lines: List[str] = []
    if not scalars and not gauges:
        lines.append(f"  (no TrainSummary events under {logdir!r} yet"
                     + (f", no metrics snapshots under {trace_dir!r}"
                        if trace_dir else "") + ")")
        return lines
    step = max((s for s, _ in scalars.values()), default=0)
    loss = _val(scalars, "Loss")
    lr = _val(scalars, "LearningRate")
    head = f"  step {step}"
    if loss is not None:
        head += f"   loss {loss:.5g}"
    if lr is not None:
        head += f"   lr {lr:.3g}"
    lines.append(head)

    thr = _val(scalars, "Throughput")
    st_ms = _val(scalars, "StepTimeMs")
    wait_ms = _val(scalars, "InfeedWaitMs")
    bound = _val(scalars, "InputBoundFraction")
    mfu = _val(scalars, "MFU")
    row = []
    if st_ms:
        row.append(f"step time {st_ms:.1f} ms "
                   f"({1000.0 / max(st_ms, 1e-9):.1f} step/s)")
    if thr is not None:
        row.append(f"{thr:.1f} samples/s")
    if mfu is not None:
        row.append(f"MFU {mfu:.2f}")
    if row:
        lines.append("  " + "   ".join(row))
    if bound is not None:
        infeed = f"  infeed-bound {bound:.2f}"
        if wait_ms is not None:
            infeed += f" (wait {wait_ms:.1f} ms/step)"
        if bound > 0.1:
            infeed += "   <-- input-bound: the device is waiting on " \
                      "the host pipeline"
        lines.append(infeed)
    gn = _val(scalars, "GradNorm")
    if gn is not None:
        lines.append(f"  grad norm {gn:.4g}")

    total = _val(scalars, "HBMTotalMB")
    if total is not None:
        lines.append(
            "  HBM (train program): total "
            f"{total:.1f} MiB | params {_val(scalars, 'HBMParamsMB'):.1f}"
            f" | opt {_val(scalars, 'HBMOptStateMB'):.1f}"
            f" | act+temp {_val(scalars, 'HBMActivationsMB'):.1f}"
            f" | transfers {_val(scalars, 'HBMTransfersMB'):.1f}")
    # per-param-group optimizer-state breakout (ZeRO visibility): the
    # per-device gauge is where stage-1's 1/dp sharding shows up — the
    # global bytes stay flat across zero_stage, by design
    groups: Dict[str, Tuple[Optional[float], Optional[float]]] = {}
    for key, value in gauges.items():
        for name, slot in (
                ("zoo_hbm_program_opt_state_group_per_device_bytes{", 1),
                ("zoo_hbm_program_opt_state_group_bytes{", 0)):
            if key.startswith(name) and "program=train" in key:
                group = next((part.split("=", 1)[1] for part in
                              key[len(name):-1].split(",")
                              if part.startswith("group=")), None)
                if group is not None:
                    pair = list(groups.get(group, (None, None)))
                    pair[slot] = value
                    groups[group] = tuple(pair)
    if groups:
        lines.append("  opt state by group (global / per-device):")
        for group in sorted(groups):
            g_total, g_dev = groups[group]
            row = f"    {group:<24s}"
            row += _fmt_bytes(g_total) if g_total is not None else "?"
            if g_dev is not None:
                row += f" / {_fmt_bytes(g_dev)}"
            lines.append(row)
    in_use = {k: v for k, v in gauges.items()
              if k.startswith("zoo_hbm_bytes_in_use")}
    if in_use:
        peak = {k: v for k, v in gauges.items()
                if k.startswith("zoo_hbm_peak_bytes")}
        limit = {k: v for k, v in gauges.items()
                 if k.startswith("zoo_hbm_bytes_limit")}
        frac = gauges.get("zoo_hbm_watermark_fraction")
        row = (f"  HBM watermark: in-use {_fmt_bytes(sum(in_use.values()))}"
               f" peak {_fmt_bytes(sum(peak.values()))}")
        if limit:
            row += f" / {_fmt_bytes(sum(limit.values()))}"
        if frac is not None:
            row += f" ({100 * frac:.0f}%)"
        lines.append(row)

    health = gauges.get("zoo_train_health_state")
    if health is None:
        health = _val(scalars, "HealthState")
    if health is not None:
        name = _HEALTH_NAMES.get(int(health), str(health))
        lines.append(f"  health: {name}")
    return lines


def cmd_top(logdir: str, trace_dir: Optional[str] = None,
            interval: float = 2.0, iterations: Optional[int] = None) -> int:
    """Live training view, refreshed every ``interval`` seconds.
    ``iterations`` bounds the loop (tests / one-shot snapshots)."""
    done = 0
    try:
        while iterations is None or done < iterations:
            if sys.stdout.isatty():
                sys.stdout.write("\x1b[2J\x1b[H")
            print(f"zoo-train top  {time.strftime('%H:%M:%S')}  "
                  f"(refresh {interval:g}s, Ctrl-C to exit)")
            for line in render_status(logdir, trace_dir):
                print(line)
            sys.stdout.flush()
            done += 1
            if iterations is None or done < iterations:
                time.sleep(interval)
    except KeyboardInterrupt:
        pass
    return 0


def cmd_summary(logdir: str, trace_dir: Optional[str] = None) -> int:
    """One-shot machine-readable dump (JSON) of the same view."""
    scalars = read_latest_scalars(logdir)
    payload = {
        "logdir": logdir,
        "scalars": {tag: {"step": s, "value": v}
                    for tag, (s, v) in sorted(scalars.items())},
    }
    if trace_dir:
        payload["gauges"] = read_live_gauges(trace_dir)
    print(json.dumps(payload, indent=2, sort_keys=True))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="zoo-train")
    ap.add_argument("command", choices=["top", "summary"])
    ap.add_argument("--logdir", default=".",
                    help="TrainSummary directory: <log_dir>/<app> as "
                         "passed to set_tensorboard, its train/ subdir, "
                         "or any directory with events.out.tfevents.*")
    ap.add_argument("--trace-dir", default=None,
                    help="telemetry trace dir (--trace-dir of the run / "
                         "ZOO_TPU_TRACE_DIR): live zoo_hbm_* watermarks "
                         "and health state from metrics-<pid>.json")
    ap.add_argument("--interval", default=2.0, type=float,
                    help="top: refresh period in seconds")
    ap.add_argument("--iterations", default=None, type=int,
                    help="top: stop after N refreshes (default: forever)")
    args = ap.parse_args(argv)
    logdir = os.path.abspath(args.logdir)
    if args.command == "top":
        return cmd_top(logdir, trace_dir=args.trace_dir,
                       interval=args.interval, iterations=args.iterations)
    return cmd_summary(logdir, trace_dir=args.trace_dir)


if __name__ == "__main__":
    sys.exit(main())
