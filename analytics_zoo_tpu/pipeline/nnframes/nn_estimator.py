"""NNFrames: DataFrame-native training/inference stages.

Parity: ``zoo/.../pipeline/nnframes/NNEstimator.scala`` (class :198,
``internalFit``:414-479, ``getDataSet``:382, ``NNModel.internalTransform``
:665, persistence :743-870), ``NNClassifier.scala`` and the python mirror
``pyzoo/zoo/pipeline/nnframes/nn_classifier.py``.

TPU redesign: the reference is a Spark ML ``Estimator`` whose ``fit`` turns
a DataFrame into an RDD of Samples and hands it to the BlockManager-allreduce
optimizer.  Here the DataFrame is a **pandas** DataFrame (the declarative
column-in/column-out surface survives; the cluster scheduler does not — the
SPMD step is one XLA program and data feeding is the host prefetcher).  The
camelCase Spark-ML setter surface is kept verbatim so reference pipelines
port line-for-line; snake_case aliases are provided for idiomatic use.
"""

from __future__ import annotations

import os
import pickle
from typing import Optional, Sequence

import numpy as np

from ...common.zoo_trigger import EveryEpoch, MaxEpoch, ZooTrigger
from ...feature.common import (ChainedPreprocessing, Preprocessing,
                               SeqToMultipleTensors, SeqToTensor)
from ...feature.feature_set import FeatureSet, Sample
from ..api.keras.objectives import get_loss
from ..api.keras.optimizers import get_optimizer
from ..estimator.estimator import Estimator
from ..api.keras.models import KerasNet


def _sizes_to_preprocessing(spec):
    """The python reference accepts a Preprocessing OR a (nested) list of
    tensor sizes (nn_classifier.py:154-181): [5] -> SeqToTensor([5]);
    [[1],[2]] -> SeqToMultipleTensors."""
    if spec is None or isinstance(spec, Preprocessing):
        return spec
    if isinstance(spec, (list, tuple)):
        if len(spec) > 0 and isinstance(spec[0], (list, tuple)):
            return SeqToMultipleTensors(spec)
        return SeqToTensor(spec)
    raise TypeError(f"unsupported preprocessing spec: {spec!r}")


def _col_values(df, col):
    try:
        return df[col].tolist()
    except TypeError:  # not a pandas frame: dict of columns
        return list(df[col])


class _Params:
    """Minimal Spark-ML-param-style mixin: camelCase setters return self."""

    def setFeaturesCol(self, value):
        self.features_col = value
        return self

    def setLabelCol(self, value):
        self.label_col = value
        return self

    def setPredictionCol(self, value):
        self.prediction_col = value
        return self

    def setBatchSize(self, value):
        self.batch_size = int(value)
        return self

    def getBatchSize(self):
        return self.batch_size

    # snake_case aliases
    set_features_col = setFeaturesCol
    set_label_col = setLabelCol
    set_prediction_col = setPredictionCol
    set_batch_size = setBatchSize


class NNEstimator(_Params):
    """``NNEstimator(model, criterion, feature_preprocessing,
    label_preprocessing)`` — fit(df) -> NNModel.

    ``model`` is a KerasNet (Sequential/Model); ``criterion`` a loss name or
    LossFunction; preprocessings are Preprocessing chains or size lists.
    """

    def __init__(self, model: KerasNet, criterion,
                 feature_preprocessing=None, label_preprocessing=None):
        self.model = model
        self.criterion = get_loss(criterion)
        self.feature_preprocessing = _sizes_to_preprocessing(
            feature_preprocessing)
        self.label_preprocessing = _sizes_to_preprocessing(
            label_preprocessing)
        self.sample_preprocessing: Optional[Preprocessing] = None
        self.features_col = "features"
        self.label_col = "label"
        self.prediction_col = "prediction"
        self.batch_size = 32
        self.max_epoch = 1
        self.end_when: Optional[ZooTrigger] = None
        self.learning_rate = 1e-3
        self.learning_rate_decay = 0.0
        self.optim_method = None
        self.caching_sample = True
        self.train_summary = None
        self.validation_summary = None
        self.validation = None  # (trigger, df, methods, batch_size)
        self.checkpoint = None  # (path, trigger, overwrite)
        self._clipping = None   # ("const", lo, hi) | ("l2", norm) | None
        self.data_cache_level = "DRAM"

    # -- Spark-ML-style configuration surface --------------------------
    def setSamplePreprocessing(self, value):
        self.sample_preprocessing = value
        return self

    def setMaxEpoch(self, value):
        self.max_epoch = int(value)
        return self

    def getMaxEpoch(self):
        return self.max_epoch

    def setEndWhen(self, trigger: ZooTrigger):
        self.end_when = trigger
        return self

    def getEndWhen(self):
        return self.end_when

    def setDataCacheLevel(self, level, num_slice=None):
        """Accepted for parity (NNEstimator.scala:260); the only tier on
        TPU hosts is RAM, so this records intent and nothing else."""
        self.data_cache_level = level
        return self

    def getDataCacheLevel(self):
        return self.data_cache_level

    def setLearningRate(self, value):
        self.learning_rate = float(value)
        return self

    def getLearningRate(self):
        return self.learning_rate

    def setLearningRateDecay(self, value):
        self.learning_rate_decay = float(value)
        return self

    def getLearningRateDecay(self):
        return self.learning_rate_decay

    def setOptimMethod(self, value):
        self.optim_method = value
        return self

    def getOptimMethod(self):
        return self.optim_method

    def setCachingSample(self, value):
        self.caching_sample = bool(value)
        return self

    def isCachingSample(self):
        return self.caching_sample

    def setTrainSummary(self, value):
        self.train_summary = value
        return self

    def getTrainSummary(self):
        return self.train_summary

    def setValidationSummary(self, value):
        self.validation_summary = value
        return self

    def getValidationSummary(self):
        return self.validation_summary

    def setValidation(self, trigger, val_df, val_method, batch_size):
        self.validation = (trigger, val_df, val_method, int(batch_size))
        return self

    def getValidation(self):
        return self.validation

    def clearGradientClipping(self):
        self._clipping = None
        return self

    def setConstantGradientClipping(self, min, max):  # noqa: A002
        self._clipping = ("const", float(min), float(max))
        return self

    def setGradientClippingByL2Norm(self, clip_norm):
        self._clipping = ("l2", float(clip_norm))
        return self

    def setCheckpoint(self, path, trigger=None, isOverWrite=True):
        self.checkpoint = (path, trigger or EveryEpoch(), isOverWrite)
        return self

    def getCheckpoint(self):
        return self.checkpoint

    # snake_case aliases
    set_sample_preprocessing = setSamplePreprocessing
    set_max_epoch = setMaxEpoch
    set_end_when = setEndWhen
    set_learning_rate = setLearningRate
    set_learning_rate_decay = setLearningRateDecay
    set_optim_method = setOptimMethod
    set_caching_sample = setCachingSample
    set_train_summary = setTrainSummary
    set_validation_summary = setValidationSummary
    set_validation = setValidation
    set_checkpoint = setCheckpoint
    clear_gradient_clipping = clearGradientClipping
    set_constant_gradient_clipping = setConstantGradientClipping
    set_gradient_clipping_by_l2_norm = setGradientClippingByL2Norm

    # -- dataset extraction (getDataSet parity, NNEstimator.scala:382) --
    def _row_to_sample(self, f, lbl) -> Sample:
        if self.sample_preprocessing is not None:
            return self.sample_preprocessing.apply((f, lbl))
        fv = self.feature_preprocessing.apply(f) \
            if self.feature_preprocessing else np.asarray(f, np.float32)
        lv = None
        if lbl is not None:
            lv = self.label_preprocessing.apply(lbl) \
                if self.label_preprocessing else np.asarray(lbl, np.float32)
        return Sample(fv, lv)

    def _raw_columns(self, df, with_label=True):
        feats = _col_values(df, self.features_col)
        labels = None
        if with_label and self.label_col is not None and \
                self.label_col in getattr(df, "columns", df):
            labels = _col_values(df, self.label_col)
        return feats, labels

    def _samples_from_columns(self, feats, labels):
        return [self._row_to_sample(
            f, labels[i] if labels is not None else None)
            for i, f in enumerate(feats)]

    def _extract_samples(self, df, with_label=True):
        return self._samples_from_columns(*self._raw_columns(df, with_label))

    @staticmethod
    def _sample_nbytes(sample: Sample) -> int:
        total = 0
        for part in (sample.features, sample.labels):
            for a in (part or ()):
                total += np.asarray(a).nbytes
        return total

    def _maybe_spill(self, feats, labels) -> Optional[FeatureSet]:
        """Auto-spill (VERDICT r3 next #8): when the PROCESSED samples of
        the DataFrame would exceed ``config.nnframes_spill_bytes``
        (preprocessing can expand rows by orders of magnitude — an image
        path becomes a 224x224x3 tensor), write ~64 MB ``.npz`` shards and
        stream them via ShardedFileFeatureSet instead of keeping every
        sample resident. The estimate processes a handful of rows spread
        across the dataset; the spill then processes chunk-by-chunk, so
        peak memory is one shard, not the dataset. The spill directory lives as long as the returned
        FeatureSet (weakref finalizer removes it)."""
        from ...common.nncontext import get_nncontext
        from ...feature.feature_set import (DiskFeatureSet,
                                            ShardedFileFeatureSet,
                                            stack_samples)

        threshold = get_nncontext().config.nnframes_spill_bytes
        n = len(feats)
        if n == 0:
            return None
        # probe rows spread across the dataset, not just row 0: with
        # heterogeneous rows (variable-length sequences, mixed image
        # sizes) a small first row would underestimate the total and the
        # spill would silently never trigger
        probe_idx = sorted({int(i) for i in
                            np.linspace(0, n - 1, num=min(n, 8))})
        probe_sizes = [max(1, self._sample_nbytes(self._row_to_sample(
            feats[i], labels[i] if labels is not None else None)))
            for i in probe_idx]
        per_sample = max(1, int(np.mean(probe_sizes)))
        if per_sample * n <= threshold:
            return None
        import shutil
        import tempfile
        import weakref

        # each shard must respect the memory bound that triggered the
        # spill (and a 64 MB practical cap); size shards by the LARGEST
        # probed row so oversized rows can't blow the bound
        shard_bytes = min(threshold, 64 << 20)
        shard_rows = int(min(n, max(1, shard_bytes // max(probe_sizes))))
        spill_dir = tempfile.mkdtemp(prefix="zoo_nnframes_spill_")
        paths = []
        for start in range(0, n, shard_rows):
            chunk = [self._row_to_sample(
                feats[i], labels[i] if labels is not None else None)
                for i in range(start, min(start + shard_rows, n))]
            xs, ys = stack_samples(chunk)
            path = os.path.join(spill_dir,
                                f"shard{start // shard_rows:05d}.npz")
            DiskFeatureSet.write_shard(path, list(xs), ys)
            paths.append(path)
        import logging
        logging.getLogger("analytics_zoo_tpu.nnframes").info(
            "NNFrames ingest spilled %d samples (~%.1f MB) to %d shards "
            "under %s", n, per_sample * n / 1e6, len(paths), spill_dir)
        # the shards were written from THIS process's rows — no further
        # per-host striping (shard_per_host would drop all but 1/P of them)
        fs = ShardedFileFeatureSet(paths, num_slice=1, shard_per_host=False)
        weakref.finalize(fs, shutil.rmtree, spill_dir, ignore_errors=True)
        return fs

    def _get_dataset(self, df, with_label=True) -> FeatureSet:
        # scalable ingest (SURVEY hard part (a)): a FeatureSet — notably
        # FeatureSet.files() over per-host-striped shards — streams
        # directly into the engine instead of materializing columns
        if isinstance(df, FeatureSet):
            return df
        if isinstance(df, str):
            # dataset URI (partitioned parquet/arrow directory): every
            # non-label column is a feature; each host streams its
            # disjoint size-balanced shard subset (feature/dataset.py)
            return FeatureSet.from_dataset(df, label_col=self.label_col)
        if isinstance(df, (list, tuple)) and df and \
                all(isinstance(p, str) for p in df):
            return FeatureSet.files(list(df), label_col=self.label_col)
        feats, labels = self._raw_columns(df, with_label)
        spilled = self._maybe_spill(feats, labels)
        if spilled is not None:
            return spilled
        return FeatureSet.samples(self._samples_from_columns(feats, labels))

    # -- fit (internalFit parity, NNEstimator.scala:414-479) ------------
    def fit(self, df) -> "NNModel":
        train_set = self._get_dataset(df)
        optimizer = get_optimizer(
            self.optim_method if self.optim_method is not None else "sgd")
        if self.optim_method is None:
            optimizer.lr = self.learning_rate
            optimizer.decay = self.learning_rate_decay
        ckpt_dir = self.checkpoint[0] if self.checkpoint else None
        est = Estimator(self.model, optim_methods=optimizer,
                        model_dir=ckpt_dir)
        if self._clipping is not None:
            if self._clipping[0] == "const":
                est.set_constant_gradient_clipping(*self._clipping[1:])
            else:
                est.set_l2_norm_gradient_clipping(self._clipping[1])
        trainer = est._ensure_trainer(self.criterion, None)
        if self.train_summary is not None:
            trainer.train_summary = self.train_summary
        if self.validation_summary is not None:
            trainer.val_summary = self.validation_summary

        validation_set = validation_trigger = validation_methods = None
        if self.validation is not None:
            validation_trigger, val_df, validation_methods, _ = \
                self.validation
            validation_set = self._get_dataset(val_df)
        end_trigger = self.end_when or MaxEpoch(self.max_epoch)
        ckpt_trigger = self.checkpoint[1] if self.checkpoint else None
        criterion = self.criterion
        trainer.loss_fn = criterion
        if validation_methods:
            from ..api.keras.metrics import get_metric
            trainer.metrics = [get_metric(m, criterion)
                               for m in validation_methods]
        trainer.train(train_set, batch_size=self.batch_size,
                      end_trigger=end_trigger,
                      checkpoint_trigger=ckpt_trigger,
                      validation_set=validation_set,
                      validation_trigger=validation_trigger)
        est._sync_model()
        return self._create_model(self.model)

    def _create_model(self, model) -> "NNModel":
        m = NNModel(model, feature_preprocessing=self.feature_preprocessing)
        m.features_col = self.features_col
        m.prediction_col = self.prediction_col
        m.batch_size = self.batch_size
        return m


class NNModel(_Params):
    """Transformer: adds ``prediction_col`` to a DataFrame
    (NNModel.internalTransform parity — broadcast model + per-partition
    predict becomes one jitted predict over prefetched batches)."""

    def __init__(self, model: KerasNet, feature_preprocessing=None):
        self.model = model
        self.feature_preprocessing = _sizes_to_preprocessing(
            feature_preprocessing)
        self.features_col = "features"
        self.prediction_col = "prediction"
        self.batch_size = 128

    def _featurize(self, df):
        feats = _col_values(df, self.features_col)
        samples = []
        for f in feats:
            fv = self.feature_preprocessing.apply(f) \
                if self.feature_preprocessing else np.asarray(f, np.float32)
            samples.append(Sample(fv))
        return FeatureSet.samples(samples)

    def transform(self, df):
        fs = self._featurize(df)
        preds = self.model.predict(fs, batch_size=self.batch_size)
        out = df.copy()
        if isinstance(preds, list):  # multi-output: tuple rows
            out[self.prediction_col] = list(zip(*[list(p) for p in preds]))
        else:
            vals = [p.tolist() if getattr(p, "ndim", 0) > 0 else float(p)
                    for p in preds]
            out[self.prediction_col] = vals
        return out

    predict = transform

    # -- ML persistence (NNEstimator.scala:743-870) ---------------------
    def save(self, path):
        os.makedirs(path, exist_ok=True)
        self.model.save_model(os.path.join(path, "model"), over_write=True)
        meta = {"class": type(self).__name__,
                "features_col": self.features_col,
                "prediction_col": self.prediction_col,
                "batch_size": self.batch_size,
                "feature_preprocessing": self.feature_preprocessing,
                "extra": self._save_extra()}
        with open(os.path.join(path, "nnmodel.pkl"), "wb") as f:
            pickle.dump(meta, f)

    def _save_extra(self):
        return {}

    @staticmethod
    def load(path) -> "NNModel":
        with open(os.path.join(path, "nnmodel.pkl"), "rb") as f:
            meta = pickle.load(f)
        klass = {"NNModel": NNModel,
                 "NNClassifierModel": NNClassifierModel}[meta["class"]]
        model = KerasNet.load_model(os.path.join(path, "model"))
        obj = klass(model,
                    feature_preprocessing=meta["feature_preprocessing"])
        obj.features_col = meta["features_col"]
        obj.prediction_col = meta["prediction_col"]
        obj.batch_size = meta["batch_size"]
        for k, v in meta.get("extra", {}).items():
            setattr(obj, k, v)
        return obj


class NNClassifier(NNEstimator):
    """Classification specialization: scalar label column, argmax
    prediction (NNClassifier.scala)."""

    def __init__(self, model, criterion=None, feature_preprocessing=None):
        super().__init__(model, criterion or "sparse_categorical_crossentropy",
                         feature_preprocessing=feature_preprocessing,
                         label_preprocessing=None)

    def _create_model(self, model) -> "NNClassifierModel":
        m = NNClassifierModel(
            model, feature_preprocessing=self.feature_preprocessing)
        m.features_col = self.features_col
        m.prediction_col = self.prediction_col
        m.batch_size = self.batch_size
        return m


class NNClassifierModel(NNModel):
    """Adds argmax + optional binary threshold (HasThreshold parity)."""

    def __init__(self, model, feature_preprocessing=None):
        super().__init__(model, feature_preprocessing)
        self.threshold = 0.5

    def setThreshold(self, value):
        self.threshold = float(value)
        return self

    set_threshold = setThreshold

    def _save_extra(self):
        return {"threshold": self.threshold}

    def transform(self, df):
        fs = self._featurize(df)
        preds = self.model.predict(fs, batch_size=self.batch_size)
        preds = np.asarray(preds)
        if preds.ndim <= 1 or preds.shape[-1] == 1:
            cls = (preds.reshape(len(preds)) > self.threshold).astype(
                np.float64)
        else:
            cls = np.argmax(preds, axis=-1).astype(np.float64)
        out = df.copy()
        out[self.prediction_col] = cls
        return out
