"""TransformerLayer and BERT.

Parity surface: ``keras/layers/TransformerLayer.scala`` (279 LoC; GPT-style
decoder blocks, post-LN, gelu, optional bidirectional) and
``keras/layers/BERT.scala`` (402 LoC; 4 inputs — token ids, positions,
segment ids, attention mask; outputs per-block sequence states + pooled
output; erf-based gelu; extended mask = (1-mask)*-10000).

TPU redesign: one KerasLayer owning all block params (pytree), attention via
the Pallas flash kernel (ops/attention.py), dropout fused in-jit, params
annotated with logical axes so ``parallel.sharding`` can lay them out over a
('data','model') mesh (qkv/mlp-in column-parallel, proj/mlp-out row-parallel
— Megatron layout, collectives inserted by XLA).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .....ops.attention import flash_attention
from ..engine.base import KerasLayer, init_tensor


def _normal(rng, shape, std):
    return std * jax.random.normal(rng, shape, jnp.float32)


def _dropout(x, p, rng, training):
    if not training or rng is None or p <= 0.0:
        return x
    keep = 1.0 - p
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


class TransformerLayer(KerasLayer):
    """GPT-style transformer stack.

    Inputs: token ids ``(B, L)`` (positions are implicit arange, parity with
    the reference's position-offset embedding). Outputs
    ``[sequence_states, pooled]`` (or all block states + pooled when
    ``output_all_block``).
    """

    stochastic = True
    gelu_approximate = True  # TransformerLayer.scala uses the tanh approx

    def __init__(self, n_block, hidden_p_drop=0.1, attn_p_drop=0.1,
                 n_head=12, initializer_range=0.02, bidirectional=False,
                 output_all_block=False, intermediate_size=0,
                 vocab=40990, seq_len=77, hidden_size=768,
                 embedding_layer=None, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.n_block = int(n_block)
        self.n_head = int(n_head)
        self.hidden_p_drop = hidden_p_drop
        self.attn_p_drop = attn_p_drop
        self.initializer_range = initializer_range
        self.bidirectional = bidirectional
        self.output_all_block = output_all_block
        self.vocab = int(vocab)
        self.seq_len = int(seq_len)
        self.hidden_size = int(hidden_size)
        self.embedding_layer = embedding_layer
        if embedding_layer is not None:
            # custom embedding (reference API): hidden size comes from its
            # output shape; it consumes the non-mask inputs
            out_shape = embedding_layer.compute_output_shape(
                (None, self.seq_len))
            self.hidden_size = int(out_shape[-1])
        self.intermediate_size = int(intermediate_size) or \
            4 * self.hidden_size
        assert self.hidden_size % self.n_head == 0
        self.num_outputs = (self.n_block if output_all_block else 1) + 1

    # -- params --------------------------------------------------------
    def _embedding_params(self, rng):
        if self.embedding_layer is not None:
            return {"embedding": self.embedding_layer.build(
                rng, (None, self.seq_len))}
        r1, r2 = jax.random.split(rng)
        params = {
            "tok_emb": _normal(r1, (self.vocab, self.hidden_size),
                               self.initializer_range),
            "pos_emb": _normal(r2, (self.seq_len, self.hidden_size),
                               self.initializer_range),
        }
        self._annotate(tok_emb=("vocab", "embed"),
                       pos_emb=(None, "embed"))
        return params

    def _block_params(self, rng, i):
        h = self.hidden_size
        m = self.intermediate_size
        keys = jax.random.split(rng, 4)
        std = self.initializer_range
        p = {
            "qkv_w": _normal(keys[0], (h, 3 * h), std),
            "qkv_b": jnp.zeros((3 * h,)),
            "proj_w": _normal(keys[1], (h, h), std),
            "proj_b": jnp.zeros((h,)),
            "ln1_g": jnp.ones((h,)), "ln1_b": jnp.zeros((h,)),
            "mlp_in_w": _normal(keys[2], (h, m), std),
            "mlp_in_b": jnp.zeros((m,)),
            "mlp_out_w": _normal(keys[3], (m, h), std),
            "mlp_out_b": jnp.zeros((h,)),
            "ln2_g": jnp.ones((h,)), "ln2_b": jnp.zeros((h,)),
        }
        self._annotate(**{
            f"block{i}/qkv_w": ("embed", "heads"),
            f"block{i}/qkv_b": ("heads",),
            f"block{i}/proj_w": ("heads", "embed"),
            f"block{i}/mlp_in_w": ("embed", "mlp"),
            f"block{i}/mlp_in_b": ("mlp",),
            f"block{i}/mlp_out_w": ("mlp", "embed"),
        })
        return p

    def build(self, rng, input_shape):
        rngs = jax.random.split(rng, self.n_block + 2)
        params = self._embedding_params(rngs[0])
        for i in range(self.n_block):
            params[f"block{i}"] = self._block_params(rngs[i + 1], i)
        params["pooler_w"] = _normal(rngs[-1],
                                     (self.hidden_size, self.hidden_size),
                                     self.initializer_range)
        params["pooler_b"] = jnp.zeros((self.hidden_size,))
        return params

    # -- compute -------------------------------------------------------
    def _ln(self, x, g, b, eps=1e-5):
        xf = x.astype(jnp.float32)
        mu = xf.mean(-1, keepdims=True)
        var = jnp.square(xf - mu).mean(-1, keepdims=True)
        return ((xf - mu) * jax.lax.rsqrt(var + eps) * g + b).astype(x.dtype)

    def _gelu(self, x):
        return jax.nn.gelu(x, approximate=self.gelu_approximate)

    def _attention(self, p, x, mask_bias, rng, training):
        b, l, h = x.shape
        nh = self.n_head
        d = h // nh
        qkv = jnp.matmul(x, p["qkv_w"].astype(x.dtype)) + \
            p["qkv_b"].astype(x.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(b, l, nh, d).transpose(0, 2, 1, 3)

        o = flash_attention(heads(q), heads(k), heads(v), bias=mask_bias,
                            causal=not self.bidirectional)
        o = o.transpose(0, 2, 1, 3).reshape(b, l, h)
        if rng is not None:
            rng, sub = jax.random.split(rng)
            o = _dropout(o, self.attn_p_drop, sub, training)
        o = jnp.matmul(o, p["proj_w"].astype(x.dtype)) + \
            p["proj_b"].astype(x.dtype)
        return o

    def _block(self, p, x, mask_bias, rng, training):
        r1 = r2 = r3 = None
        if rng is not None:
            r1, r2, r3 = jax.random.split(rng, 3)
        a = self._attention(p, x, mask_bias, r1, training)
        a = _dropout(a, self.hidden_p_drop, r2, training)
        n = self._ln(x + a, p["ln1_g"], p["ln1_b"])
        m = jnp.matmul(n, p["mlp_in_w"].astype(x.dtype)) + \
            p["mlp_in_b"].astype(x.dtype)
        m = self._gelu(m)
        m = jnp.matmul(m, p["mlp_out_w"].astype(x.dtype)) + \
            p["mlp_out_b"].astype(x.dtype)
        m = _dropout(m, self.hidden_p_drop, r3, training)
        return self._ln(n + m, p["ln2_g"], p["ln2_b"])

    def _embed(self, params, inputs, rng, training):
        if self.embedding_layer is not None:
            x = inputs if not isinstance(inputs, (list, tuple)) or \
                len(inputs) > 1 else inputs[0]
            e = self.embedding_layer.call(params["embedding"], x,
                                          training=training)
            return e, None
        tokens = (inputs[0] if isinstance(inputs, (list, tuple))
                  else inputs).astype(jnp.int32)
        e = jnp.take(params["tok_emb"], tokens, axis=0)
        e = e + params["pos_emb"][None, :e.shape[1]]
        return e, None

    def _pooler(self, params, x):
        first = x[:, 0]
        return jnp.tanh(jnp.matmul(first, params["pooler_w"]
                                   .astype(x.dtype)) +
                        params["pooler_b"].astype(x.dtype))

    def call(self, params, inputs, training=False, rng=None, **kw):
        e, mask_bias = self._embed(params, inputs, rng, training)
        if rng is not None:
            rng, sub = jax.random.split(rng)
            e = _dropout(e, self.hidden_p_drop, sub, training)
        states = []
        x = e
        for i in range(self.n_block):
            block_rng = None
            if rng is not None:
                rng, block_rng = jax.random.split(rng)
            x = self._block(params[f"block{i}"], x, mask_bias, block_rng,
                            training)
            states.append(x)
        pooled = self._pooler(params, x)
        if self.output_all_block:
            return tuple(states) + (pooled,)
        return (x, pooled)

    def compute_output_shape(self, input_shape):
        first = input_shape[0] if isinstance(input_shape, list) \
            else input_shape
        seq_shape = (first[0], first[1], self.hidden_size)
        pooled = (first[0], self.hidden_size)
        if self.output_all_block:
            return [seq_shape] * self.n_block + [pooled]
        return [seq_shape, pooled]


class BERT(TransformerLayer):
    """BERT encoder (BERT.scala). Inputs: ``[token_ids (B,L),
    position_ids (B,L), segment_ids (B,L), attention_mask (B,1,1,L)]``."""

    gelu_approximate = False  # BERT.scala overrides gelu with the erf form

    def __init__(self, vocab=40990, hidden_size=768, n_block=12, n_head=12,
                 seq_len=512, intermediate_size=3072, hidden_p_drop=0.1,
                 attn_p_drop=0.1, initializer_range=0.02,
                 output_all_block=True, input_shape=None, name=None,
                 **kwargs):
        super().__init__(
            n_block=n_block, hidden_p_drop=hidden_p_drop,
            attn_p_drop=attn_p_drop, n_head=n_head,
            initializer_range=initializer_range, bidirectional=True,
            output_all_block=output_all_block,
            intermediate_size=intermediate_size, vocab=vocab,
            seq_len=seq_len, hidden_size=hidden_size,
            input_shape=input_shape, name=name)

    def _embedding_params(self, rng):
        params = super()._embedding_params(rng)
        r = jax.random.fold_in(rng, 7)
        params["seg_emb"] = _normal(r, (2, self.hidden_size),
                                    self.initializer_range)
        params["emb_ln_g"] = jnp.ones((self.hidden_size,))
        params["emb_ln_b"] = jnp.zeros((self.hidden_size,))
        return params

    def _embed(self, params, inputs, rng, training):
        tokens, positions, segments, mask = inputs
        tokens = tokens.astype(jnp.int32)
        positions = positions.astype(jnp.int32)
        segments = segments.astype(jnp.int32)
        e = jnp.take(params["tok_emb"], tokens, axis=0)
        e = e + jnp.take(params["pos_emb"], positions, axis=0)
        e = e + jnp.take(params["seg_emb"], segments, axis=0)
        e = self._ln(e, params["emb_ln_g"], params["emb_ln_b"], eps=1e-12)
        # extended mask, parity with BERT.scala buildInput:
        # (-mask + 1) * -10000
        mask_bias = (1.0 - mask.astype(jnp.float32)) * -10000.0
        return e, mask_bias

    def compute_output_shape(self, input_shape):
        first = input_shape[0]
        seq_shape = (first[0], first[1], self.hidden_size)
        pooled = (first[0], self.hidden_size)
        if self.output_all_block:
            return [seq_shape] * self.n_block + [pooled]
        return [seq_shape, pooled]
