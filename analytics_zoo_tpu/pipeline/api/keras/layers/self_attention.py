"""TransformerLayer and BERT.

Parity surface: ``keras/layers/TransformerLayer.scala`` (279 LoC; GPT-style
decoder blocks, post-LN, gelu, optional bidirectional) and
``keras/layers/BERT.scala`` (402 LoC; 4 inputs — token ids, positions,
segment ids, attention mask; outputs per-block sequence states + pooled
output; erf-based gelu; extended mask = (1-mask)*-10000).

TPU redesign: one KerasLayer owning all block params (pytree), attention via
the Pallas flash kernel (ops/attention.py), dropout fused in-jit, params
annotated with logical axes so ``parallel.sharding`` can lay them out over a
('data','model') mesh (qkv/mlp-in column-parallel, proj/mlp-out row-parallel
— Megatron layout, collectives inserted by XLA).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .....common.jax_compat import shard_map as _shard_map
from .....ops.attention import flash_attention_blhd
from .....ops.fused_dropout_ln import dropout_add_layer_norm
from ..engine.base import KerasLayer, init_tensor


def _normal(rng, shape, std):
    return std * jax.random.normal(rng, shape, jnp.float32)


def _dropout(x, p, rng, training):
    if not training or rng is None or p <= 0.0:
        return x
    keep = 1.0 - p
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


def _dp_dropout_add_ln(x, resid, gamma, beta, rng, p_drop, training):
    """dropout_add_layer_norm, entered through a pure-dp shard_map when
    one is needed for the kernel to engage (see _dp_mesh). The key is
    folded with the shard index so dropout masks decorrelate across
    data shards."""
    dp = _dp_mesh(x.shape[0])
    if dp is None or not training or rng is None or p_drop <= 0.0:
        return dropout_add_layer_norm(x, resid, gamma, beta, rng,
                                      p_drop, training)
    from jax.sharding import PartitionSpec as P
    px = P("data", None, None)
    pv = P(None)

    def body(x_, r_, g_, b_, key_):
        key_ = jax.random.fold_in(key_, jax.lax.axis_index("data"))
        return dropout_add_layer_norm(x_, r_, g_, b_, key_, p_drop,
                                      training)

    # check_vma=False: pallas interpret mode cannot trace under the vma
    # checker (jax's own error suggests this flag), and the region is a
    # single elementwise+rowwise op — gradient correctness of the wrap
    # (incl. the replicated gamma/beta psum on transpose) is pinned by
    # test_dp_wrap_grad_parity on the 8-device mesh
    return _shard_map(body, mesh=dp, in_specs=(px, px, pv, pv, P()),
                      out_specs=px, check_vma=False)(
        x, resid, gamma, beta, rng)


def _dp_mesh(batch):
    """The active mesh when kernels need a shard_map to engage: pure
    data parallelism (>1 devices, every other axis 1), batch divisible,
    and not already inside a shard_map. Mosaic custom calls cannot be
    auto-partitioned (ops/attention.py mosaic_partition_ok), so under a
    dp>1 mesh the layer enters a fully-manual shard_map at its kernel
    sites itself — batch-parallel attention and dropout+add+LN are
    embarrassingly parallel, so the wrap is spec-exact (no resharding)
    and the XLA fallback inside computes identically when the kernels
    stay ineligible. Mixed layouts (tp/pp/sp/ep) are handled by their
    own shard_map paths or the XLA fallback."""
    from .....common import nncontext as _nn
    ctx = _nn._global_context
    if ctx is None:
        return None
    sizes = dict(ctx.mesh.shape)
    dp = int(sizes.get("data", 1))
    if dp <= 1 or any(int(v) > 1 for k, v in sizes.items()
                      if k != "data"):
        return None
    if batch % dp != 0:
        return None
    try:
        from jax._src import mesh as _jmesh
        if tuple(getattr(_jmesh.get_abstract_mesh(), "axis_names",
                         ()) or ()):
            return None          # already inside a shard_map
    except Exception as e:  # noqa: BLE001 - private API moved; don't wrap
        global _MESH_PROBE_WARNED
        if not _MESH_PROBE_WARNED:
            _MESH_PROBE_WARNED = True
            import logging
            logging.getLogger(
                "analytics_zoo_tpu.pipeline.api.keras").warning(
                "jax._src.mesh probe failed (%s): cannot detect an "
                "enclosing shard_map after this jax upgrade, so the "
                "pure-dp kernel wrap stays DISABLED (XLA fallback, "
                "correct but slower). Update _dp_mesh for the new jax "
                "private-API layout.", e)
        return None
    return ctx.mesh


_MESH_PROBE_WARNED = False


class TransformerLayer(KerasLayer):
    """GPT-style transformer stack.

    Inputs: token ids ``(B, L)`` (positions are implicit arange, parity with
    the reference's position-offset embedding). Outputs
    ``[sequence_states, pooled]`` (or all block states + pooled when
    ``output_all_block``).
    """

    stochastic = True
    gelu_approximate = True  # TransformerLayer.scala uses the tanh approx

    def __init__(self, n_block, hidden_p_drop=0.1, attn_p_drop=0.1,
                 n_head=12, initializer_range=0.02, bidirectional=False,
                 output_all_block=False, intermediate_size=0,
                 vocab=40990, seq_len=77, hidden_size=768,
                 embedding_layer=None, moe_experts=0, moe_top_k=2,
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.n_block = int(n_block)
        self.n_head = int(n_head)
        self.hidden_p_drop = hidden_p_drop
        self.attn_p_drop = attn_p_drop
        self.initializer_range = initializer_range
        self.bidirectional = bidirectional
        self.output_all_block = output_all_block
        self.vocab = int(vocab)
        self.seq_len = int(seq_len)
        self.hidden_size = int(hidden_size)
        self.embedding_layer = embedding_layer
        # moe_experts > 0 swaps each block's MLP for a SparseMoE (expert
        # parallelism reachable from the model zoo, VERDICT r2 #8)
        self.moe_experts = int(moe_experts)
        self.moe_top_k = int(moe_top_k)
        self._moe = None
        if embedding_layer is not None:
            # custom embedding (reference API): hidden size comes from its
            # output shape; it consumes the non-mask inputs
            out_shape = embedding_layer.compute_output_shape(
                (None, self.seq_len))
            self.hidden_size = int(out_shape[-1])
        self.intermediate_size = int(intermediate_size) or \
            4 * self.hidden_size
        assert self.hidden_size % self.n_head == 0
        self.num_outputs = (self.n_block if output_all_block else 1) + 1

    # -- params --------------------------------------------------------
    def _embedding_params(self, rng):
        if self.embedding_layer is not None:
            return {"embedding": self.embedding_layer.build(
                rng, (None, self.seq_len))}
        r1, r2 = jax.random.split(rng)
        params = {
            "tok_emb": _normal(r1, (self.vocab, self.hidden_size),
                               self.initializer_range),
            "pos_emb": _normal(r2, (self.seq_len, self.hidden_size),
                               self.initializer_range),
        }
        self._annotate(tok_emb=("vocab", "embed"),
                       pos_emb=(None, "embed"))
        return params

    def _block_params(self, rng):
        h = self.hidden_size
        m = self.intermediate_size
        keys = jax.random.split(rng, 5)
        std = self.initializer_range
        p = {
            "qkv_w": _normal(keys[0], (h, 3 * h), std),
            "qkv_b": jnp.zeros((3 * h,)),
            "proj_w": _normal(keys[1], (h, h), std),
            "proj_b": jnp.zeros((h,)),
            "ln1_g": jnp.ones((h,)), "ln1_b": jnp.zeros((h,)),
            "ln2_g": jnp.ones((h,)), "ln2_b": jnp.zeros((h,)),
        }
        if self.moe_experts:
            p["moe"] = self._moe.build(keys[2], (None, self.seq_len, h))
        else:
            p.update({
                "mlp_in_w": _normal(keys[2], (h, m), std),
                "mlp_in_b": jnp.zeros((m,)),
                "mlp_out_w": _normal(keys[3], (m, h), std),
                "mlp_out_b": jnp.zeros((h,)),
            })
        return p

    def _block_axis_map(self):
        """Logical axes per block param (Megatron TP layout)."""
        axes = {
            "qkv_w": ("embed", "heads"), "qkv_b": ("heads",),
            "proj_w": ("heads", "embed"), "proj_b": (None,),
            "ln1_g": (None,), "ln1_b": (None,),
            "ln2_g": (None,), "ln2_b": (None,),
        }
        if self.moe_experts:
            for k, v in self._moe.param_axes().items():
                axes[f"moe/{k}"] = v
        else:
            axes.update({"mlp_in_w": ("embed", "mlp"),
                         "mlp_in_b": ("mlp",),
                         "mlp_out_w": ("mlp", "embed"),
                         "mlp_out_b": (None,)})
        return axes

    def _pp_stages(self) -> int:
        """Pipeline stages from the ambient context (0/1 = no pipelining).
        Peeks the global context without creating one."""
        from .....common import nncontext as _nn
        ctx = _nn._global_context
        if ctx is None:
            return 1
        return int(ctx.mesh.shape.get("pipe", 1))

    def build(self, rng, input_shape):
        if self.moe_experts and self._moe is None:
            from .moe import SparseMoE
            self._moe = SparseMoE(self.moe_experts,
                                  self.intermediate_size,
                                  top_k=self.moe_top_k)
        rngs = jax.random.split(rng, self.n_block + 2)
        params = self._embedding_params(rngs[0])
        pp = self._pp_stages()
        if pp > 1:
            # GPipe layout: block params stacked on a leading 'stage'-
            # annotated axis so each pipe rank holds only its blocks
            # (parallel/pipeline.py schedule, reachable from Model.fit)
            if self.n_block % pp:
                raise ValueError(
                    f"pipeline_parallel={pp} must divide n_block="
                    f"{self.n_block}")
            if self.output_all_block:
                raise ValueError(
                    "output_all_block=True is incompatible with "
                    "pipeline_parallel > 1 (intermediate block states "
                    "live on other pipe ranks); build with "
                    "output_all_block=False")
            per_block = [self._block_params(rngs[i + 1])
                         for i in range(self.n_block)]
            params["blocks"] = jax.tree.map(
                lambda *ls: jnp.stack(ls), *per_block)
            self._annotate(**{
                f"blocks/{k}": ("stage",) + tuple(v)
                for k, v in self._block_axis_map().items()})
        else:
            for i in range(self.n_block):
                params[f"block{i}"] = self._block_params(rngs[i + 1])
                self._annotate(**{
                    f"block{i}/{k}": v
                    for k, v in self._block_axis_map().items()})
        params["pooler_w"] = _normal(rngs[-1],
                                     (self.hidden_size, self.hidden_size),
                                     self.initializer_range)
        params["pooler_b"] = jnp.zeros((self.hidden_size,))
        return params

    # -- compute -------------------------------------------------------
    def _ln(self, x, g, b, eps=1e-5):
        from .....ops.layernorm import layer_norm
        return layer_norm(x, g, b, eps)

    def _gelu(self, x):
        return jax.nn.gelu(x, approximate=self.gelu_approximate)

    def _seq_parallel(self) -> int:
        from .....common import nncontext as _nn
        ctx = _nn._global_context
        if ctx is None:
            return 1
        return int(ctx.mesh.shape.get("seq", 1))

    def _attention(self, p, x, mask_bias, rng, training):
        b, l, h = x.shape
        nh = self.n_head
        d = h // nh
        qkv = jnp.matmul(x, p["qkv_w"].astype(x.dtype)) + \
            p["qkv_b"].astype(x.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        sp = self._seq_parallel()
        if sp > 1 and l % sp == 0:
            # sequence parallelism over the 'seq' mesh axis: ulysses
            # (all-to-all head/seq swap, full-L local attention — the
            # flash kernel's favourite shape) when the head count splits
            # across the axis, else the ppermute ring with O(L/sp) score
            # memory (parallel/ulysses.py, parallel/ring_attention.py;
            # key-padding bias rides along either way)
            from .....common.nncontext import get_nncontext
            from .....parallel.ring_attention import \
                ring_attention_blhd_sharded
            from .....parallel.ulysses import \
                ulysses_attention_blhd_sharded

            mode = str(getattr(get_nncontext().config,
                               "sequence_parallel_mode", "auto")).lower()
            if mode not in ("auto", "ring", "ulysses"):
                raise ValueError(
                    f"sequence_parallel_mode must be auto|ring|ulysses, "
                    f"got {mode!r}")
            use_ulysses = (mode == "ulysses" or
                           (mode == "auto" and nh % sp == 0))
            kb = None
            if mask_bias is not None:
                kb = jnp.broadcast_to(
                    mask_bias.reshape(mask_bias.shape[0], l),
                    (b, l)).astype(jnp.float32)
            if use_ulysses:
                # blhd twin: all-to-alls swap the head/seq axes of the
                # projection's natural layout, so neither the collective
                # nor the kernel forces a relayout copy
                o = ulysses_attention_blhd_sharded(
                    q.reshape(b, l, nh, d), k.reshape(b, l, nh, d),
                    v.reshape(b, l, nh, d), get_nncontext().mesh,
                    causal=not self.bidirectional, kbias=kb)
            else:
                # blhd twin: the ring folds chunks in the projection's
                # native (B, L, H, d) layout, so neither entry nor exit
                # needs the [B,H,L,d] relayout transpose pair
                o = ring_attention_blhd_sharded(
                    q.reshape(b, l, nh, d), k.reshape(b, l, nh, d),
                    v.reshape(b, l, nh, d), get_nncontext().mesh,
                    causal=not self.bidirectional, kbias=kb)
        else:
            # blhd entry: the (B, L, H, d) reshape of the fused QKV
            # projection feeds the kernel directly — no [B,H,L,d]
            # relayout copies in, no transpose back out (ops/attention.py
            # blhd section; falls back to the transposed path when the
            # kernel is ineligible, where XLA folds the transposes into
            # its dots anyway)
            q4, k4, v4 = (t.reshape(b, l, nh, d) for t in (q, k, v))
            attn = functools.partial(flash_attention_blhd,
                                     causal=not self.bidirectional)
            dp = _dp_mesh(b)
            if dp is None:
                o = attn(q4, k4, v4, bias=mask_bias)
            else:
                from jax.sharding import PartitionSpec as P
                p4 = P("data", None, None, None)
                # check_vma=False: see _dp_dropout_add_ln
                operands = [q4, k4, v4]
                in_specs = [p4, p4, p4]
                if mask_bias is not None:
                    operands.append(mask_bias)
                    in_specs.append(
                        P("data", *([None] * (mask_bias.ndim - 1)))
                        if mask_bias.shape[0] == b else
                        P(*([None] * mask_bias.ndim)))

                def body(q_, k_, v_, bias_=None):
                    return attn(q_, k_, v_, bias=bias_)

                o = _shard_map(
                    body, mesh=dp, in_specs=tuple(in_specs),
                    out_specs=p4, check_vma=False)(*operands)
        o = o.reshape(b, l, h)
        if rng is not None:
            rng, sub = jax.random.split(rng)
            o = _dropout(o, self.attn_p_drop, sub, training)
        o = jnp.matmul(o, p["proj_w"].astype(x.dtype)) + \
            p["proj_b"].astype(x.dtype)
        return o

    def _block(self, p, x, mask_bias, rng, training):
        # both residual sites run the fused dropout+add+LN op: one
        # bandwidth pass on the TPU kernel path (ops/fused_dropout_ln.py
        # — the composed XLA fusions measured ~4x off ideal, 17.6 ms of
        # the BERT-base step, r5 session 3), the exact pre-existing
        # bernoulli+layer_norm composition everywhere else
        r1 = r2 = r3 = None
        if rng is not None:
            r1, r2, r3 = jax.random.split(rng, 3)
        a = self._attention(p, x, mask_bias, r1, training)
        n = _dp_dropout_add_ln(a, x, p["ln1_g"], p["ln1_b"], r2,
                               self.hidden_p_drop, training)
        m = self._ffn(p, n, training)
        return _dp_dropout_add_ln(m, n, p["ln2_g"], p["ln2_b"], r3,
                                  self.hidden_p_drop, training)

    def _ffn(self, p, n, training):
        if self.moe_experts:
            return self._moe.call(p["moe"], n, training=training)
        m = jnp.matmul(n, p["mlp_in_w"].astype(n.dtype)) + \
            p["mlp_in_b"].astype(n.dtype)
        m = self._gelu(m)
        return jnp.matmul(m, p["mlp_out_w"].astype(n.dtype)) + \
            p["mlp_out_b"].astype(n.dtype)

    def _embed(self, params, inputs, rng, training):
        if self.embedding_layer is not None:
            x = inputs if not isinstance(inputs, (list, tuple)) or \
                len(inputs) > 1 else inputs[0]
            e = self.embedding_layer.call(params["embedding"], x,
                                          training=training)
            return e, None
        tokens = (inputs[0] if isinstance(inputs, (list, tuple))
                  else inputs).astype(jnp.int32)
        e = jnp.take(params["tok_emb"], tokens, axis=0)
        e = e + params["pos_emb"][None, :e.shape[1]]
        return e, None

    def _pooler(self, params, x):
        first = x[:, 0]
        return jnp.tanh(jnp.matmul(first, params["pooler_w"]
                                   .astype(x.dtype)) +
                        params["pooler_b"].astype(x.dtype))

    def _call_pp(self, params, e, mask_bias, rng, training):
        """Run the block trunk as a GPipe pipeline over the 'pipe' mesh
        axis (parallel/pipeline.py): the stacked block params are already
        sharded one stage per rank; activations + mask + dropout seed
        rotate along the ring as one pytree."""
        from .....common.nncontext import get_nncontext
        from .....parallel.pipeline import pipeline_forward

        ctx = get_nncontext()
        mesh = ctx.mesh
        S = int(mesh.shape["pipe"])
        bps = self.n_block // S
        n_micro = int(getattr(ctx.config, "pipeline_microbatches", 0)) or S
        b = e.shape[0]
        tree = {"x": e}
        if mask_bias is not None:
            tree["mask"] = jnp.broadcast_to(
                mask_bias, (b,) + tuple(mask_bias.shape[1:]))
        if rng is not None:
            seed = jax.random.randint(rng, (), 0, np.iinfo(np.int32).max)
            tree["seed"] = jnp.broadcast_to(seed, (b,))

        blocks = jax.tree.map(
            lambda l: l.reshape((S, bps) + l.shape[1:]), params["blocks"])

        def stage(p_local, t):
            x = t["x"]
            mask = t.get("mask")
            key = None
            if "seed" in t:
                key = jax.random.fold_in(
                    jax.random.PRNGKey(0), t["seed"][0])
                key = jax.random.fold_in(
                    key, jax.lax.axis_index("pipe"))

            def body(x, p_i):
                bp, i = p_i
                brng = (jax.random.fold_in(key, i)
                        if key is not None else None)
                return self._block(bp, x, mask, brng, training), None

            x, _ = jax.lax.scan(body, x, (p_local, jnp.arange(bps)))
            return dict(t, x=x)

        out = pipeline_forward(stage, blocks, tree, mesh,
                               n_microbatch=n_micro)
        return out["x"]

    # -- KV-cache incremental decode (ops/kv_cache.py) -----------------
    #
    # The generative-serving path: prefill runs the prompt once through
    # the standard causal flash/blockwise route and stashes every
    # block's projected K/V into preallocated slabs; decode_step then
    # advances one token per call with O(S) cached attention — the
    # step's jaxpr has no (L, L) contraction (bench generate gate).
    # Decode is inference-only: no dropout, per-block param layout
    # (pipeline_parallel stacking is a training layout).

    def _require_decode_layout(self, params):
        if self.bidirectional:
            raise ValueError(
                "KV-cache decode needs a causal trunk; this layer was "
                "built bidirectional (BERT-style)")
        if "blocks" in params:
            raise ValueError(
                "KV-cache decode does not support the pipeline-parallel "
                "stacked-block layout; rebuild with pipeline_parallel=1")

    def init_decode_state(self, batch, capacity, dtype=jnp.float32,
                          rng=None):
        """Preallocate (B, S, H, D) K/V slabs for every block.
        ``dtype="int8"`` allocates quantized ``Int8KVSlab`` slabs — the
        cache ops dequantize inside the attention einsums, so prefill /
        decode_step / decode_chunk below run unchanged."""
        from .....ops.kv_cache import init_decode_state
        return init_decode_state(
            self.n_block, batch, capacity, self.n_head,
            self.hidden_size // self.n_head, dtype=dtype, rng=rng)

    def lm_logits(self, params, x):
        """Token logits via embedding weight tying: x @ tok_emb^T."""
        if self.embedding_layer is not None:
            raise ValueError("lm_logits needs the built-in token "
                             "embedding (weight tying)")
        return jnp.matmul(x, params["tok_emb"].T.astype(x.dtype))

    def prefill(self, params, tokens, lengths, state):
        """Fill the cache from padded prompts; return last-token logits.

        tokens: (B, Lp) left-aligned prompt ids padded to a shared Lp;
        lengths: (B,) int32 true prompt lengths (the ragged tail is
        masked with a key bias). Returns (logits (B, vocab), state).
        """
        from .....ops.kv_cache import write_prompt
        self._require_decode_layout(params)
        tokens = tokens.astype(jnp.int32)
        b, lp = tokens.shape
        nh = self.n_head
        d = self.hidden_size // nh
        x = jnp.take(params["tok_emb"], tokens, axis=0)
        x = x + params["pos_emb"][None, :lp]
        # additive key bias over the padded tail, rides the flash route
        # exactly like BERT's attention_mask bias
        kb = jnp.where(jnp.arange(lp)[None, :] < lengths[:, None],
                       0.0, -1e9).astype(jnp.float32)
        kb = kb[:, None, None, :]
        k_caches, v_caches = [], []
        for i in range(self.n_block):
            p = params[f"block{i}"]
            qkv = jnp.matmul(x, p["qkv_w"].astype(x.dtype)) + \
                p["qkv_b"].astype(x.dtype)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q4, k4, v4 = (t.reshape(b, lp, nh, d) for t in (q, k, v))
            o = flash_attention_blhd(q4, k4, v4, bias=kb, causal=True)
            k_caches.append(write_prompt(state.k_cache[i], k4))
            v_caches.append(write_prompt(state.v_cache[i], v4))
            a = jnp.matmul(o.reshape(b, lp, self.hidden_size),
                           p["proj_w"].astype(x.dtype)) + \
                p["proj_b"].astype(x.dtype)
            n = _dp_dropout_add_ln(a, x, p["ln1_g"], p["ln1_b"], None,
                                   0.0, False)
            m = self._ffn(p, n, False)
            x = _dp_dropout_add_ln(m, n, p["ln2_g"], p["ln2_b"], None,
                                   0.0, False)
        last = jnp.take_along_axis(
            x, jnp.maximum(lengths - 1, 0)[:, None, None].astype(
                jnp.int32), axis=1)[:, 0]
        state = state._replace(k_cache=tuple(k_caches),
                               v_cache=tuple(v_caches),
                               lengths=lengths.astype(jnp.int32))
        return self.lm_logits(params, last), state

    def decode_step(self, params, state, tokens):
        """Advance every slot one token: (B,) ids -> ((B, vocab), state).

        Appends each slot's K/V row at its own write offset and attends
        the single query row against the slab — O(S) per token, no
        full-sequence recompute.
        """
        from .....ops.kv_cache import cached_attention_step
        self._require_decode_layout(params)
        nh = self.n_head
        d = self.hidden_size // nh
        b = state.lengths.shape[0]
        pos = jnp.minimum(state.lengths, self.seq_len - 1)
        x = jnp.take(params["tok_emb"], tokens.astype(jnp.int32),
                     axis=0)[:, None]
        x = x + jnp.take(params["pos_emb"], pos, axis=0)[:, None]
        k_caches, v_caches = [], []
        for i in range(self.n_block):
            p = params[f"block{i}"]
            qkv = jnp.matmul(x, p["qkv_w"].astype(x.dtype)) + \
                p["qkv_b"].astype(x.dtype)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            o, kc, vc, _ = cached_attention_step(
                q.reshape(b, 1, nh, d), k.reshape(b, 1, nh, d),
                v.reshape(b, 1, nh, d), state.k_cache[i],
                state.v_cache[i], state.lengths)
            k_caches.append(kc)
            v_caches.append(vc)
            a = jnp.matmul(o.reshape(b, 1, self.hidden_size),
                           p["proj_w"].astype(x.dtype)) + \
                p["proj_b"].astype(x.dtype)
            n = _dp_dropout_add_ln(a, x, p["ln1_g"], p["ln1_b"], None,
                                   0.0, False)
            m = self._ffn(p, n, False)
            x = _dp_dropout_add_ln(m, n, p["ln2_g"], p["ln2_b"], None,
                                   0.0, False)
        state = state._replace(k_cache=tuple(k_caches),
                               v_cache=tuple(v_caches),
                               lengths=state.lengths + 1)
        return self.lm_logits(params, x[:, 0]), state

    def decode_chunk(self, params, state, tokens, n_valid=None):
        """Advance every slot C tokens in ONE rectangular attention step:
        (B, C) ids -> ((B, C, vocab), state).

        The two decode fast paths share this call. Chunked prefill feeds
        prompt slices (C = chunk size; ``n_valid`` (B,) masks a ragged
        final chunk — lengths advance by n_valid and the tail rows land
        above the watermark, never attended, overwritten by the next
        write). Speculative verification feeds [last, draft_1..draft_k]
        (C = k + 1): row i's logits score draft i+1, row k is the bonus
        token, and rejected suffixes roll back by plain ``lengths``
        surgery since their rows also sit above the new watermark.

        Row c embeds at position ``lengths + c`` and attends slab keys
        ``<= lengths + c`` (``cached_attention_chunk``) — the jaxpr still
        carries no (S, S) contraction, so the cached-decode bench gate
        holds for any C < S.
        """
        from .....ops.kv_cache import cached_attention_chunk
        self._require_decode_layout(params)
        nh = self.n_head
        d = self.hidden_size // nh
        b, c = tokens.shape
        pos = jnp.minimum(
            state.lengths[:, None] + jnp.arange(c)[None, :],
            self.seq_len - 1)
        x = jnp.take(params["tok_emb"], tokens.astype(jnp.int32), axis=0)
        x = x + jnp.take(params["pos_emb"], pos, axis=0)
        k_caches, v_caches = [], []
        new_lengths = state.lengths
        for i in range(self.n_block):
            p = params[f"block{i}"]
            qkv = jnp.matmul(x, p["qkv_w"].astype(x.dtype)) + \
                p["qkv_b"].astype(x.dtype)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            o, kc, vc, new_lengths = cached_attention_chunk(
                q.reshape(b, c, nh, d), k.reshape(b, c, nh, d),
                v.reshape(b, c, nh, d), state.k_cache[i],
                state.v_cache[i], state.lengths, n_valid=n_valid)
            k_caches.append(kc)
            v_caches.append(vc)
            a = jnp.matmul(o.reshape(b, c, self.hidden_size),
                           p["proj_w"].astype(x.dtype)) + \
                p["proj_b"].astype(x.dtype)
            n = _dp_dropout_add_ln(a, x, p["ln1_g"], p["ln1_b"], None,
                                   0.0, False)
            m = self._ffn(p, n, False)
            x = _dp_dropout_add_ln(m, n, p["ln2_g"], p["ln2_b"], None,
                                   0.0, False)
        state = state._replace(k_cache=tuple(k_caches),
                               v_cache=tuple(v_caches),
                               lengths=new_lengths)
        return self.lm_logits(params, x), state

    def call(self, params, inputs, training=False, rng=None, **kw):
        e, mask_bias = self._embed(params, inputs, rng, training)
        if rng is not None:
            rng, sub = jax.random.split(rng)
            e = _dropout(e, self.hidden_p_drop, sub, training)
        if "blocks" in params:         # GPipe layout (pipeline_parallel>1)
            x = self._call_pp(params, e, mask_bias, rng, training)
            return (x, self._pooler(params, x))
        states = []
        x = e
        for i in range(self.n_block):
            block_rng = None
            if rng is not None:
                rng, block_rng = jax.random.split(rng)
            x = self._block(params[f"block{i}"], x, mask_bias, block_rng,
                            training)
            states.append(x)
        pooled = self._pooler(params, x)
        if self.output_all_block:
            return tuple(states) + (pooled,)
        return (x, pooled)

    def compute_output_shape(self, input_shape):
        first = input_shape[0] if isinstance(input_shape, list) \
            else input_shape
        seq_shape = (first[0], first[1], self.hidden_size)
        pooled = (first[0], self.hidden_size)
        if self.output_all_block:
            return [seq_shape] * self.n_block + [pooled]
        return [seq_shape, pooled]


class BERT(TransformerLayer):
    """BERT encoder (BERT.scala). Inputs: ``[token_ids (B,L),
    position_ids (B,L), segment_ids (B,L), attention_mask (B,1,1,L)]``."""

    gelu_approximate = False  # BERT.scala overrides gelu with the erf form

    def __init__(self, vocab=40990, hidden_size=768, n_block=12, n_head=12,
                 seq_len=512, intermediate_size=3072, hidden_p_drop=0.1,
                 attn_p_drop=0.1, initializer_range=0.02,
                 output_all_block=True, moe_experts=0, moe_top_k=2,
                 input_shape=None, name=None, **kwargs):
        super().__init__(
            n_block=n_block, hidden_p_drop=hidden_p_drop,
            attn_p_drop=attn_p_drop, n_head=n_head,
            initializer_range=initializer_range, bidirectional=True,
            output_all_block=output_all_block,
            intermediate_size=intermediate_size, vocab=vocab,
            seq_len=seq_len, hidden_size=hidden_size,
            moe_experts=moe_experts, moe_top_k=moe_top_k,
            input_shape=input_shape, name=name)

    def _embedding_params(self, rng):
        params = super()._embedding_params(rng)
        r = jax.random.fold_in(rng, 7)
        params["seg_emb"] = _normal(r, (2, self.hidden_size),
                                    self.initializer_range)
        params["emb_ln_g"] = jnp.ones((self.hidden_size,))
        params["emb_ln_b"] = jnp.zeros((self.hidden_size,))
        return params

    def _embed(self, params, inputs, rng, training):
        tokens, positions, segments, mask = inputs
        tokens = tokens.astype(jnp.int32)
        positions = positions.astype(jnp.int32)
        segments = segments.astype(jnp.int32)
        e = jnp.take(params["tok_emb"], tokens, axis=0)
        e = e + jnp.take(params["pos_emb"], positions, axis=0)
        e = e + jnp.take(params["seg_emb"], segments, axis=0)
        e = self._ln(e, params["emb_ln_g"], params["emb_ln_b"], eps=1e-12)
        # extended mask, parity with BERT.scala buildInput:
        # (-mask + 1) * -10000
        mask_bias = (1.0 - mask.astype(jnp.float32)) * -10000.0
        return e, mask_bias

    def compute_output_shape(self, input_shape):
        first = input_shape[0]
        seq_shape = (first[0], first[1], self.hidden_size)
        pooled = (first[0], self.hidden_size)
        if self.output_all_block:
            return [seq_shape] * self.n_block + [pooled]
        return [seq_shape, pooled]
