from ..engine.base import Input, InputLayer, KerasLayer
from .core import (AddConstant, Activation, BinaryThreshold, CAdd, CMul,
                   Dense, Dropout, Exp, ExpandDim, Flatten, GaussianDropout,
                   GaussianNoise, GaussianSampler, HardShrink, HardTanh,
                   Highway, Identity, Log, Masking, Max, MaxoutDense, Mul,
                   MulConstant, Narrow, Negative, Permute, Power,
                   RepeatVector, Reshape, ResizeBilinear, Scale, Select,
                   SoftShrink, SpatialDropout1D, SpatialDropout2D,
                   SpatialDropout3D, SplitTensor, Sqrt, Square, Squeeze,
                   Threshold)
from .embeddings import Embedding, SparseEmbedding, WordEmbedding
from .merge import (Add, Average, Concatenate, Maximum, Merge, Multiply,
                    merge)
from .normalization import (BatchNormalization, LayerNorm, LRN2D,
                            WithinChannelLRN2D)
