from ..engine.base import Input, InputLayer, KerasLayer
from .core import (AddConstant, Activation, BinaryThreshold, CAdd, CMul,
                   Dense, Dropout, Exp, ExpandDim, Flatten, GaussianDropout,
                   GaussianNoise, GaussianSampler, HardShrink, HardTanh,
                   Highway, Identity, Log, Masking, Max, MaxoutDense, Mul,
                   MulConstant, Narrow, Negative, Permute, Power,
                   RepeatVector, Reshape, ResizeBilinear, Scale, Select,
                   SoftShrink, SpatialDropout1D, SpatialDropout2D,
                   SpatialDropout3D, SplitTensor, Sqrt, Square, Squeeze,
                   Threshold, Expand, GetShape, SelectTable, SparseDense)
from .embeddings import Embedding, SparseEmbedding, WordEmbedding
from .merge import (Add, Average, Concatenate, Maximum, Merge, Multiply,
                    merge)
from .normalization import (BatchNormalization, LayerNorm, LRN2D,
                            WithinChannelLRN2D)
from .convolutional import (AtrousConvolution1D, AtrousConvolution2D,
                            Convolution1D, Convolution2D, Convolution3D,
                            Cropping1D, Cropping2D, Cropping3D,
                            Deconvolution2D, LocallyConnected1D,
                            LocallyConnected2D, SeparableConvolution2D,
                            ShareConvolution2D, UpSampling1D, UpSampling2D,
                            UpSampling3D, ZeroPadding1D, ZeroPadding2D,
                            ZeroPadding3D)
from .pooling import (AveragePooling1D, AveragePooling2D, AveragePooling3D,
                      GlobalAveragePooling1D, GlobalAveragePooling2D,
                      GlobalAveragePooling3D, GlobalMaxPooling1D,
                      GlobalMaxPooling2D, GlobalMaxPooling3D, MaxPooling1D,
                      MaxPooling2D, MaxPooling3D)
from .recurrent import (GRU, LSTM, ConvLSTM2D, ConvLSTM3D, SimpleRNN)
from .wrappers import Bidirectional, KerasLayerWrapper, TimeDistributed
from .advanced_activations import (ELU, LeakyReLU, PReLU, RReLU, Softmax,
                                   SReLU, ThresholdedReLU)
from .moe import SparseMoE
from .crf import CRF

# Convenience aliases matching Keras-2-style names used around the reference
Conv1D = Convolution1D
Conv2D = Convolution2D
Conv3D = Convolution3D
