"""Convolution layers.

Parity surface: Convolution1D/2D/3D, AtrousConvolution1D/2D, Deconvolution2D,
SeparableConvolution2D, ShareConvolution2D, LocallyConnected1D/2D,
Cropping1/2/3D, UpSampling1/2/3D, ZeroPadding1/2/3D (keras/layers/*.scala).

TPU design: every conv lowers to ``lax.conv_general_dilated`` with explicit
``dimension_numbers`` — no host-side layout transposes; XLA picks the MXU
tiling. Default dim_ordering is "th" (NCHW) for API parity with the
reference's BigDL backend, but kernels are stored HWIO so "tf" mode shares
code.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.base import KerasLayer, get_activation_fn, init_tensor


def _pair(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v)


def _conv_out(length, k, stride, border_mode, dilation=1):
    if length is None:
        return None
    keff = (k - 1) * dilation + 1
    if border_mode == "same":
        return (length + stride - 1) // stride
    return (length - keff) // stride + 1


class Convolution2D(KerasLayer):
    def __init__(self, nb_filter, nb_row, nb_col, init="glorot_uniform",
                 activation=None, border_mode="valid", subsample=(1, 1),
                 dim_ordering="th", W_regularizer=None, b_regularizer=None,
                 bias=True, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.nb_filter = int(nb_filter)
        self.kernel_size = (int(nb_row), int(nb_col))
        self.init = init
        self.activation = get_activation_fn(activation)
        self.border_mode = border_mode
        self.subsample = _pair(subsample)
        self.dim_ordering = dim_ordering
        self.bias = bias
        self.dilation = (1, 1)

    def _in_channels(self, input_shape):
        return int(input_shape[1] if self.dim_ordering == "th"
                   else input_shape[3])

    def build(self, rng, input_shape):
        cin = self._in_channels(input_shape)
        kh, kw = self.kernel_size
        params = {"kernel": init_tensor(
            rng, (kh, kw, cin, self.nb_filter), self.init)}
        self._annotate(kernel=(None, None, "in", "out"))
        if self.bias:
            params["bias"] = jnp.zeros((self.nb_filter,))
        return params

    def _dn(self):
        return ("NCHW", "HWIO", "NCHW") if self.dim_ordering == "th" \
            else ("NHWC", "HWIO", "NHWC")

    def call(self, params, x, training=False, **kw):
        pad = "SAME" if self.border_mode == "same" else "VALID"
        # quant.conv2d owns the whole epilogue: float kernels reproduce
        # conv + bias + activation verbatim; calibrated int8 kernels
        # fold bias into the int32 accumulator and may emit int8 for
        # the next requantization-chain link
        from .....ops import quant
        return quant.conv2d(x, params["kernel"], self.subsample, pad,
                            rhs_dilation=self.dilation,
                            dimension_numbers=self._dn(),
                            bias=params["bias"] if self.bias else None,
                            activation=self.activation)

    def compute_output_shape(self, s):
        kh, kw = self.kernel_size
        sh, sw = self.subsample
        dh, dw = self.dilation
        if self.dim_ordering == "th":
            return (s[0], self.nb_filter,
                    _conv_out(s[2], kh, sh, self.border_mode, dh),
                    _conv_out(s[3], kw, sw, self.border_mode, dw))
        return (s[0], _conv_out(s[1], kh, sh, self.border_mode, dh),
                _conv_out(s[2], kw, sw, self.border_mode, dw), self.nb_filter)


class AtrousConvolution2D(Convolution2D):
    def __init__(self, nb_filter, nb_row, nb_col, atrous_rate=(1, 1),
                 **kwargs):
        super().__init__(nb_filter, nb_row, nb_col, **kwargs)
        self.dilation = _pair(atrous_rate)


class Convolution1D(KerasLayer):
    """Conv over (batch, steps, dim) — Keras-1 layout regardless of
    dim_ordering (Convolution1D.scala)."""

    def __init__(self, nb_filter, filter_length, init="glorot_uniform",
                 activation=None, border_mode="valid", subsample_length=1,
                 W_regularizer=None, b_regularizer=None, bias=True,
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.nb_filter = int(nb_filter)
        self.filter_length = int(filter_length)
        self.init = init
        self.activation = get_activation_fn(activation)
        self.border_mode = border_mode
        self.subsample = int(subsample_length)
        self.bias = bias
        self.dilation = 1

    def build(self, rng, input_shape):
        cin = int(input_shape[-1])
        params = {"kernel": init_tensor(
            rng, (self.filter_length, cin, self.nb_filter), self.init)}
        self._annotate(kernel=(None, "in", "out"))
        if self.bias:
            params["bias"] = jnp.zeros((self.nb_filter,))
        return params

    def call(self, params, x, training=False, **kw):
        pad = "SAME" if self.border_mode == "same" else "VALID"
        y = jax.lax.conv_general_dilated(
            x, params["kernel"].astype(x.dtype), (self.subsample,), pad,
            rhs_dilation=(self.dilation,),
            dimension_numbers=("NWC", "WIO", "NWC"))
        if self.bias:
            y = y + params["bias"].astype(x.dtype)
        return self.activation(y) if self.activation else y

    def compute_output_shape(self, s):
        return (s[0], _conv_out(s[1], self.filter_length, self.subsample,
                                self.border_mode, self.dilation),
                self.nb_filter)


class AtrousConvolution1D(Convolution1D):
    def __init__(self, nb_filter, filter_length, atrous_rate=1, **kwargs):
        super().__init__(nb_filter, filter_length, **kwargs)
        self.dilation = int(atrous_rate)


class Convolution3D(KerasLayer):
    def __init__(self, nb_filter, kernel_dim1, kernel_dim2, kernel_dim3,
                 init="glorot_uniform", activation=None, border_mode="valid",
                 subsample=(1, 1, 1), dim_ordering="th", W_regularizer=None,
                 b_regularizer=None, bias=True, input_shape=None, name=None,
                 **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.nb_filter = int(nb_filter)
        self.kernel_size = (int(kernel_dim1), int(kernel_dim2),
                            int(kernel_dim3))
        self.init = init
        self.activation = get_activation_fn(activation)
        self.border_mode = border_mode
        self.subsample = tuple(subsample)
        self.dim_ordering = dim_ordering
        self.bias = bias

    def build(self, rng, input_shape):
        cin = int(input_shape[1] if self.dim_ordering == "th"
                  else input_shape[4])
        params = {"kernel": init_tensor(
            rng, self.kernel_size + (cin, self.nb_filter), self.init)}
        if self.bias:
            params["bias"] = jnp.zeros((self.nb_filter,))
        return params

    def call(self, params, x, training=False, **kw):
        pad = "SAME" if self.border_mode == "same" else "VALID"
        dn = ("NCDHW", "DHWIO", "NCDHW") if self.dim_ordering == "th" \
            else ("NDHWC", "DHWIO", "NDHWC")
        y = jax.lax.conv_general_dilated(
            x, params["kernel"].astype(x.dtype), self.subsample, pad,
            dimension_numbers=dn)
        if self.bias:
            b = params["bias"].astype(x.dtype)
            y = y + (b[None, :, None, None, None]
                     if self.dim_ordering == "th" else b)
        return self.activation(y) if self.activation else y

    def compute_output_shape(self, s):
        ks, ss = self.kernel_size, self.subsample
        if self.dim_ordering == "th":
            dims = tuple(_conv_out(s[2 + i], ks[i], ss[i], self.border_mode)
                         for i in range(3))
            return (s[0], self.nb_filter) + dims
        dims = tuple(_conv_out(s[1 + i], ks[i], ss[i], self.border_mode)
                     for i in range(3))
        return (s[0],) + dims + (self.nb_filter,)


class Deconvolution2D(KerasLayer):
    """Transposed conv (Deconvolution2D.scala); 'th' ordering only in the
    reference."""

    def __init__(self, nb_filter, nb_row, nb_col, output_shape=None,
                 init="glorot_uniform", activation=None, border_mode="valid",
                 subsample=(1, 1), dim_ordering="th", W_regularizer=None,
                 b_regularizer=None, bias=True, input_shape=None, name=None,
                 **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.nb_filter = int(nb_filter)
        self.kernel_size = (int(nb_row), int(nb_col))
        self.init = init
        self.activation = get_activation_fn(activation)
        self.border_mode = border_mode
        self.subsample = _pair(subsample)
        self.dim_ordering = dim_ordering
        self.bias = bias

    def build(self, rng, input_shape):
        cin = int(input_shape[1] if self.dim_ordering == "th"
                  else input_shape[3])
        kh, kw = self.kernel_size
        # conv_transpose with HWIO: (kh, kw, out, in) via transpose_kernel
        params = {"kernel": init_tensor(
            rng, (kh, kw, self.nb_filter, cin), self.init)}
        if self.bias:
            params["bias"] = jnp.zeros((self.nb_filter,))
        return params

    def call(self, params, x, training=False, **kw):
        pad = "SAME" if self.border_mode == "same" else "VALID"
        dn = ("NCHW", "HWIO", "NCHW") if self.dim_ordering == "th" \
            else ("NHWC", "HWIO", "NHWC")
        y = jax.lax.conv_transpose(
            x, params["kernel"].astype(x.dtype), self.subsample, pad,
            dimension_numbers=dn, transpose_kernel=True)
        if self.bias:
            b = params["bias"].astype(x.dtype)
            y = y + (b[None, :, None, None] if self.dim_ordering == "th"
                     else b)
        return self.activation(y) if self.activation else y

    def compute_output_shape(self, s):
        kh, kw = self.kernel_size
        sh, sw = self.subsample

        def out(l, k, st):
            if l is None:
                return None
            if self.border_mode == "same":
                return l * st
            return (l - 1) * st + k

        if self.dim_ordering == "th":
            return (s[0], self.nb_filter, out(s[2], kh, sh), out(s[3], kw, sw))
        return (s[0], out(s[1], kh, sh), out(s[2], kw, sw), self.nb_filter)


class SeparableConvolution2D(KerasLayer):
    def __init__(self, nb_filter, nb_row, nb_col, init="glorot_uniform",
                 activation=None, border_mode="valid", subsample=(1, 1),
                 depth_multiplier=1, dim_ordering="th", bias=True,
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.nb_filter = int(nb_filter)
        self.kernel_size = (int(nb_row), int(nb_col))
        self.init = init
        self.activation = get_activation_fn(activation)
        self.border_mode = border_mode
        self.subsample = _pair(subsample)
        self.depth_multiplier = int(depth_multiplier)
        self.dim_ordering = dim_ordering
        self.bias = bias

    def build(self, rng, input_shape):
        cin = int(input_shape[1] if self.dim_ordering == "th"
                  else input_shape[3])
        kh, kw = self.kernel_size
        r1, r2 = jax.random.split(rng)
        params = {
            "depthwise": init_tensor(
                r1, (kh, kw, 1, cin * self.depth_multiplier), self.init),
            "pointwise": init_tensor(
                r2, (1, 1, cin * self.depth_multiplier, self.nb_filter),
                self.init)}
        if self.bias:
            params["bias"] = jnp.zeros((self.nb_filter,))
        return params

    def call(self, params, x, training=False, **kw):
        pad = "SAME" if self.border_mode == "same" else "VALID"
        dn = ("NCHW", "HWIO", "NCHW") if self.dim_ordering == "th" \
            else ("NHWC", "HWIO", "NHWC")
        cin = x.shape[1] if self.dim_ordering == "th" else x.shape[3]
        y = jax.lax.conv_general_dilated(
            x, params["depthwise"].astype(x.dtype), self.subsample, pad,
            dimension_numbers=dn, feature_group_count=cin)
        y = jax.lax.conv_general_dilated(
            y, params["pointwise"].astype(x.dtype), (1, 1), "VALID",
            dimension_numbers=dn)
        if self.bias:
            b = params["bias"].astype(x.dtype)
            y = y + (b[None, :, None, None] if self.dim_ordering == "th"
                     else b)
        return self.activation(y) if self.activation else y

    def compute_output_shape(self, s):
        kh, kw = self.kernel_size
        sh, sw = self.subsample
        if self.dim_ordering == "th":
            return (s[0], self.nb_filter,
                    _conv_out(s[2], kh, sh, self.border_mode),
                    _conv_out(s[3], kw, sw, self.border_mode))
        return (s[0], _conv_out(s[1], kh, sh, self.border_mode),
                _conv_out(s[2], kw, sw, self.border_mode), self.nb_filter)


class ShareConvolution2D(Convolution2D):
    """Reference ShareConvolution2D shares gradient buffers across time — a
    JVM memory optimization with identical math; alias of Convolution2D."""


class LocallyConnected2D(KerasLayer):
    """Unshared conv (LocallyConnected2D.scala): per-position kernels via
    patch extraction + einsum (MXU-friendly batched matmul)."""

    def __init__(self, nb_filter, nb_row, nb_col, activation=None,
                 border_mode="valid", subsample=(1, 1), dim_ordering="th",
                 bias=True, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.nb_filter = int(nb_filter)
        self.kernel_size = (int(nb_row), int(nb_col))
        self.activation = get_activation_fn(activation)
        self.border_mode = border_mode
        self.subsample = _pair(subsample)
        self.dim_ordering = dim_ordering
        self.bias = bias

    def _out_hw(self, input_shape):
        kh, kw = self.kernel_size
        sh, sw = self.subsample
        if self.dim_ordering == "th":
            h, w = input_shape[2], input_shape[3]
        else:
            h, w = input_shape[1], input_shape[2]
        return (_conv_out(h, kh, sh, self.border_mode),
                _conv_out(w, kw, sw, self.border_mode))

    def build(self, rng, input_shape):
        cin = int(input_shape[1] if self.dim_ordering == "th"
                  else input_shape[3])
        kh, kw = self.kernel_size
        oh, ow = self._out_hw(input_shape)
        params = {"kernel": init_tensor(
            rng, (oh * ow, kh * kw * cin, self.nb_filter), "glorot_uniform")}
        if self.bias:
            params["bias"] = jnp.zeros((oh, ow, self.nb_filter))
        return params

    def call(self, params, x, training=False, **kw):
        if self.dim_ordering != "th":
            x = jnp.transpose(x, (0, 3, 1, 2))
        pad = "SAME" if self.border_mode == "same" else "VALID"
        patches = jax.lax.conv_general_dilated_patches(
            x, self.kernel_size, self.subsample, pad)  # (B, C*kh*kw, OH, OW)
        b, ck, oh, ow = patches.shape
        patches = patches.reshape(b, ck, oh * ow).transpose(2, 0, 1)
        y = jnp.einsum("pbc,pcf->pbf", patches,
                       params["kernel"].astype(x.dtype))
        y = y.transpose(1, 2, 0).reshape(b, self.nb_filter, oh, ow)
        if self.bias:
            y = y + params["bias"].astype(x.dtype).transpose(2, 0, 1)[None]
        if self.dim_ordering != "th":
            y = jnp.transpose(y, (0, 2, 3, 1))
        return self.activation(y) if self.activation else y

    def compute_output_shape(self, s):
        oh, ow = self._out_hw(s)
        if self.dim_ordering == "th":
            return (s[0], self.nb_filter, oh, ow)
        return (s[0], oh, ow, self.nb_filter)


class LocallyConnected1D(KerasLayer):
    def __init__(self, nb_filter, filter_length, activation=None,
                 border_mode="valid", subsample_length=1, bias=True,
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.nb_filter = int(nb_filter)
        self.filter_length = int(filter_length)
        self.activation = get_activation_fn(activation)
        self.border_mode = border_mode
        self.subsample = int(subsample_length)
        self.bias = bias

    def build(self, rng, input_shape):
        cin = int(input_shape[-1])
        ol = _conv_out(input_shape[1], self.filter_length, self.subsample,
                       self.border_mode)
        params = {"kernel": init_tensor(
            rng, (ol, self.filter_length * cin, self.nb_filter))}
        if self.bias:
            params["bias"] = jnp.zeros((ol, self.nb_filter))
        return params

    def call(self, params, x, training=False, **kw):
        # x: (B, L, C) -> patches (B, C*k, OL)
        pad = "SAME" if self.border_mode == "same" else "VALID"
        patches = jax.lax.conv_general_dilated_patches(
            jnp.transpose(x, (0, 2, 1))[:, :, None, :],
            (1, self.filter_length), (1, self.subsample), pad)
        b, ck, _, ol = patches.shape
        patches = patches.reshape(b, ck, ol).transpose(2, 0, 1)
        y = jnp.einsum("pbc,pcf->pbf", patches,
                       params["kernel"].astype(x.dtype)).transpose(1, 0, 2)
        if self.bias:
            y = y + params["bias"].astype(x.dtype)
        return self.activation(y) if self.activation else y

    def compute_output_shape(self, s):
        ol = _conv_out(s[1], self.filter_length, self.subsample,
                       self.border_mode)
        return (s[0], ol, self.nb_filter)


# ---------------------------------------------------------------------------
# Shape-manipulation conv companions
# ---------------------------------------------------------------------------

class Cropping1D(KerasLayer):
    def __init__(self, cropping=(1, 1), input_shape=None, name=None, **kw):
        super().__init__(input_shape=input_shape, name=name)
        self.cropping = tuple(cropping)

    def call(self, params, x, training=False, **kw):
        a, b = self.cropping
        return x[:, a:x.shape[1] - b if b else x.shape[1]]

    def compute_output_shape(self, s):
        return (s[0], None if s[1] is None else s[1] - sum(self.cropping),
                s[2])


class Cropping2D(KerasLayer):
    def __init__(self, cropping=((0, 0), (0, 0)), dim_ordering="th",
                 input_shape=None, name=None, **kw):
        super().__init__(input_shape=input_shape, name=name)
        self.cropping = tuple(tuple(c) for c in cropping)
        self.dim_ordering = dim_ordering

    def call(self, params, x, training=False, **kw):
        (t, b), (l, r) = self.cropping
        if self.dim_ordering == "th":
            return x[:, :, t:x.shape[2] - b if b else x.shape[2],
                     l:x.shape[3] - r if r else x.shape[3]]
        return x[:, t:x.shape[1] - b if b else x.shape[1],
                 l:x.shape[2] - r if r else x.shape[2], :]

    def compute_output_shape(self, s):
        (t, b), (l, r) = self.cropping

        def crop(d, c):
            return None if d is None else d - c

        if self.dim_ordering == "th":
            return (s[0], s[1], crop(s[2], t + b), crop(s[3], l + r))
        return (s[0], crop(s[1], t + b), crop(s[2], l + r), s[3])


class Cropping3D(KerasLayer):
    def __init__(self, cropping=((1, 1), (1, 1), (1, 1)), dim_ordering="th",
                 input_shape=None, name=None, **kw):
        super().__init__(input_shape=input_shape, name=name)
        self.cropping = tuple(tuple(c) for c in cropping)
        self.dim_ordering = dim_ordering

    def call(self, params, x, training=False, **kw):
        slices = [slice(None)] * x.ndim
        offset = 2 if self.dim_ordering == "th" else 1
        for i, (a, b) in enumerate(self.cropping):
            dim = offset + i
            slices[dim] = slice(a, x.shape[dim] - b if b else x.shape[dim])
        return x[tuple(slices)]

    def compute_output_shape(self, s):
        s = list(s)
        offset = 2 if self.dim_ordering == "th" else 1
        for i, (a, b) in enumerate(self.cropping):
            if s[offset + i] is not None:
                s[offset + i] -= (a + b)
        return tuple(s)


class UpSampling1D(KerasLayer):
    def __init__(self, length=2, input_shape=None, name=None, **kw):
        super().__init__(input_shape=input_shape, name=name)
        self.length = int(length)

    def call(self, params, x, training=False, **kw):
        return jnp.repeat(x, self.length, axis=1)

    def compute_output_shape(self, s):
        return (s[0], None if s[1] is None else s[1] * self.length, s[2])


class UpSampling2D(KerasLayer):
    def __init__(self, size=(2, 2), dim_ordering="th", input_shape=None,
                 name=None, **kw):
        super().__init__(input_shape=input_shape, name=name)
        self.size = _pair(size)
        self.dim_ordering = dim_ordering

    def call(self, params, x, training=False, **kw):
        h_ax, w_ax = (2, 3) if self.dim_ordering == "th" else (1, 2)
        x = jnp.repeat(x, self.size[0], axis=h_ax)
        return jnp.repeat(x, self.size[1], axis=w_ax)

    def compute_output_shape(self, s):
        def up(d, f):
            return None if d is None else d * f

        if self.dim_ordering == "th":
            return (s[0], s[1], up(s[2], self.size[0]), up(s[3], self.size[1]))
        return (s[0], up(s[1], self.size[0]), up(s[2], self.size[1]), s[3])


class UpSampling3D(KerasLayer):
    def __init__(self, size=(2, 2, 2), dim_ordering="th", input_shape=None,
                 name=None, **kw):
        super().__init__(input_shape=input_shape, name=name)
        self.size = tuple(size)

    def call(self, params, x, training=False, **kw):
        for i, f in enumerate(self.size):
            x = jnp.repeat(x, f, axis=2 + i)
        return x

    def compute_output_shape(self, s):
        s = list(s)
        for i, f in enumerate(self.size):
            if s[2 + i] is not None:
                s[2 + i] *= f
        return tuple(s)


class ZeroPadding1D(KerasLayer):
    def __init__(self, padding=1, input_shape=None, name=None, **kw):
        super().__init__(input_shape=input_shape, name=name)
        self.padding = _pair(padding) if isinstance(padding, (list, tuple)) \
            else (int(padding), int(padding))

    def call(self, params, x, training=False, **kw):
        return jnp.pad(x, ((0, 0), self.padding, (0, 0)))

    def compute_output_shape(self, s):
        return (s[0], None if s[1] is None else s[1] + sum(self.padding),
                s[2])


class ZeroPadding2D(KerasLayer):
    def __init__(self, padding=(1, 1), dim_ordering="th", input_shape=None,
                 name=None, **kw):
        super().__init__(input_shape=input_shape, name=name)
        if len(padding) == 2 and not isinstance(padding[0], (list, tuple)):
            self.padding = ((padding[0], padding[0]),
                            (padding[1], padding[1]))
        else:
            self.padding = tuple(tuple(p) for p in padding)
        self.dim_ordering = dim_ordering

    def call(self, params, x, training=False, **kw):
        if self.dim_ordering == "th":
            return jnp.pad(x, ((0, 0), (0, 0)) + self.padding)
        return jnp.pad(x, ((0, 0),) + self.padding + ((0, 0),))

    def compute_output_shape(self, s):
        (t, b), (l, r) = self.padding

        def pad(d, c):
            return None if d is None else d + c

        if self.dim_ordering == "th":
            return (s[0], s[1], pad(s[2], t + b), pad(s[3], l + r))
        return (s[0], pad(s[1], t + b), pad(s[2], l + r), s[3])


class ZeroPadding3D(KerasLayer):
    def __init__(self, padding=(1, 1, 1), dim_ordering="th", input_shape=None,
                 name=None, **kw):
        super().__init__(input_shape=input_shape, name=name)
        self.padding = tuple(int(p) for p in padding)

    def call(self, params, x, training=False, **kw):
        p = self.padding
        return jnp.pad(x, ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]),
                           (p[2], p[2])))

    def compute_output_shape(self, s):
        s = list(s)
        for i, p in enumerate(self.padding):
            if s[2 + i] is not None:
                s[2 + i] += 2 * p
        return tuple(s)
