"""Pooling layers (Pooling1D/2D/3D + Global variants, keras/layers/*.scala).
All lower to ``lax.reduce_window`` — XLA maps these onto the VPU."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.base import KerasLayer
from .convolutional import _conv_out, _pair


def _pool2d(x, window, strides, padding, mode, dim_ordering):
    if dim_ordering == "th":
        dims = (1, 1) + window
        strd = (1, 1) + strides
    else:
        dims = (1,) + window + (1,)
        strd = (1,) + strides + (1,)
    if mode == "max":
        # int8 activations flow through max-pool on a requantization
        # chain: the identity for integer max is iinfo.min, not -inf
        init = x.dtype.type(jnp.iinfo(x.dtype).min) if jnp.issubdtype(
            x.dtype, jnp.integer) else -jnp.inf
        out = jax.lax.reduce_window(x, init, jax.lax.max, dims, strd, padding)
        return out
    out = jax.lax.reduce_window(x, 0.0, jax.lax.add, dims, strd, padding)
    if padding == "VALID":
        return out / float(np.prod(window))
    ones = jnp.ones_like(x)
    counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, dims, strd,
                                   padding)
    return out / counts


class MaxPooling2D(KerasLayer):
    mode = "max"

    def __init__(self, pool_size=(2, 2), strides=None, border_mode="valid",
                 dim_ordering="th", input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.pool_size = _pair(pool_size)
        self.strides = _pair(strides) if strides is not None else \
            self.pool_size
        self.border_mode = border_mode
        self.dim_ordering = dim_ordering

    def call(self, params, x, training=False, **kw):
        pad = "SAME" if self.border_mode == "same" else "VALID"
        return _pool2d(x, self.pool_size, self.strides, pad, self.mode,
                       self.dim_ordering)

    def compute_output_shape(self, s):
        ph, pw = self.pool_size
        sh, sw = self.strides
        if self.dim_ordering == "th":
            return (s[0], s[1], _conv_out(s[2], ph, sh, self.border_mode),
                    _conv_out(s[3], pw, sw, self.border_mode))
        return (s[0], _conv_out(s[1], ph, sh, self.border_mode),
                _conv_out(s[2], pw, sw, self.border_mode), s[3])


class AveragePooling2D(MaxPooling2D):
    mode = "avg"


class MaxPooling1D(KerasLayer):
    mode = "max"

    def __init__(self, pool_length=2, stride=None, border_mode="valid",
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.pool_length = int(pool_length)
        self.stride = int(stride) if stride is not None else self.pool_length
        self.border_mode = border_mode

    def call(self, params, x, training=False, **kw):
        pad = "SAME" if self.border_mode == "same" else "VALID"
        dims = (1, self.pool_length, 1)
        strd = (1, self.stride, 1)
        if self.mode == "max":
            return jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, dims,
                                         strd, pad)
        out = jax.lax.reduce_window(x, 0.0, jax.lax.add, dims, strd, pad)
        if pad == "VALID":
            return out / float(self.pool_length)
        counts = jax.lax.reduce_window(jnp.ones_like(x), 0.0, jax.lax.add,
                                       dims, strd, pad)
        return out / counts

    def compute_output_shape(self, s):
        return (s[0], _conv_out(s[1], self.pool_length, self.stride,
                                self.border_mode), s[2])


class AveragePooling1D(MaxPooling1D):
    mode = "avg"


class MaxPooling3D(KerasLayer):
    mode = "max"

    def __init__(self, pool_size=(2, 2, 2), strides=None, border_mode="valid",
                 dim_ordering="th", input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.pool_size = tuple(pool_size)
        self.strides = tuple(strides) if strides is not None else \
            self.pool_size
        self.border_mode = border_mode
        self.dim_ordering = dim_ordering

    def call(self, params, x, training=False, **kw):
        pad = "SAME" if self.border_mode == "same" else "VALID"
        if self.dim_ordering == "th":
            dims = (1, 1) + self.pool_size
            strd = (1, 1) + self.strides
        else:
            dims = (1,) + self.pool_size + (1,)
            strd = (1,) + self.strides + (1,)
        if self.mode == "max":
            return jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, dims,
                                         strd, pad)
        out = jax.lax.reduce_window(x, 0.0, jax.lax.add, dims, strd, pad)
        if pad == "VALID":
            return out / float(np.prod(self.pool_size))
        counts = jax.lax.reduce_window(jnp.ones_like(x), 0.0, jax.lax.add,
                                       dims, strd, pad)
        return out / counts

    def compute_output_shape(self, s):
        ps, ss = self.pool_size, self.strides
        off = 2 if self.dim_ordering == "th" else 1
        dims = tuple(_conv_out(s[off + i], ps[i], ss[i], self.border_mode)
                     for i in range(3))
        if self.dim_ordering == "th":
            return (s[0], s[1]) + dims
        return (s[0],) + dims + (s[4],)


class AveragePooling3D(MaxPooling3D):
    mode = "avg"


class GlobalMaxPooling2D(KerasLayer):
    def __init__(self, dim_ordering="th", input_shape=None, name=None, **kw):
        super().__init__(input_shape=input_shape, name=name)
        self.dim_ordering = dim_ordering

    def call(self, params, x, training=False, **kw):
        axes = (2, 3) if self.dim_ordering == "th" else (1, 2)
        return jnp.max(x, axis=axes)

    def compute_output_shape(self, s):
        return (s[0], s[1] if self.dim_ordering == "th" else s[3])


class GlobalAveragePooling2D(GlobalMaxPooling2D):
    def call(self, params, x, training=False, **kw):
        axes = (2, 3) if self.dim_ordering == "th" else (1, 2)
        return jnp.mean(x, axis=axes)


class GlobalMaxPooling1D(KerasLayer):
    def call(self, params, x, training=False, **kw):
        return jnp.max(x, axis=1)

    def compute_output_shape(self, s):
        return (s[0], s[2])


class GlobalAveragePooling1D(GlobalMaxPooling1D):
    def call(self, params, x, training=False, **kw):
        return jnp.mean(x, axis=1)


class GlobalMaxPooling3D(KerasLayer):
    def __init__(self, dim_ordering="th", input_shape=None, name=None, **kw):
        super().__init__(input_shape=input_shape, name=name)
        self.dim_ordering = dim_ordering

    def _axes(self):
        return (2, 3, 4) if self.dim_ordering == "th" else (1, 2, 3)

    def call(self, params, x, training=False, **kw):
        return jnp.max(x, axis=self._axes())

    def compute_output_shape(self, s):
        return (s[0], s[1] if self.dim_ordering == "th" else s[4])


class GlobalAveragePooling3D(GlobalMaxPooling3D):
    def call(self, params, x, training=False, **kw):
        return jnp.mean(x, axis=self._axes())
