"""Normalization layers: BatchNormalization (moving stats as engine state),
LayerNorm, LRN2D, WithinChannelLRN2D.

Parity: BatchNormalization.scala (Keras-1 args: axis default 1 = channel for
'th' ordering), LayerNorm.scala / InternalLayerNorm.scala (used by
Transformer/BERT).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.base import KerasLayer


class BatchNormalization(KerasLayer):
    has_state = True

    def __init__(self, epsilon=1e-3, momentum=0.99, beta_init="zero",
                 gamma_init="one", axis=1, dim_ordering="th",
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.epsilon = epsilon
        self.momentum = momentum
        self.axis = axis
        self.scale_and_center = True

    def _dim(self, input_shape):
        axis = self.axis if self.axis >= 0 else len(input_shape) + self.axis
        d = input_shape[axis]
        return axis, int(d)

    def build(self, rng, input_shape):
        _, d = self._dim(input_shape)
        return {"gamma": jnp.ones((d,)), "beta": jnp.zeros((d,))}

    def init_state(self, input_shape):
        _, d = self._dim(input_shape)
        return {"moving_mean": jnp.zeros((d,)),
                "moving_var": jnp.ones((d,))}

    def call(self, params, x, training=False, state=None, **kw):
        # fused single-pass op (ops/batchnorm.py): the naive mean+var+
        # autodiff form cost ~7 HBM passes over the activation per layer
        # per step — 58 of ResNet-50's 95 ms device step on v5e (r5)
        from .....ops.batchnorm import (batch_norm_inference,
                                        batch_norm_train)
        axis, d = self._dim((None,) + x.shape[1:])
        state = state or self.init_state((None,) + x.shape[1:])
        if training:
            y, mean, var = batch_norm_train(
                x, params["gamma"], params["beta"], axis, self.epsilon)
            m = self.momentum
            new_state = {
                "moving_mean": m * state["moving_mean"] +
                (1 - m) * mean.astype(state["moving_mean"].dtype),
                "moving_var": m * state["moving_var"] +
                (1 - m) * var.astype(state["moving_var"].dtype),
            }
            return y, new_state
        y = batch_norm_inference(x, params["gamma"], params["beta"],
                                 state["moving_mean"],
                                 state["moving_var"], axis, self.epsilon)
        return y, state


class LayerNorm(KerasLayer):
    """Layer normalization over the last dim (LayerNorm.scala /
    InternalLayerNorm.scala — hidden_size, epsilon args)."""

    def __init__(self, hidden_size=None, epsilon=1e-5, input_shape=None,
                 name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.hidden_size = hidden_size
        self.epsilon = epsilon

    def build(self, rng, input_shape):
        d = int(self.hidden_size or input_shape[-1])
        return {"gamma": jnp.ones((d,)), "beta": jnp.zeros((d,))}

    def call(self, params, x, training=False, **kw):
        # fused single-pass op with f32 statistics (ops/layernorm.py)
        from .....ops.layernorm import layer_norm
        return layer_norm(x, params["gamma"], params["beta"],
                          self.epsilon)


class LRN2D(KerasLayer):
    """Local response normalization across channels (LRN2D.scala)."""

    def __init__(self, alpha=1e-4, k=1.0, beta=0.75, n=5, dim_ordering="th",
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.alpha, self.k, self.beta, self.n = alpha, k, beta, int(n)
        self.dim_ordering = dim_ordering

    def call(self, params, x, training=False, **kw):
        c_axis = 1 if self.dim_ordering == "th" else 3
        sq = jnp.square(x)
        half = self.n // 2
        # sum over a sliding window of channels via padding + cumsum
        pads = [(0, 0)] * x.ndim
        pads[c_axis] = (half, half)
        padded = jnp.pad(sq, pads)
        windows = [jax.lax.slice_in_dim(padded, i, i + x.shape[c_axis],
                                        axis=c_axis)
                   for i in range(self.n)]
        norm = self.k + (self.alpha / self.n) * sum(windows)
        return x / jnp.power(norm, self.beta)


class WithinChannelLRN2D(KerasLayer):
    def __init__(self, size=5, alpha=1.0, beta=0.75, input_shape=None,
                 name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.size, self.alpha, self.beta = int(size), alpha, beta

    def call(self, params, x, training=False, **kw):
        # average of squares over a spatial window per channel ('th' layout)
        sq = jnp.square(x)
        window = jnp.ones((self.size, self.size), x.dtype) / (self.size ** 2)
        kernel = window[None, None]
        b, c, h, w = x.shape
        avg = jax.lax.conv_general_dilated(
            sq.reshape(b * c, 1, h, w), kernel, (1, 1), "SAME",
            dimension_numbers=("NCHW", "OIHW", "NCHW")).reshape(b, c, h, w)
        return x / jnp.power(1.0 + self.alpha * avg, self.beta)
