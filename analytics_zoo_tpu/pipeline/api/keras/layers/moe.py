"""Sparse Mixture-of-Experts layer, sharded over the ``expert`` mesh axis.

Rebuild-scope new work (the reference has no MoE / expert parallelism —
SURVEY.md §2.3 lists EP as absent). TPU-first design: the classic
top-k-gating MoE (Shazeer-style) expressed entirely as dense einsums over a
stacked expert dimension so XLA can lay the experts across the ``expert``
mesh axis and insert the dispatch/combine all-to-alls itself — no
host-side routing, no ragged shapes, MXU-shaped matmuls throughout.

Dispatch uses the standard one-hot capacity scheme: each token picks its
top-k experts; a running per-expert cumsum assigns capacity slots; tokens
over capacity are dropped (their combine weight is zero), keeping every
shape static under ``jit``.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.base import KerasLayer, get_activation_fn, init_tensor


class SparseMoE(KerasLayer):
    """Top-k gated mixture of expert MLPs.

    Input ``(B, L, H)`` (or ``(B, H)``); output same shape. Expert weights
    are stacked ``(E, ...)`` and annotated with the ``expert`` logical axis
    so ``parallel.sharding`` lays them across the ``expert`` mesh axis.
    """

    def __init__(self, n_experts: int, intermediate_size: int,
                 top_k: int = 2, capacity_factor: float = 1.25,
                 activation: str = "gelu", router_noise: float = 0.0,
                 input_shape=None, name: Optional[str] = None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        if top_k < 1 or top_k > n_experts:
            raise ValueError(f"top_k {top_k} out of range for "
                             f"{n_experts} experts")
        self.n_experts = n_experts
        self.intermediate_size = intermediate_size
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.activation = get_activation_fn(activation)
        self.router_noise = router_noise

    def build(self, rng, input_shape):
        h = int(input_shape[-1])
        e, f = self.n_experts, self.intermediate_size
        r1, r2, r3 = jax.random.split(rng, 3)
        params = {
            "router_w": init_tensor(r1, (h, e)),
            "w_in": init_tensor(r2, (e, h, f)),
            "b_in": jnp.zeros((e, f)),
            "w_out": init_tensor(r3, (e, f, h)),
            "b_out": jnp.zeros((e, h)),
        }
        self._annotate(**{
            "router_w": ("embed", None),
            "w_in": ("expert", "embed", "mlp"),
            "b_in": ("expert", "mlp"),
            "w_out": ("expert", "mlp", "embed"),
            "b_out": ("expert", "embed"),
        })
        return params

    def compute_output_shape(self, input_shape):
        return tuple(input_shape)

    # ------------------------------------------------------------------
    def _route(self, params, flat, rng, training):
        logits = jnp.matmul(flat, params["router_w"].astype(flat.dtype))
        if training and self.router_noise > 0 and rng is not None:
            logits = logits + self.router_noise * jax.random.normal(
                rng, logits.shape, logits.dtype)
        gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        return gates

    def call(self, params, inputs, training: bool = False, rng=None,
             **kwargs):
        x = inputs[0] if isinstance(inputs, (list, tuple)) else inputs
        orig_shape = x.shape
        h = orig_shape[-1]
        flat = x.reshape(-1, h)                       # (N, H)
        n = flat.shape[0]
        e, k = self.n_experts, self.top_k
        cap = max(1, int(math.ceil(k * n / e * self.capacity_factor)))

        gates = self._route(params, flat, rng, training)     # (N, E) f32
        top_w, top_i = jax.lax.top_k(gates, k)               # (N, k)
        top_w = top_w / jnp.maximum(top_w.sum(-1, keepdims=True), 1e-9)

        # capacity assignment: a running per-expert count across k slots
        dispatch = jnp.zeros((n, e, cap), jnp.float32)
        combine = jnp.zeros((n, e, cap), jnp.float32)
        used = jnp.zeros((e,), jnp.float32)  # slots consumed per expert
        for slot in range(k):                # k is small and static
            onehot = jax.nn.one_hot(top_i[:, slot], e)       # (N, E)
            pos = jnp.cumsum(onehot, axis=0) - 1 + used[None, :]
            pos = pos * onehot
            in_cap = (pos < cap).astype(jnp.float32) * onehot
            sel = jax.nn.one_hot(jnp.clip(pos, 0, cap - 1).astype(jnp.int32),
                                 cap) * in_cap[..., None]    # (N, E, C)
            dispatch = dispatch + sel
            combine = combine + sel * top_w[:, slot][:, None, None]
            used = used + jnp.sum(onehot, axis=0)

        # overflow semantics (pinned by tests/test_parallel_props.py):
        # a (token, slot) assignment past expert capacity contributes
        # ZERO dispatch and ZERO combine weight — the token's output row
        # is zero for that slot, it is DROPPED, never re-routed to a
        # colder expert. Drops must be observable instead of silently
        # flattening the loss: the shortfall vs the n*k issued
        # assignments rides out through a host callback into the
        # telemetry counter. Gated on telemetry.enabled() at TRACE time
        # (a program traced while disabled keeps no callback); under
        # multi-device jit the callback may fire once per device — read
        # the counter as "drops observed", not an exact global count.
        dropped = jnp.asarray(float(n * k)) - jnp.sum(dispatch)
        from .....utils import telemetry
        if telemetry.enabled():
            name = self.name

            def _surface(d):
                telemetry.counter("zoo_moe_dropped_tokens_total",
                                  layer=name).inc(float(d))

            jax.debug.callback(_surface, dropped)

        xin = jnp.einsum("nec,nh->ech", dispatch.astype(x.dtype), flat)
        h1 = jnp.einsum("ech,ehf->ecf", xin,
                        params["w_in"].astype(x.dtype)) + \
            params["b_in"][:, None].astype(x.dtype)
        h1 = self.activation(h1)
        h2 = jnp.einsum("ecf,efh->ech", h1,
                        params["w_out"].astype(x.dtype)) + \
            params["b_out"][:, None].astype(x.dtype)
        out = jnp.einsum("nec,ech->nh", combine.astype(x.dtype), h2)
        return out.reshape(orig_shape)

    # ------------------------------------------------------------------
    def load_balancing_loss(self, params, x):
        """Switch-style aux loss ``E * sum_e f_e * p_e`` (fraction of tokens
        routed to e × mean router prob for e); add to the training loss to
        keep experts utilized."""
        x = x[0] if isinstance(x, (list, tuple)) else x
        flat = x.reshape(-1, x.shape[-1])
        gates = self._route(params, flat, None, False)
        top1 = jnp.argmax(gates, axis=-1)
        frac = jnp.mean(jax.nn.one_hot(top1, self.n_experts), axis=0)
        prob = jnp.mean(gates, axis=0)
        return self.n_experts * jnp.sum(frac * prob)
