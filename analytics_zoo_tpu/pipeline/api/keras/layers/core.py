"""Core layers.

Parity surface: ``zoo/.../pipeline/api/keras/layers/`` — Dense, Dropout,
Activation, Flatten, Reshape, Permute, RepeatVector, Masking, Highway,
MaxoutDense, Select, Narrow, Squeeze, ExpandDim, Identity, and the simple
elementwise layers (Exp, Log, Sqrt, Square, Power, Negative, AddConstant,
MulConstant, CAdd, CMul, Mul, Scale, BinaryThreshold, Threshold, HardTanh,
HardShrink, SoftShrink, ...). All are pure jnp: XLA fuses them into
surrounding matmuls, so depth here is free on TPU.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.base import (KerasLayer, get_activation_fn, init_tensor)


def _dims(shape):
    return tuple(-1 if d is None else int(d) for d in shape)


class Dense(KerasLayer):
    """Fully connected: applies to the last dim (Dense.scala). Kernel is
    annotated ('in','out') so tensor-parallel layouts can shard it."""

    def __init__(self, output_dim, init="glorot_uniform", activation=None,
                 W_regularizer=None, b_regularizer=None, bias=True,
                 input_dim=None, input_shape=None, name=None, **kwargs):
        if input_dim is not None and input_shape is None:
            input_shape = (input_dim,)
        super().__init__(input_shape=input_shape, name=name)
        self.output_dim = int(output_dim)
        self.init = init
        self.activation = get_activation_fn(activation)
        self.bias = bias

    def build(self, rng, input_shape):
        in_dim = int(input_shape[-1])
        k_rng, b_rng = jax.random.split(rng)
        params = {"kernel": init_tensor(k_rng, (in_dim, self.output_dim),
                                        self.init)}
        self._annotate(kernel=("in", "out"))
        if self.bias:
            params["bias"] = jnp.zeros((self.output_dim,))
            self._annotate(bias=("out",))
        return params

    def call(self, params, x, training=False, **kw):
        # quant.matmul owns the whole epilogue: float kernels reproduce
        # matmul + bias + activation verbatim; calibrated int8 kernels
        # fold bias into the int32 accumulator and may emit int8 for
        # the next requantization-chain link
        from .....ops import quant
        return quant.matmul(x, params["kernel"],
                            bias=params["bias"] if self.bias else None,
                            activation=self.activation)

    def compute_output_shape(self, input_shape):
        return tuple(input_shape[:-1]) + (self.output_dim,)


class Activation(KerasLayer):
    def __init__(self, activation, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.fn = get_activation_fn(activation)

    def call(self, params, x, training=False, **kw):
        return self.fn(x)


class Dropout(KerasLayer):
    stochastic = True

    def __init__(self, p, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.p = float(p)

    def call(self, params, x, training=False, rng=None, **kw):
        if not training or rng is None or self.p <= 0.0:
            return x
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


class SpatialDropout1D(KerasLayer):
    stochastic = True

    def __init__(self, p=0.5, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.p = float(p)

    def call(self, params, x, training=False, rng=None, **kw):
        if not training or rng is None or self.p <= 0.0:
            return x
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(rng, keep, (x.shape[0], 1, x.shape[2]))
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


class SpatialDropout2D(KerasLayer):
    stochastic = True

    def __init__(self, p=0.5, dim_ordering="th", input_shape=None, name=None,
                 **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.p = float(p)
        self.dim_ordering = dim_ordering

    def call(self, params, x, training=False, rng=None, **kw):
        if not training or rng is None or self.p <= 0.0:
            return x
        keep = 1.0 - self.p
        if self.dim_ordering == "th":  # (B, C, H, W): drop whole channels
            shape = (x.shape[0], x.shape[1], 1, 1)
        else:  # (B, H, W, C)
            shape = (x.shape[0], 1, 1, x.shape[3])
        mask = jax.random.bernoulli(rng, keep, shape)
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


class SpatialDropout3D(KerasLayer):
    stochastic = True

    def __init__(self, p=0.5, dim_ordering="th", input_shape=None, name=None,
                 **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.p = float(p)
        self.dim_ordering = dim_ordering

    def call(self, params, x, training=False, rng=None, **kw):
        if not training or rng is None or self.p <= 0.0:
            return x
        keep = 1.0 - self.p
        if self.dim_ordering == "th":
            shape = (x.shape[0], x.shape[1], 1, 1, 1)
        else:
            shape = (x.shape[0], 1, 1, 1, x.shape[4])
        mask = jax.random.bernoulli(rng, keep, shape)
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


class Flatten(KerasLayer):
    def call(self, params, x, training=False, **kw):
        return x.reshape(x.shape[0], -1) if x.ndim > 1 else \
            x.reshape(x.shape[0], 1)

    def compute_output_shape(self, input_shape):
        rest = [d for d in input_shape[1:]]
        return (input_shape[0], int(np.prod(rest)) if rest else 1)


class Reshape(KerasLayer):
    def __init__(self, target_shape, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.target_shape = tuple(int(d) for d in target_shape)

    def call(self, params, x, training=False, **kw):
        return x.reshape((x.shape[0],) + self.target_shape)

    def compute_output_shape(self, input_shape):
        target = self.target_shape
        if -1 in target:
            if target.count(-1) > 1:
                raise ValueError(f"Reshape{target}: at most one -1 allowed")
            known = 1
            for d in input_shape[1:]:
                known *= int(d)
            fixed = 1
            for d in target:
                if d != -1:
                    fixed *= d
            if known % fixed != 0:
                raise ValueError(
                    f"cannot Reshape {tuple(input_shape[1:])} "
                    f"({known} elements) into {target}")
            target = tuple(known // fixed if d == -1 else d for d in target)
        return (input_shape[0],) + target


class Permute(KerasLayer):
    """Permute non-batch dims; dims are 1-based like Keras (Permute.scala)."""

    def __init__(self, dims, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.dims = tuple(int(d) for d in dims)

    def call(self, params, x, training=False, **kw):
        return jnp.transpose(x, (0,) + self.dims)

    def compute_output_shape(self, input_shape):
        return (input_shape[0],) + tuple(input_shape[d] for d in self.dims)


class RepeatVector(KerasLayer):
    def __init__(self, n, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.n = int(n)

    def call(self, params, x, training=False, **kw):
        return jnp.repeat(x[:, None, :], self.n, axis=1)

    def compute_output_shape(self, input_shape):
        return (input_shape[0], self.n, input_shape[1])


class Masking(KerasLayer):
    def __init__(self, mask_value=0.0, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.mask_value = mask_value

    def call(self, params, x, training=False, **kw):
        keep = jnp.any(x != self.mask_value, axis=-1, keepdims=True)
        return jnp.where(keep, x, 0.0).astype(x.dtype)


class Highway(KerasLayer):
    """Highway network layer (Highway.scala)."""

    def __init__(self, activation="tanh", W_regularizer=None,
                 b_regularizer=None, bias=True, input_shape=None, name=None,
                 **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.activation = get_activation_fn(activation)
        self.bias = bias

    def build(self, rng, input_shape):
        d = int(input_shape[-1])
        r1, r2 = jax.random.split(rng)
        params = {"kernel": init_tensor(r1, (d, d)),
                  "gate_kernel": init_tensor(r2, (d, d))}
        if self.bias:
            params["bias"] = jnp.zeros((d,))
            params["gate_bias"] = jnp.full((d,), -2.0)
        return params

    def call(self, params, x, training=False, **kw):
        h = jnp.matmul(x, params["kernel"])
        g = jnp.matmul(x, params["gate_kernel"])
        if self.bias:
            h = h + params["bias"]
            g = g + params["gate_bias"]
        h = self.activation(h) if self.activation else h
        t = jax.nn.sigmoid(g)
        return t * h + (1.0 - t) * x


class MaxoutDense(KerasLayer):
    def __init__(self, output_dim, nb_feature=4, bias=True, input_shape=None,
                 name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.output_dim = int(output_dim)
        self.nb_feature = int(nb_feature)
        self.bias = bias

    def build(self, rng, input_shape):
        d = int(input_shape[-1])
        params = {"kernel": init_tensor(
            rng, (self.nb_feature, d, self.output_dim))}
        if self.bias:
            params["bias"] = jnp.zeros((self.nb_feature, self.output_dim))
        return params

    def call(self, params, x, training=False, **kw):
        y = jnp.einsum("bd,kdo->bko", x, params["kernel"])
        if self.bias:
            y = y + params["bias"]
        return jnp.max(y, axis=1)

    def compute_output_shape(self, input_shape):
        return (input_shape[0], self.output_dim)


class Select(KerasLayer):
    """Select one index along a dim, removing it (Select.scala). ``dim``
    counts the batch dim as 0; negative indexes from the end."""

    def __init__(self, dim, index, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.dim = int(dim)
        self.index = int(index)

    def call(self, params, x, training=False, **kw):
        idx = self.index if self.index >= 0 else x.shape[self.dim] + self.index
        return jax.lax.index_in_dim(x, idx, self.dim, keepdims=False)

    def compute_output_shape(self, input_shape):
        dim = self.dim if self.dim >= 0 else len(input_shape) + self.dim
        return tuple(d for i, d in enumerate(input_shape) if i != dim)


class Narrow(KerasLayer):
    """Slice `length` elements starting at `offset` along `dim`
    (Narrow.scala)."""

    def __init__(self, dim, offset, length=1, input_shape=None, name=None,
                 **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.dim, self.offset, self.length = int(dim), int(offset), int(length)

    def call(self, params, x, training=False, **kw):
        length = self.length if self.length > 0 else \
            x.shape[self.dim] - self.offset
        return jax.lax.slice_in_dim(x, self.offset, self.offset + length,
                                    axis=self.dim)

    def compute_output_shape(self, input_shape):
        dim = self.dim if self.dim >= 0 else len(input_shape) + self.dim
        length = self.length if self.length > 0 else \
            input_shape[dim] - self.offset
        return tuple(length if i == dim else d
                     for i, d in enumerate(input_shape))


class Squeeze(KerasLayer):
    def __init__(self, dim, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.dim = dim

    def call(self, params, x, training=False, **kw):
        return jnp.squeeze(x, axis=self.dim)

    def compute_output_shape(self, input_shape):
        dims = self.dim if isinstance(self.dim, (list, tuple)) else [self.dim]
        dims = [d if d >= 0 else len(input_shape) + d for d in dims]
        return tuple(d for i, d in enumerate(input_shape) if i not in dims)


class ExpandDim(KerasLayer):
    def __init__(self, dim, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.dim = int(dim)

    def call(self, params, x, training=False, **kw):
        return jnp.expand_dims(x, self.dim)

    def compute_output_shape(self, input_shape):
        shape = list(input_shape)
        dim = self.dim if self.dim >= 0 else len(shape) + self.dim + 1
        shape.insert(dim, 1)
        return tuple(shape)


class Identity(KerasLayer):
    def call(self, params, x, training=False, **kw):
        return x


class Max(KerasLayer):
    """Max along a dim (Max.scala), optionally returning indices."""

    def __init__(self, dim, num_input_dims=-1, return_value=True,
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.dim = int(dim)
        self.return_value = return_value

    def call(self, params, x, training=False, **kw):
        if self.return_value:
            return jnp.max(x, axis=self.dim)
        return jnp.argmax(x, axis=self.dim)

    def compute_output_shape(self, input_shape):
        dim = self.dim if self.dim >= 0 else len(input_shape) + self.dim
        return tuple(d for i, d in enumerate(input_shape) if i != dim)


class SplitTensor(KerasLayer):
    """Split along a dim into equal chunks (SplitTensor.scala)."""

    def __init__(self, dim, num_splits, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.dim = int(dim)
        self.num_splits = int(num_splits)
        self.num_outputs = self.num_splits

    def call(self, params, x, training=False, **kw):
        return tuple(jnp.split(x, self.num_splits, axis=self.dim))

    def compute_output_shape(self, input_shape):
        dim = self.dim if self.dim >= 0 else len(input_shape) + self.dim
        chunk = input_shape[dim] // self.num_splits if input_shape[dim] else \
            None
        one = tuple(chunk if i == dim else d
                    for i, d in enumerate(input_shape))
        return [one] * self.num_splits


# ---------------------------------------------------------------------------
# Simple elementwise layers
# ---------------------------------------------------------------------------

class _Elementwise(KerasLayer):
    fn = staticmethod(lambda x: x)

    def call(self, params, x, training=False, **kw):
        return type(self).fn(x)


class Exp(_Elementwise):
    fn = staticmethod(jnp.exp)


class Log(_Elementwise):
    fn = staticmethod(jnp.log)


class Sqrt(_Elementwise):
    fn = staticmethod(jnp.sqrt)


class Square(_Elementwise):
    fn = staticmethod(jnp.square)


class Negative(_Elementwise):
    fn = staticmethod(jnp.negative)


class Power(KerasLayer):
    def __init__(self, power, scale=1.0, shift=0.0, input_shape=None,
                 name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.power, self.scale, self.shift = power, scale, shift

    def call(self, params, x, training=False, **kw):
        return jnp.power(self.scale * x + self.shift, self.power)


class AddConstant(KerasLayer):
    def __init__(self, constant, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.constant = constant

    def call(self, params, x, training=False, **kw):
        return x + self.constant


class MulConstant(KerasLayer):
    def __init__(self, constant, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.constant = constant

    def call(self, params, x, training=False, **kw):
        return x * self.constant


class CAdd(KerasLayer):
    """Learnable per-element bias with broadcastable shape (CAdd.scala)."""

    def __init__(self, size, b_regularizer=None, input_shape=None, name=None,
                 **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.size = tuple(int(s) for s in size)

    def build(self, rng, input_shape):
        return {"bias": jnp.zeros(self.size)}

    def call(self, params, x, training=False, **kw):
        return x + params["bias"]


class CMul(KerasLayer):
    def __init__(self, size, W_regularizer=None, input_shape=None, name=None,
                 **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.size = tuple(int(s) for s in size)

    def build(self, rng, input_shape):
        return {"weight": jnp.ones(self.size)}

    def call(self, params, x, training=False, **kw):
        return x * params["weight"]


class Mul(KerasLayer):
    """Single learnable scalar multiplier (Mul.scala)."""

    def build(self, rng, input_shape):
        return {"weight": jnp.ones(())}

    def call(self, params, x, training=False, **kw):
        return x * params["weight"]


class Scale(KerasLayer):
    """y = weight * x + bias, both of shape `size` (Scale.scala)."""

    def __init__(self, size, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.size = tuple(int(s) for s in size)

    def build(self, rng, input_shape):
        return {"weight": jnp.ones(self.size), "bias": jnp.zeros(self.size)}

    def call(self, params, x, training=False, **kw):
        return x * params["weight"] + params["bias"]


class BinaryThreshold(KerasLayer):
    def __init__(self, value=1e-6, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.value = value

    def call(self, params, x, training=False, **kw):
        return (x > self.value).astype(x.dtype)


class Threshold(KerasLayer):
    def __init__(self, th=1e-6, v=0.0, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.th, self.v = th, v

    def call(self, params, x, training=False, **kw):
        return jnp.where(x > self.th, x, self.v).astype(x.dtype)


class HardTanh(KerasLayer):
    def __init__(self, min_value=-1.0, max_value=1.0, input_shape=None,
                 name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.min_value, self.max_value = min_value, max_value

    def call(self, params, x, training=False, **kw):
        return jnp.clip(x, self.min_value, self.max_value)


class HardShrink(KerasLayer):
    def __init__(self, value=0.5, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.value = value

    def call(self, params, x, training=False, **kw):
        return jnp.where(jnp.abs(x) > self.value, x, 0.0).astype(x.dtype)


class SoftShrink(KerasLayer):
    def __init__(self, value=0.5, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.value = value

    def call(self, params, x, training=False, **kw):
        return jnp.sign(x) * jnp.maximum(jnp.abs(x) - self.value, 0.0)


class GaussianNoise(KerasLayer):
    stochastic = True

    def __init__(self, sigma, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.sigma = sigma

    def call(self, params, x, training=False, rng=None, **kw):
        if not training or rng is None:
            return x
        return x + self.sigma * jax.random.normal(rng, x.shape, x.dtype)


class GaussianDropout(KerasLayer):
    stochastic = True

    def __init__(self, p, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.p = p

    def call(self, params, x, training=False, rng=None, **kw):
        if not training or rng is None:
            return x
        stddev = np.sqrt(self.p / (1.0 - self.p))
        return x * (1.0 + stddev * jax.random.normal(rng, x.shape, x.dtype))


class GaussianSampler(KerasLayer):
    """VAE reparameterization: input [mean, log_var] (GaussianSampler.scala)."""

    stochastic = True

    def call(self, params, x, training=False, rng=None, **kw):
        mean, log_var = x
        if rng is None:
            return mean
        eps = jax.random.normal(rng, mean.shape, mean.dtype)
        return mean + jnp.exp(0.5 * log_var) * eps

    def compute_output_shape(self, input_shape):
        return input_shape[0]


class ResizeBilinear(KerasLayer):
    def __init__(self, output_height, output_width, align_corners=False,
                 dim_ordering="th", input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.oh, self.ow = int(output_height), int(output_width)
        self.dim_ordering = dim_ordering

    def call(self, params, x, training=False, **kw):
        if self.dim_ordering == "th":
            shape = (x.shape[0], x.shape[1], self.oh, self.ow)
        else:
            shape = (x.shape[0], self.oh, self.ow, x.shape[3])
        return jax.image.resize(x, shape, method="bilinear")

    def compute_output_shape(self, s):
        if self.dim_ordering == "th":
            return (s[0], s[1], self.oh, self.ow)
        return (s[0], self.oh, self.ow, s[3])


class SparseDense(KerasLayer):
    """Dense over (conceptually) sparse inputs (SparseDense.scala). Two
    behavioral differences from ``Dense``: (1) by default NO gradient flows
    back to the input — the reference skips it because a dense gradInput
    over a huge sparse feature vector is useless; (2) ``backward_start`` /
    ``backward_length`` (1-based start, per the Scala surface) open a
    window of the last input dim that DOES receive gradient, which is what
    Wide&Deep uses to train the dense half of a mixed input.

    TPU-first note: there is no SparseTensor on the MXU — a sparse row
    batch lowers to the same dense matmul, and XLA's scatter-add already
    gives the weight gradient sparse-update behavior, so the input is a
    plain dense array and sparsity is purely a gradient-routing contract.
    """

    def __init__(self, output_dim, init="glorot_uniform", activation=None,
                 W_regularizer=None, b_regularizer=None, backward_start=-1,
                 backward_length=-1, bias=True, input_shape=None, name=None,
                 **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.output_dim = int(output_dim)
        self.init = init
        self.activation = get_activation_fn(activation)
        self.bias = bias
        self.backward_start = int(backward_start)
        self.backward_length = int(backward_length)

    def build(self, rng, input_shape):
        if len(input_shape) < 2:
            raise ValueError("SparseDense requires input dim >= 2, got %r"
                             % (input_shape,))
        in_dim = int(input_shape[-1])
        k_rng, _ = jax.random.split(rng)
        params = {"kernel": init_tensor(k_rng, (in_dim, self.output_dim),
                                        self.init)}
        self._annotate(kernel=("in", "out"))
        if self.bias:
            params["bias"] = jnp.zeros((self.output_dim,))
            self._annotate(bias=("out",))
        return params

    def call(self, params, x, training=False, **kw):
        if self.backward_start > 0 and self.backward_length > 0:
            start = self.backward_start - 1
            mask = jnp.zeros((x.shape[-1],), x.dtype).at[
                start:start + self.backward_length].set(1.0)
            x = jax.lax.stop_gradient(x) * (1.0 - mask) + x * mask
        else:
            x = jax.lax.stop_gradient(x)
        from .....ops import quant
        return quant.matmul(x, params["kernel"],
                            bias=params["bias"] if self.bias else None,
                            activation=self.activation)

    def compute_output_shape(self, input_shape):
        return tuple(input_shape[:-1]) + (self.output_dim,)


class SelectTable(KerasLayer):
    """Pick element ``index`` (0-based, per the zoo python surface) from a
    table of inputs (SelectTable.scala; BigDL ``nn.SelectTable`` is 1-based
    — the zoo wrapper adds 1). Gradient flows only to the selected input."""

    def __init__(self, index, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.index = int(index)

    def call(self, params, xs, training=False, **kw):
        if not isinstance(xs, (list, tuple)):
            raise ValueError("SelectTable expects a table (list) input")
        return xs[self.index]

    def compute_output_shape(self, input_shape):
        if input_shape and isinstance(input_shape[0], (list, tuple)):
            return tuple(input_shape[self.index])
        return tuple(input_shape)


class Expand(KerasLayer):
    """Broadcast singleton dims to ``tgt_sizes`` (Expand.scala /
    InternalExpand.scala). ``tgt_sizes`` covers EVERY dim including batch;
    -1 keeps a dim; only size-1 dims may grow. Backward is the usual
    broadcast transpose (sum over expanded dims), which jax derives."""

    def __init__(self, tgt_sizes, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name)
        self.tgt_sizes = tuple(int(t) for t in tgt_sizes)

    def _target(self, shape):
        if len(self.tgt_sizes) != len(shape):
            raise ValueError(
                "tgt_sizes must cover every dim: got %d for rank %d"
                % (len(self.tgt_sizes), len(shape)))
        out = []
        for have, want in zip(shape, self.tgt_sizes):
            if want == -1:
                out.append(have)
            elif have is None:
                # unknown (batch) dim with an explicit target: the output
                # size is statically the target either way
                out.append(want)
            elif have not in (1, want):
                raise ValueError(
                    "only singleton expansion supported: %r -> %r"
                    % (tuple(shape), self.tgt_sizes))
            else:
                out.append(want)
        return tuple(out)

    def call(self, params, x, training=False, **kw):
        return jnp.broadcast_to(x, self._target(x.shape))

    def compute_output_shape(self, input_shape):
        return self._target(tuple(input_shape))


class GetShape(KerasLayer):
    """Return the (static) shape of the input, batch dim included, as a
    1-D tensor (GetShape.scala). The output carries no dependence on the
    input values, so the gradient to the input is zero — same contract as
    the reference's InternalGetShape.updateGradInput."""

    def call(self, params, x, training=False, **kw):
        return jnp.asarray(x.shape, jnp.float32)

    def compute_output_shape(self, input_shape):
        return (len(input_shape),)
