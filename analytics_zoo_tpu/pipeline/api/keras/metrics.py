"""Validation metrics.

Parity surface: ``zoo/.../pipeline/api/keras/metrics/`` (Accuracy, Top5Accuracy,
AUC, MAE, MSE) + KerasUtils.toBigDLMetrics:229. Metrics are streaming: the
jitted eval step emits per-batch ``(numerator, denominator)`` partial sums
(device-side, psum-friendly) and the host accumulates across batches — no
per-sample host round trips.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class Metric:
    name = "metric"

    def batch_stats(self, y_pred, y_true, sample_weight=None):
        """Return (numerator, denominator) partial sums for one batch.

        Contract: the returned arrays must be SHAPE-STABLE across batches
        of the same batch size — the fused eval path carries the
        accumulator through a ``lax.scan`` over stacked batches, so a
        metric whose partial-sum shape depended on batch content would
        fail to trace."""
        raise NotImplementedError

    def finalize(self, num, den):
        """Reduce accumulated partials to the final value; ``num``/``den``
        arrive as host arrays summed over every batch (np.maximum keeps
        this array-safe for vector-valued partials)."""
        import numpy as np
        return float(np.asarray(num / np.maximum(den, 1e-12)))

    def __repr__(self):
        return self.name


def _weights(y_pred, sample_weight):
    if sample_weight is None:
        return jnp.ones((y_pred.shape[0],), jnp.float32)
    return sample_weight.astype(jnp.float32)


def _labels_of(y_true, y_pred, zero_based_label=True):
    if y_true.ndim == y_pred.ndim and y_true.shape[-1] == y_pred.shape[-1] \
            and y_pred.shape[-1] > 1:
        return jnp.argmax(y_true, axis=-1)  # one-hot targets
    labels = y_true.astype(jnp.int32)
    if labels.ndim == y_pred.ndim:
        labels = labels.reshape(labels.shape[:-1])
    if not zero_based_label:
        labels = labels - 1
    return labels


class Accuracy(Metric):
    """Top-1 accuracy; handles binary (sigmoid scalar output) and
    categorical predictions like the reference's Accuracy metric."""

    name = "accuracy"

    def __init__(self, zero_based_label=True):
        self.zero_based_label = zero_based_label

    def batch_stats(self, y_pred, y_true, sample_weight=None):
        w = _weights(y_pred, sample_weight)
        if y_pred.ndim == 1 or y_pred.shape[-1] == 1:
            pred = (y_pred.reshape(y_pred.shape[0]) > 0.5).astype(jnp.int32)
            labels = y_true.reshape(y_true.shape[0]).astype(jnp.int32)
        else:
            pred = jnp.argmax(y_pred, axis=-1)
            labels = _labels_of(y_true, y_pred, self.zero_based_label)
            if pred.ndim > 1:  # sequence outputs: per-token accuracy
                w = jnp.broadcast_to(w.reshape((-1,) + (1,) * (pred.ndim - 1)),
                                     pred.shape)
        correct = (pred == labels).astype(jnp.float32)
        return jnp.sum(correct * w), jnp.sum(w * jnp.ones_like(correct))


class SparseCategoricalAccuracy(Accuracy):
    name = "sparse_categorical_accuracy"


class BinaryAccuracy(Metric):
    name = "binary_accuracy"

    def batch_stats(self, y_pred, y_true, sample_weight=None):
        w = _weights(y_pred, sample_weight)
        pred = (y_pred.reshape(y_pred.shape[0], -1) > 0.5).astype(jnp.float32)
        labels = y_true.reshape(y_true.shape[0], -1).astype(jnp.float32)
        correct = (pred == labels).all(axis=-1).astype(jnp.float32)
        return jnp.sum(correct * w), jnp.sum(w)


class CategoricalAccuracy(Metric):
    name = "categorical_accuracy"

    def batch_stats(self, y_pred, y_true, sample_weight=None):
        w = _weights(y_pred, sample_weight)
        pred = jnp.argmax(y_pred, axis=-1)
        labels = jnp.argmax(y_true, axis=-1)
        correct = (pred == labels).astype(jnp.float32)
        while correct.ndim > 1:
            correct = correct.mean(axis=-1)
        return jnp.sum(correct * w), jnp.sum(w)


class Top5Accuracy(Metric):
    name = "top5accuracy"

    def __init__(self, zero_based_label=True):
        self.zero_based_label = zero_based_label

    def batch_stats(self, y_pred, y_true, sample_weight=None):
        w = _weights(y_pred, sample_weight)
        labels = _labels_of(y_true, y_pred, self.zero_based_label)
        k = min(5, y_pred.shape[-1])
        _, topk = jax.lax.top_k(y_pred, k)
        correct = (topk == labels[..., None]).any(axis=-1).astype(jnp.float32)
        while correct.ndim > 1:
            correct = correct.mean(axis=-1)
        return jnp.sum(correct * w), jnp.sum(w)


class MAE(Metric):
    name = "mae"

    def batch_stats(self, y_pred, y_true, sample_weight=None):
        w = _weights(y_pred, sample_weight)
        err = jnp.abs(y_pred - y_true).reshape(y_pred.shape[0], -1).mean(-1)
        return jnp.sum(err * w), jnp.sum(w)


class MSE(Metric):
    name = "mse"

    def batch_stats(self, y_pred, y_true, sample_weight=None):
        w = _weights(y_pred, sample_weight)
        err = jnp.square(y_pred - y_true).reshape(y_pred.shape[0], -1).mean(-1)
        return jnp.sum(err * w), jnp.sum(w)


class AUC(Metric):
    """Streaming AUC via fixed thresholds (reference: metrics wrapping BigDL
    AUC with thresholdNum). num/den here are TPR/FPR histogram counts."""

    name = "auc"

    def __init__(self, threshold_num: int = 200):
        self.threshold_num = threshold_num

    def batch_stats(self, y_pred, y_true, sample_weight=None):
        w = _weights(y_pred, sample_weight)
        scores = y_pred.reshape(y_pred.shape[0], -1)[:, -1]
        labels = y_true.reshape(y_true.shape[0], -1)[:, -1]
        if y_pred.ndim > 1 and y_pred.shape[-1] == 2:
            scores = y_pred[:, 1]
        thresholds = jnp.linspace(0.0, 1.0, self.threshold_num)
        pred_pos = scores[None, :] >= thresholds[:, None]  # (T, B)
        pos = (labels > 0.5).astype(jnp.float32) * w
        neg = (labels <= 0.5).astype(jnp.float32) * w
        tp = jnp.sum(pred_pos * pos[None, :], axis=1)
        fp = jnp.sum(pred_pos * neg[None, :], axis=1)
        return jnp.stack([tp, fp]), jnp.stack(
            [jnp.sum(pos) * jnp.ones(()), jnp.sum(neg) * jnp.ones(())])

    def finalize(self, num, den):
        tp, fp = num[0], num[1]
        p, n = float(den[0]), float(den[1])
        tpr = tp / max(p, 1e-12)
        fpr = fp / max(n, 1e-12)
        # thresholds descend fpr; integrate via trapezoid on sorted fpr
        import numpy as np
        fpr = np.asarray(fpr)[::-1]
        tpr = np.asarray(tpr)[::-1]
        trapezoid = getattr(np, "trapezoid", None) or np.trapz
        return float(trapezoid(tpr, fpr))


class Loss(Metric):
    """Reports the loss function as a validation metric (reference: BigDL
    ``Loss`` validation method)."""

    name = "loss"

    def __init__(self, loss_fn=None):
        from .objectives import get_loss
        self.loss_fn = get_loss(loss_fn) if loss_fn is not None else None

    def batch_stats(self, y_pred, y_true, sample_weight=None):
        w = _weights(y_pred, sample_weight)
        losses = self.loss_fn.per_sample(y_pred, y_true)
        return jnp.sum(losses * w), jnp.sum(w)


_METRICS = {
    "accuracy": Accuracy,
    "acc": Accuracy,
    "sparse_categorical_accuracy": SparseCategoricalAccuracy,
    "categorical_accuracy": CategoricalAccuracy,
    "binary_accuracy": BinaryAccuracy,
    "top5accuracy": Top5Accuracy,
    "top5acc": Top5Accuracy,
    "mae": MAE,
    "mse": MSE,
    "auc": AUC,
    "loss": Loss,
}


def get_metric(identifier, loss_fn=None):
    if isinstance(identifier, Metric):
        return identifier
    name = identifier.lower()
    if name == "loss":
        return Loss(loss_fn)
    try:
        return _METRICS[name]()
    except KeyError:
        raise ValueError(f"Unknown metric: {identifier}")
